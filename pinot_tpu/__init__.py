"""pinot_tpu — a TPU-native realtime distributed OLAP framework.

A from-scratch re-design of the capabilities of Apache Pinot (reference:
/root/reference, 0.11.0-SNAPSHOT) for TPU hardware:

- Columnar segments live as padded, dict-encoded device arrays in HBM
  (replacing mmap'd ``PinotDataBuffer`` byte buffers,
  pinot-segment-spi/.../memory/PinotDataBuffer.java).
- The per-segment operator chain (filter -> doc-id-set -> projection ->
  transform -> aggregate, pinot-core/.../operator/) is replaced by fused,
  jitted mask-based kernel pipelines specialized per query shape.
- The per-server multi-segment combine (BaseCombineOperator thread fan-out +
  BlockingQueue merge) is replaced by batched kernel launches over a stacked
  segment axis and ``psum``/``all_gather`` collectives over a
  ``jax.sharding.Mesh``.
- Broker / controller / ingestion control planes stay host-side Python/C++.

int64 support is required for exact integral aggregation (SUM over 100M+
int32 rows overflows 32 bits); TPUs execute int64 as lowered int32 pairs.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
