"""Partial-upsert mergers: combine an incoming row with the previous
version of its primary key.

Equivalent of the reference's ``upsert/merger/`` package
(pinot-segment-local/.../upsert/merger/PartialUpsertHandler.java and the
per-strategy mergers OverwriteMerger/IgnoreMerger/IncrementMerger/
AppendMerger/UnionMerger/MaxMerger/MinMerger): each non-key column gets a
merge strategy; unlisted columns default to OVERWRITE. Primary-key columns
and the comparison column are never merged — the reference excludes them
the same way.

The merged row is what gets indexed, so sealed segments durably hold merged
values and restart replay (manager._reconcile_committed) reconstructs the
same state with no special casing.
"""

from __future__ import annotations

import numpy as np


def _as_list(v) -> list:
    if isinstance(v, (list, tuple, np.ndarray)):
        return list(v)
    return [v]


def _overwrite(prev, new):
    return new


def _ignore(prev, new):
    return prev


def _increment(prev, new):
    return prev + new


def _append(prev, new):
    return _as_list(prev) + _as_list(new)


def _union(prev, new):
    out = _as_list(prev)
    seen = set(out)
    for v in _as_list(new):
        if v not in seen:
            seen.add(v)
            out.append(v)
    return out


def _max(prev, new):
    return max(prev, new)


def _min(prev, new):
    return min(prev, new)


STRATEGIES = {
    "OVERWRITE": _overwrite,
    "IGNORE": _ignore,
    "INCREMENT": _increment,
    "APPEND": _append,
    "UNION": _union,
    "MAX": _max,
    "MIN": _min,
}


class PartialUpsertMerger:
    """Merges the previous version of a row into the incoming one."""

    def __init__(self, schema, upsert_config):
        strategies = dict(upsert_config.partial_upsert_strategies)
        unknown = set(strategies.values()) - set(STRATEGIES)
        if unknown:
            raise ValueError(f"unknown partial-upsert strategies: {sorted(unknown)}")
        protected = set(schema.primary_key_columns)
        if upsert_config.comparison_column:
            protected.add(upsert_config.comparison_column)
        bad = protected & set(strategies)
        if bad:
            raise ValueError(
                f"partial-upsert strategies not allowed on key/comparison "
                f"columns: {sorted(bad)}")
        self._mergers = {
            col: STRATEGIES[strategies.get(col, "OVERWRITE")]
            for col in schema.column_names()
            if col not in protected
        }

    def merge(self, prev_row: dict, new_row: dict) -> dict:
        out = dict(new_row)
        for col, fn in self._mergers.items():
            prev_val = prev_row.get(col)
            new_val = new_row.get(col)
            if new_val is None:
                # absent or explicit null: previous value carries over
                # (the reference's mergers keep the previous value when the
                # incoming one is null)
                out[col] = prev_val
            elif prev_val is None:
                # previous value was null: take the incoming value unmerged
                out[col] = new_val
            else:
                out[col] = fn(prev_val, new_val)
        return out


def read_row(segment, doc_id: int, columns) -> dict:
    """Previous-version read: one row's values out of the segment currently
    holding the key (mutable in the common case). Null columns come back as
    None so merge() can distinguish them from default-fill values."""
    out = {}
    for col in columns:
        out[col] = segment.row_value(col, doc_id)
    return out
