"""Realtime ingestion managers: consume → index → seal → commit.

Equivalent of the reference's realtime data-manager layer
(pinot-core/.../data/manager/realtime/LLRealtimeSegmentDataManager.java —
per-partition consume loop with the CONSUMING→HOLDING→COMMITTING state
machine — and RealtimeTableDataManager), single-process edition: the
controller-side commit FSM (SegmentCompletionManager committer election)
collapses to a local checkpoint store; the multi-replica protocol arrives
with the cluster layer.

Crash/restart contract (SURVEY.md §5 checkpoint/resume): sealed segments are
the checkpoints; the CheckpointStore records (segment, end offset, sequence)
per partition, and a restarted manager re-consumes from the last committed
offset — exactly the reference's ZK segment-metadata semantics.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.ingestion.transform import TransformError
from pinot_tpu.realtime import merger
from pinot_tpu.realtime.upsert import PartitionUpsertMetadataManager
from pinot_tpu.storage.mutable import MutableSegment
from pinot_tpu.stream.spi import (
    StreamPartitionMsgOffset,
    create_consumer_factory,
    get_decoder,
)

log = logging.getLogger("pinot_tpu.realtime")


class CheckpointStore:
    """Durable per-partition commit log (segment ZK metadata analog)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._state = {}
        if os.path.exists(path):
            with open(path) as f:
                self._state = json.load(f)

    def _key(self, table: str, partition: int) -> str:
        return f"{table}/{partition}"

    def committed(self, table: str, partition: int) -> Optional[dict]:
        return self._state.get(self._key(table, partition))

    def committed_name(self, table: str, partition: int, sequence: int):
        """Name of the committed segment at ``sequence``, or None if unknown
        (legacy checkpoint written before names were logged)."""
        entry = self._state.get(self._key(table, partition))
        if entry is None:
            return None
        return entry.get("names", {}).get(str(sequence))

    def record_commit(self, table: str, partition: int, segment_name: str,
                      end_offset: str, sequence: int) -> None:
        with self._lock:
            prior = self._state.get(self._key(table, partition), {})
            # full seq→name log (the ZK segment-metadata list analog): restart
            # reconciliation uses it to tell committed dirs from crash orphans
            # at ANY sequence, not just the latest
            names = dict(prior.get("names", {}))
            names[str(sequence)] = segment_name
            self._state[self._key(table, partition)] = {
                "segment": segment_name,
                "offset": end_offset,
                "sequence": sequence,
                "names": names,
            }
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._state, f)
            os.replace(tmp, self.path)


def llc_segment_name(table: str, partition: int, sequence: int,
                     start_offset: str = None) -> str:
    """LLCSegmentName analog: table__partition__sequence__suffix. The suffix
    is the START OFFSET (deterministic), not a creation timestamp: replicas
    consuming the same partition resume from the same committed offset, so
    they agree on the name of the segment they're racing to commit — the
    property the reference gets from the controller assigning the name in
    ZK. Falls back to a timestamp when no offset is known."""
    suffix = start_offset if start_offset is not None \
        else time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    return f"{table}__{partition}__{sequence}__{suffix}"


class RealtimePartitionManager:
    """One partition's consume loop (LLRealtimeSegmentDataManager analog)."""

    CONSUMING = "CONSUMING"
    COMMITTING = "COMMITTING"
    STOPPED = "STOPPED"
    ERROR = "ERROR"

    def __init__(
        self,
        table: str,
        schema: Schema,
        table_config: TableConfig,
        partition: int,
        consumer_factory,
        decoder: Callable,
        checkpoint: CheckpointStore,
        segment_dir: str,
        on_consuming_segment: Callable,    # (partition, MutableSegment) -> None
        on_committed_segment: Callable,    # (partition, mutable, immutable) -> None
        upsert_manager: Optional[PartitionUpsertMetadataManager] = None,
        fetch_timeout_ms: int = 100,
        idle_sleep_s: float = 0.02,
        completion=None,  # SegmentCompletionClient for multi-replica commit
        peer_fetch=None,  # (segment_name, dest_dir) -> path; deep-store-down fallback
    ):
        self.table = table
        self.schema = schema
        self.table_config = table_config
        self.partition = partition
        self.factory = consumer_factory
        self.decoder = decoder
        self.checkpoint = checkpoint
        self.segment_dir = segment_dir
        self.on_consuming_segment = on_consuming_segment
        self.on_committed_segment = on_committed_segment
        self.upsert = upsert_manager
        from pinot_tpu.ingestion.transform import RecordTransformer

        self.record_transformer = RecordTransformer(table_config)
        self.partial_merger = None
        if upsert_manager is not None and table_config.upsert.mode == "PARTIAL":
            self.partial_merger = merger.PartialUpsertMerger(
                schema, table_config.upsert)
        self.fetch_timeout_ms = fetch_timeout_ms
        self.idle_sleep_s = idle_sleep_s
        self.completion = completion
        self.peer_fetch = peer_fetch
        self.adoptions = 0

        stream = table_config.stream
        self.rows_threshold = stream.segment_flush_threshold_rows
        self.time_threshold_s = stream.segment_flush_threshold_seconds
        self.state = self.CONSUMING
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.commits = 0
        self.index_errors = 0

        prior = checkpoint.committed(table, partition)
        if prior is not None:
            self._offset = StreamPartitionMsgOffset.from_string(prior["offset"])
            self._sequence = prior["sequence"] + 1
        else:
            self._offset = self.factory.earliest_offset(partition)
            self._sequence = 0
        self._new_consuming_segment()

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"rt-{self.table}-p{self.partition}", daemon=True
        )
        self._thread.start()

    def stop(self, commit_remaining: bool = True, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # consume thread still running (e.g. mid-seal): committing
                # from this thread too would double-seal the same segment
                log.warning("partition %s did not stop within %ss; skipping "
                            "final commit", self.partition, timeout)
                return
        if commit_remaining and self.segment.n_docs > 0:
            self._commit()
        self.state = self.STOPPED

    # ---- consume loop ----------------------------------------------------
    def _new_consuming_segment(self) -> None:
        name = llc_segment_name(self.table, self.partition, self._sequence,
                                self._offset.to_string())
        self.segment = MutableSegment(
            self.schema, name, self.table_config,
            enable_upsert=self.upsert is not None,
        )
        self.segment.start_offset = self._offset.to_string()
        self._segment_start_time = time.time()
        self.on_consuming_segment(self.partition, self.segment)

    def _run(self) -> None:
        consumer = self.factory.create_partition_consumer(self.partition)
        try:
            while not self._stop.is_set():
                try:
                    batch = consumer.fetch_messages(self._offset, self.fetch_timeout_ms)
                except Exception as e:  # flaky stream: retry from checkpointed offset
                    log.warning("partition %s consumer error: %s; recreating", self.partition, e)
                    time.sleep(self.idle_sleep_s)
                    try:
                        consumer.close()
                    except Exception:
                        pass
                    consumer = self.factory.create_partition_consumer(self.partition)
                    continue
                if self.upsert is None:
                    # columnar batch path (chunklet subsystem ingest basis):
                    # decode + transform per row, ONE index_batch per fetch
                    self._index_message_batch(batch.messages)
                else:
                    # upsert: the primary-key CAS is inherently per-row
                    for msg in batch.messages:
                        # poison messages must not wedge the partition: skip
                        # and count (the reference skips undecodable rows
                        # the same way); the offset still advances past
                        # them. Transform failures are CONFIG bugs, not bad
                        # data — those kill the partition loudly (ERROR
                        # state) instead of silently draining the stream
                        try:
                            row = self.decoder(msg.payload)
                            self._index_row(row, msg)
                        except TransformError:
                            raise
                        except Exception as e:  # noqa: BLE001
                            self._note_bad_message(msg, e)
                if len(batch) > 0:
                    self._offset = batch.next_offset
                    ci = self.segment.chunklet_index
                    if ci is not None:
                        # incremental seal: promote every full frozen block
                        # so queries ride the device path while consuming.
                        # Promotion failure is NON-FATAL: the rows are
                        # already indexed and keep serving from the host
                        # tail; the next batch retries
                        try:
                            ci.promote()
                        except Exception:  # noqa: BLE001 — optimization
                            log.exception(
                                "chunklet promotion failed for %s; rows "
                                "stay on the host tail path",
                                self.segment.name)
                else:
                    time.sleep(self.idle_sleep_s)
                if self._should_flush():
                    self.state = self.COMMITTING
                    self._commit()
                    self._new_consuming_segment()
                    self.state = self.CONSUMING
        except Exception:
            self.state = self.ERROR
            log.exception("partition %s consume loop died", self.partition)
        finally:
            consumer.close()

    def _note_bad_message(self, msg, e) -> None:
        self.index_errors += 1
        if self.index_errors <= 10 or self.index_errors % 1000 == 0:
            log.warning(
                "partition %s: dropping bad message at %s: %s",
                self.partition, getattr(msg, "offset", "?"), e,
            )

    def _index_message_batch(self, messages) -> None:
        """Non-upsert fetch handling: decode + transform row by row (poison
        rows skip, TransformError still kills the partition), then index
        the survivors through ONE columnar index_batch. A batch-level
        failure falls back to row-at-a-time so a single bad row is counted
        alone instead of dropping its whole fetch."""
        rows = []
        for msg in messages:
            try:
                row = self.decoder(msg.payload)
                if self.record_transformer.active:
                    row = self.record_transformer.apply_row(row)
                    if row is None:
                        continue  # filter_function dropped the record
                rows.append(row)
            except TransformError:
                raise
            except Exception as e:  # noqa: BLE001
                self._note_bad_message(msg, e)
        if not rows:
            return
        try:
            self.segment.index_batch(rows)
        except Exception:  # noqa: BLE001 — isolate the poison row
            for row in rows:
                try:
                    self.segment.index(row)
                except Exception as e:  # noqa: BLE001
                    self._note_bad_message(None, e)

    def _index_row(self, row: dict, msg) -> None:
        if self.record_transformer.active:
            row = self.record_transformer.apply_row(row)
            if row is None:
                return  # filter_function dropped the record
        if self.upsert is not None:
            key = tuple(row[k] for k in self.schema.primary_key_columns)
            cmp_col = self.upsert.comparison_column
            cmp_val = row.get(cmp_col) if cmp_col else msg.offset.value
            if self.partial_merger is not None:
                prev = self.upsert.get_location(key)
                # out-of-order events don't merge (the CAS below drops them),
                # mirroring the reference's ordered partial-upsert contract
                if prev is not None and (
                    cmp_col is None or cmp_val >= prev.comparison_value
                ):
                    prev_row = merger.read_row(
                        prev.segment, prev.doc_id, self.schema.column_names())
                    row = self.partial_merger.merge(prev_row, row)
            doc_id = self.segment.index(row)
            self.upsert.add_record(self.segment, doc_id, key, cmp_val)
        else:
            self.segment.index(row)

    def _should_flush(self) -> bool:
        if self.segment.n_docs >= self.rows_threshold:
            return True
        return (
            self.segment.n_docs > 0
            and time.time() - self._segment_start_time >= self.time_threshold_s
        )

    def _commit(self) -> None:
        """Seal → checkpoint → publish (the commit protocol).

        Checkpoint BEFORE publishing: a crash between the two must not leave
        a live registered segment whose offset range the restarted consumer
        re-consumes into a duplicate segment (double counting). The sealed
        dir + checkpoint entry are the durable commit — the reference makes
        segment metadata + offset one atomic ZK write; here restart
        reconciliation (RealtimeTableDataManager.start) republishes a
        committed-but-unpublished segment.

        With a completion client (multi-replica consumption), the commit is
        arbitrated first: exactly one replica builds the segment, the rest
        adopt its output (SegmentCompletionManager FSM semantics)."""
        mutable = self.segment
        mutable.end_offset = self._offset.to_string()
        if self.completion is not None:
            from pinot_tpu.realtime.completion import CommitOutcome

            outcome, entry = self.completion.arbitrate(
                self.partition, self._sequence, mutable.segment_name, self._stop
            )
            if outcome == CommitOutcome.ABORT:
                return  # shutting down while holding: leave rows unconsumed
            if outcome == CommitOutcome.ADOPT:
                self._adopt_committed(entry)
                return
        out = os.path.join(self.segment_dir, mutable.segment_name)
        sealed = mutable.seal(out)
        self.checkpoint.record_commit(
            self.table, self.partition, mutable.segment_name,
            self._offset.to_string(), self._sequence,
        )
        if self.completion is not None:
            self.completion.finish(
                self.partition, self._sequence, mutable.segment_name, out,
                self._offset.to_string(),
            )
        if self.upsert is not None:
            self.upsert.replace_segment(mutable, sealed)
        self.on_committed_segment(self.partition, mutable, sealed)
        self._sequence += 1
        self.commits += 1

    def _adopt_committed(self, entry: dict) -> None:
        """HOLDING replica path: another replica won the commit — discard
        the local in-progress rows, copy its sealed segment, resume from its
        end offset (the reference's download-and-replace)."""
        from pinot_tpu.realtime.completion import adopt_segment
        from pinot_tpu.storage.segment import ImmutableSegment

        try:
            local = adopt_segment(entry, self.segment_dir)
        except OSError:
            # the winner's published location is unreachable (deep store /
            # shared FS down): fetch from a serving replica over the data
            # plane instead (PeerServerSegmentFinder role, server/peer.py)
            if self.peer_fetch is None:
                raise
            local = self.peer_fetch(
                entry["segment"],
                os.path.join(self.segment_dir, entry["segment"]))
        sealed = ImmutableSegment(local)
        self._offset = StreamPartitionMsgOffset.from_string(entry["offset"])
        self.checkpoint.record_commit(
            self.table, self.partition, entry["segment"], entry["offset"],
            self._sequence,
        )
        self.on_committed_segment(self.partition, self.segment, sealed)
        self._sequence += 1
        self.adoptions += 1


class RealtimeTableDataManager:
    """All partitions of one realtime table (RealtimeTableDataManager.java),
    wired to a query-engine TableDataManager so consuming rows are
    immediately queryable."""

    def __init__(self, schema: Schema, table_config: TableConfig,
                 engine_table, data_dir: str, completion_client=None,
                 peer_fetch=None):
        if table_config.stream is None:
            raise ValueError("realtime table needs a stream config")
        self.schema = schema
        self.table_config = table_config
        self.engine_table = engine_table  # engine.TableDataManager
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.checkpoint = CheckpointStore(os.path.join(data_dir, "checkpoints.json"))
        self.partition_managers: dict[int, RealtimePartitionManager] = {}
        self.upsert_managers: dict[int, PartitionUpsertMetadataManager] = {}
        self._factory = create_consumer_factory(table_config.stream)
        self._decoder = get_decoder(table_config.stream.decoder, table_config.stream)
        self.completion = completion_client  # multi-replica commit FSM
        self.peer_fetch = peer_fetch  # deep-store-down adopt fallback
        self._on_commit_cb = None
        self._on_consuming_cb = None

    def start(self, partitions=None, on_commit=None, on_consuming=None) -> None:
        """``partitions``: subset to consume (cluster mode: only the
        partitions assigned to this server); callbacks let the server layer
        publish segment state to the cluster registry."""
        self._on_commit_cb = on_commit
        self._on_consuming_cb = on_consuming
        parts = list(partitions) if partitions is not None \
            else range(self._factory.partition_count())
        for p in parts:
            self.add_partition(p)

    def add_partition(self, p: int) -> None:
        """Start consuming one partition (idempotent) — called at start and
        when the controller reassigns a dead server's partitions here."""
        if p in self.partition_managers:
            return
        upsert = None
        if self.table_config.upsert.mode != "NONE":
            if not self.schema.primary_key_columns:
                raise ValueError("upsert requires schema primaryKeyColumns")
            upsert = PartitionUpsertMetadataManager(
                self.table_config.upsert.comparison_column
            )
            self.upsert_managers[p] = upsert
        self._reconcile_committed(p, upsert)
        mgr = RealtimePartitionManager(
            table=self.table_config.table_name,
            schema=self.schema,
            table_config=self.table_config,
            partition=p,
            consumer_factory=self._factory,
            decoder=self._decoder,
            checkpoint=self.checkpoint,
            segment_dir=self.data_dir,
            on_consuming_segment=self._on_consuming,
            on_committed_segment=self._on_committed,
            upsert_manager=upsert,
            completion=self.completion,
            peer_fetch=self.peer_fetch,
        )
        self.partition_managers[p] = mgr
        mgr.start()

    def stop_partition(self, p: int) -> None:
        """Stop consuming a partition (reassigned away): uncommitted rows
        are dropped — the new owner re-consumes from the last commit."""
        mgr = self.partition_managers.pop(p, None)
        if mgr is not None:
            mgr.stop(commit_remaining=False)
            self.engine_table.remove_segment(mgr.segment.segment_name)

    def stop(self, commit_remaining: bool = True) -> None:
        for mgr in self.partition_managers.values():
            mgr.stop(commit_remaining=commit_remaining)

    def _sealed_on_disk(self, partition: int) -> list:
        """(sequence, name) of this partition's sealed segment dirs, in
        commit order (LLCSegmentName: table__partition__sequence__ts)."""
        prefix = f"{self.table_config.table_name}__{partition}__"
        out = []
        try:
            entries = os.listdir(self.data_dir)
        except OSError:
            return []
        for name in entries:
            if not name.startswith(prefix):
                continue
            if not os.path.isdir(os.path.join(self.data_dir, name)):
                continue
            try:
                seq = int(name.split("__")[2])
            except (IndexError, ValueError):
                continue
            out.append((seq, name))
        out.sort()
        return out

    def _reconcile_committed(self, partition: int, upsert=None) -> None:
        """Restart reconciliation, two duties:

        1. Crash-window repair: if the checkpoint names a sealed segment that
           exists on disk but was never registered (crash after record_commit,
           before publication), publish it now.
        2. Upsert replay: sealed dirs hold ALL rows with no persisted
           validDocIds, and the server layer's registry sync loads them with
           bare add_segment — so replay EVERY sealed segment's primary keys
           through the fresh upsert manager, in commit (sequence) order, so
           stale duplicates are re-invalidated and later stream updates keep
           invalidating them."""
        from pinot_tpu.storage.segment import ImmutableSegment

        prior = self.checkpoint.committed(self.table_config.table_name, partition)
        if prior is None:
            return
        committed_seq = prior["sequence"]
        committed_name = prior["segment"]
        engine_segs = getattr(self.engine_table, "segments", {})
        cmp_base = 0  # running doc base across sealed segments (commit order)
        for seq, name in self._sealed_on_disk(partition):
            if seq > committed_seq:
                continue  # sealed dir past the checkpoint: orphan, not committed
            expected = self.checkpoint.committed_name(
                self.table_config.table_name, partition, seq
            )
            if expected is None and seq == committed_seq:
                expected = committed_name  # legacy checkpoint without names log
            if expected is not None and name != expected:
                # orphan from a crash between seal() and record_commit(): the
                # later re-consumed committed segment shares this sequence
                # (names embed a creation timestamp, so they differ), and its
                # rows are duplicates of the committed one's — quarantine it
                # so neither this pass nor future restarts publish or replay
                # it (an orphan at an OLDER sequence would otherwise inflate
                # cmp_base and make replayed stale rows beat live updates)
                log.warning("partition %s: quarantining orphan segment %s "
                            "(committed name at seq %s is %s)",
                            partition, name, seq, expected)
                orphans = os.path.join(self.data_dir, "_orphans")
                os.makedirs(orphans, exist_ok=True)
                os.replace(os.path.join(self.data_dir, name),
                           os.path.join(orphans, name))
                continue
            # Replay must target the instance the engine queries (the
            # valid_docs_mask attaches to the object), not a fresh load.
            existing = engine_segs.get(name)
            sealed = existing
            if sealed is None:
                sealed = ImmutableSegment(os.path.join(self.data_dir, name))
            if upsert is not None:
                pk_cols = [sealed.values(c) for c in self.schema.primary_key_columns]
                keys = list(zip(*pk_cols))
                if upsert.comparison_column is not None:
                    cmps = sealed.values(upsert.comparison_column)
                else:
                    # doc order == offset order, but only WITHIN a segment:
                    # offset the range by the docs replayed so far so a later
                    # segment's rows compare greater than an earlier one's
                    # (live ingestion uses the global stream offset, which is
                    # >= total replayed docs on resume)
                    cmps = range(cmp_base, cmp_base + sealed.n_docs)
                upsert.add_segment(sealed, keys, cmps)
            cmp_base += sealed.n_docs
            if existing is None and (upsert is not None or seq == committed_seq):
                # non-upsert: only the checkpointed segment can be in the
                # crash window; earlier ones come from the registry sync
                self._publish_committed(partition, sealed)

    # ---- engine wiring ---------------------------------------------------
    def _on_consuming(self, partition: int, segment: MutableSegment) -> None:
        self.engine_table.add_segment(segment)
        cb = getattr(self, "_on_consuming_cb", None)
        if cb is not None:
            cb(self.table_config.table_name, partition, segment)

    def _on_committed(self, partition: int, mutable, sealed) -> None:
        if mutable is not None and mutable.segment_name != sealed.name:
            # adopted segment under a different name: drop the discarded
            # consuming segment so its rows don't double-count
            self.engine_table.remove_segment(mutable.segment_name)
        self._publish_committed(partition, sealed)

    def _publish_committed(self, partition: int, sealed) -> None:
        # same segment name: registering the sealed segment atomically
        # replaces the consuming one in the table's dict
        self.engine_table.add_segment(sealed)
        cb = getattr(self, "_on_commit_cb", None)
        if cb is not None:
            cb(self.table_config.table_name, partition, sealed)

    def total_docs_indexed(self) -> int:
        return sum(m.segment.n_docs for m in self.partition_managers.values())
