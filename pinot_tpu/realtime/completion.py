"""Segment-completion protocol client: multi-replica commit coordination.

The TPU-build analog of the reference's controller-side
SegmentCompletionManager FSM (pinot-controller/.../core/realtime/
SegmentCompletionManager.java) plus the server-side commit steps of
LLRealtimeSegmentDataManager (HOLDING / COMMITTING / adopt-committed):

- every replica of a stream partition consumes independently;
- the first replica to hit its flush threshold CAS-claims the commit for
  (partition, sequence) in the cluster registry;
- the winner seals its rows, durably records the segment, and marks the
  entry DONE with the segment location + end offset;
- losers HOLD (poll), then ADOPT the committed segment: discard their
  in-progress rows, copy the winner's sealed dir, resume consuming from the
  winner's end offset — the reference's "download and replace" path;
- if the committer dies mid-build the entry goes stale and a holder takes
  over (the reference's committer-timeout re-election).
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Optional


class CommitOutcome:
    WON = "WON"          # this replica builds + publishes the segment
    ADOPT = "ADOPT"      # another replica committed: adopt its segment
    ABORT = "ABORT"      # shutdown requested while holding


class SegmentCompletionClient:
    """Registry-backed completion FSM, one per (server, realtime table)."""

    def __init__(self, registry, table: str, instance_id: str,
                 stale_ms: int = 5_000, poll_s: float = 0.05,
                 hold_timeout_s: float = 30.0):
        self.registry = registry
        self.table = table
        self.instance_id = instance_id
        self.stale_ms = stale_ms
        self.poll_s = poll_s
        self.hold_timeout_s = hold_timeout_s

    def arbitrate(self, partition: int, sequence: int, segment_name: str,
                  stop_event=None):
        """Blocks until this replica either WINS the commit or can ADOPT a
        committed segment. Returns (outcome, entry)."""
        entry = self.registry.try_claim_commit(
            self.table, partition, sequence, self.instance_id, segment_name
        )
        if entry["committer"] == self.instance_id and entry["state"] == "COMMITTING":
            return CommitOutcome.WON, entry
        # HOLDING: wait for the winner, taking over if it goes stale
        deadline = time.time() + self.hold_timeout_s
        while time.time() < deadline:
            if stop_event is not None and stop_event.is_set():
                return CommitOutcome.ABORT, entry
            entry = self.registry.takeover_commit(
                self.table, partition, sequence, self.instance_id, self.stale_ms
            )
            if entry["state"] == "DONE":
                return CommitOutcome.ADOPT, entry
            if entry["committer"] == self.instance_id:
                return CommitOutcome.WON, entry  # takeover: dead committer
            time.sleep(self.poll_s)
        raise TimeoutError(
            f"segment completion for {self.table} p{partition} seq{sequence} "
            f"never resolved (committer {entry['committer']})"
        )

    def finish(self, partition: int, sequence: int, segment_name: str,
               location: str, end_offset: str) -> bool:
        return self.registry.finish_commit(
            self.table, partition, sequence, self.instance_id, segment_name,
            location, end_offset
        )

    def committed_entry(self, partition: int, sequence: int) -> Optional[dict]:
        e = self.registry.commit_entry(self.table, partition, sequence)
        return e if e is not None and e["state"] == "DONE" else None


def adopt_segment(entry: dict, dest_dir: str) -> str:
    """Copy the committed segment dir into this server's data dir (the
    download-from-deep-store step). Returns the local path."""
    dest = os.path.join(dest_dir, entry["segment"])
    src = entry["location"]
    if os.path.abspath(src) != os.path.abspath(dest):
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(src, dest)
    return dest
