"""Upsert metadata: primary-key → latest-record tracking + validDocIds.

Equivalent of the reference's ``PartitionUpsertMetadataManager``
(pinot-segment-local/.../upsert/PartitionUpsertMetadataManager.java:67-117):
a per-partition map primaryKey → RecordLocation with compare-and-swap on the
comparison column; losers get their doc flipped out of the segment's
validDocIds bitmap. Queries AND validDocIds into the filter
(FilterPlanNode.java:94-100 analog — engine/host.py applies the snapshot).

Restart recovery: ``add_segment`` rebuilds the map from sealed segments in
commit order, exactly like the reference re-adds segments on server start.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass
class RecordLocation:
    segment: object  # Mutable/ImmutableSegment with invalidate()/valid mask
    doc_id: int
    comparison_value: object


import numpy as np


def _invalidate(segment, doc_id: int) -> None:
    if hasattr(segment, "invalidate"):
        segment.invalidate(doc_id)
        return
    # sealed segment: flip the in-memory valid mask, materializing it on
    # first use (segments freshly loaded from disk start with mask=None ==
    # all-valid; the mask is rebuilt from the upsert map on restart)
    mask = getattr(segment, "valid_docs_mask", None)
    if mask is None:
        mask = np.ones(segment.n_docs, dtype=bool)
        segment.valid_docs_mask = mask
    mask[doc_id] = False


class PartitionUpsertMetadataManager:
    def __init__(self, comparison_column: Optional[str] = None):
        self.comparison_column = comparison_column
        self._map: dict = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._map)

    def get_location(self, key: tuple) -> Optional[RecordLocation]:
        """Current winner for a key (partial-upsert previous-version read).
        Safe under the single-consumer-per-partition writer contract."""
        with self._lock:
            return self._map.get(key)

    def add_record(self, segment, doc_id: int, key: tuple, comparison_value) -> bool:
        """CAS semantics (reference :102-117): the record with the greater
        comparison value wins; ties go to the newer record."""
        with self._lock:
            loc = self._map.get(key)
            if loc is None or comparison_value >= loc.comparison_value:
                if loc is not None:
                    _invalidate(loc.segment, loc.doc_id)
                self._map[key] = RecordLocation(segment, doc_id, comparison_value)
                return True
            _invalidate(segment, doc_id)
            return False

    def add_segment(self, segment, keys, comparison_values) -> None:
        """Bulk (re)register a sealed segment's rows (restart rebuild)."""
        for doc_id, (k, c) in enumerate(zip(keys, comparison_values)):
            self.add_record(segment, doc_id, tuple(k), c)

    def replace_segment(self, old_segment, new_segment) -> None:
        """Consuming → sealed handoff: doc ids are preserved (no compaction
        at commit, matching the reference), so locations just re-point."""
        with self._lock:
            for loc in self._map.values():
                if loc.segment is old_segment:
                    loc.segment = new_segment
