"""Chunklet subsystem: columnar batch ingest + device promotion for
consuming segments.

The reference serves CONSUMING segments through ``MutableSegmentImpl`` row
structures and sealed segments through immutable readers, with
``LLRealtimeSegmentDataManager`` walking rows between the two worlds. On
this engine that split was absolute: a consuming segment was permanently
device-ineligible, so a 1M-row consuming tail became the cluster's latency
ceiling (BENCH_r05: 72ms host p50 at just 200k rows) while sealed data
answered in single-digit device milliseconds.

Chunklets close that gap. A consuming segment's doc space splits into

- a FROZEN PREFIX of fixed-size sealed blocks (``Chunklet``): the
  single-writer contract means docs below the published count never change,
  so once ``rows_per_chunklet`` docs accumulate they re-encode into sorted
  dictionaries + int32 forward ids — the exact shape
  ``engine/params.BatchContext`` uploads to HBM. Chunklets duck-type the
  ImmutableSegment reader protocol, so they ride the SAME batched (S, L)
  device templates, batch LRU + in-flight refcounting (PR-2), and mesh
  sharding as sealed segments — no new kernel code.
- an UNFROZEN ROW TAIL that stays on the host scan path
  (``MutableTailView`` exposes just the tail rows to the host executor);
  ``engine/engine.py`` merges the device and host partials like any other
  mixed backend split.

Upsert: validDocIds can flip docs INSIDE the frozen prefix (a newer version
of a key arrives in the tail). ``MutableSegment.invalidate`` notifies the
index; a dirtied chunklet drops off the device path and executes on the
host with its mask slice — correctness first, device speed for the
untouched blocks.

Ingest: ``MutableSegment.index_batch`` (columnar numpy appends +
vectorized dictionary growth) replaces per-row ``index(dict)`` as the
consume-loop basis, and ``ingest_worker_main`` runs one partition's
consume loop in its own OS process (the controller-HA test's process
harness pattern) so multi-partition ingest scales past the GIL.
"""

from __future__ import annotations

import bisect
import json
import logging
import sys
import threading
import time

import numpy as np

from pinot_tpu.common.datatypes import FieldRole
from pinot_tpu.storage.dictionary import Dictionary
from pinot_tpu.storage.segment import ColumnMetadata, Encoding, SegmentMetadata

log = logging.getLogger("pinot_tpu.realtime.chunklet")


def _invalidate_device_partials(match: str) -> None:
    """Fan a partials-cache invalidation out to every live DeviceExecutor
    (engine/device.py invalidate_cached_partials). Import-free when the
    device module was never loaded — ingest worker processes must not
    pull jax in just to notify a cache that cannot exist there.
    Correctness never rides on this hook (batch keys change with the
    chunklet set, so stale entries are unreachable); it frees the device
    bytes they pin."""
    dev_mod = sys.modules.get("pinot_tpu.engine.device")
    if dev_mod is None:
        return
    try:
        dev_mod.invalidate_cached_partials(match)
    except Exception:  # noqa: BLE001 — cache hygiene must not fail ingest
        log.exception("device partials invalidation failed for %r", match)


def _use_dictionary(spec, no_dict_cols) -> bool:
    """Mirror the segment creator's encoding policy (storage/creator.py):
    strings always dict-encode; numeric dimensions/datetimes dict-encode
    unless listed in no_dictionary_columns; metrics stay RAW. Chunklets
    must match sealed segments so the same query templates apply."""
    if spec.data_type.is_string_like:
        return True
    if spec.name in no_dict_cols:
        return False
    return spec.role is not FieldRole.METRIC


class Chunklet:
    """One sealed 64k-row block of a consuming segment's frozen prefix.

    Immutable by construction (docs below the published count never
    mutate), device-eligible while clean, and a full duck-type of the
    ImmutableSegment reader surface the batch/host layers touch:
    ``metadata.columns`` / ``column_metadata`` / ``dictionary`` /
    ``forward`` / ``values`` / ``null_vector`` / ``n_docs`` / ``dir``.
    ``dir`` is the executor's batch cache key — stable per block, so
    repeated queries over the same frozen prefix hit the HBM-resident
    BatchContext. Because per-block ColumnMetadata carries exact
    cardinality and min/max (``_seal_column``), the batch layer's width
    planner (engine/params.py ColPlan) narrows chunklet planes exactly
    like sealed segments' — uint8/uint16 dict ids, frame-of-reference
    raw values — pinned by tests/test_narrow.py."""

    is_mutable = False

    def __init__(self, segment, ordinal: int, start: int, stop: int):
        self._seg = segment
        self.ordinal = ordinal
        self.start = start
        self.stop = stop
        self.name = f"{segment.segment_name}__ck{ordinal}"
        self.dir = f"<chunklet:{segment.segment_name}:{ordinal}:{start}-{stop}>"
        # upsert invalidation landed inside [start, stop): invalidations
        # that PREDATE promotion (a newer key version arrived before this
        # block filled) must dirty the block at seal time — note_invalidated
        # only covers blocks that already exist
        v = segment._valid
        self._dirty = bool(v is not None and not v[start:stop].all())
        self._fwd: dict[str, np.ndarray] = {}
        self._dicts: dict[str, Dictionary] = {}
        self._nulls: dict[str, np.ndarray] = {}
        self._zmaps: dict[str, np.ndarray] = {}
        no_dict = getattr(segment.table_config.indexing,
                          "no_dictionary_columns", [])
        cols_meta: dict[str, ColumnMetadata] = {}
        for cname, col in segment._cols.items():
            cols_meta[cname] = self._seal_column(cname, col, no_dict)
        self.metadata = SegmentMetadata(
            segment_name=self.name,
            table_name=segment.schema.name,
            n_docs=stop - start,
            columns=cols_meta,
        )

    def _seal_column(self, name: str, col, no_dict) -> ColumnMetadata:
        start, stop, n = self.start, self.stop, self.stop - self.start
        spec = col.spec
        # null mask over the block (null_docs appends in doc order)
        nd = col.null_docs
        lo = bisect.bisect_left(nd, start)
        hi = bisect.bisect_left(nd, stop)
        has_nulls = hi > lo
        if has_nulls:
            mask = np.zeros(n, dtype=bool)
            mask[np.asarray(nd[lo:hi], dtype=np.int64) - start] = True
            self._nulls[name] = mask
        if col.dict_encoded:
            # insertion-ordered ids → per-block SORTED dictionary: unique
            # over the ids first (distinct count << block rows), decode only
            # the distinct values, rank-remap the forward index
            ids = np.asarray(col._data[start:stop])
            table = col.dict_table()
            uids, inv = np.unique(ids, return_inverse=True)
            uvals = table[uids]
            order = np.argsort(uvals)
            sorted_vals = uvals[order]
            rank = np.empty(len(order), dtype=np.int32)
            rank[order] = np.arange(len(order), dtype=np.int32)
            self._fwd[name] = rank[inv].astype(np.int32)
            self._dicts[name] = Dictionary(sorted_vals)
            return ColumnMetadata(
                name=name, data_type=spec.data_type, encoding=Encoding.DICT,
                cardinality=len(sorted_vals),
                min_value=sorted_vals[0].item() if sorted_vals.dtype.kind
                not in ("U", "S", "O") else sorted_vals[0],
                max_value=sorted_vals[-1].item() if sorted_vals.dtype.kind
                not in ("U", "S", "O") else sorted_vals[-1],
                is_sorted=False, single_value=True, has_dictionary=True,
                has_null_vector=has_nulls, total_number_of_entries=n,
            )
        vals = np.asarray(col._data[start:stop])
        if _use_dictionary(spec, no_dict):
            sorted_vals, inv = np.unique(vals, return_inverse=True)
            self._fwd[name] = inv.astype(np.int32)
            self._dicts[name] = Dictionary(sorted_vals)
            return ColumnMetadata(
                name=name, data_type=spec.data_type, encoding=Encoding.DICT,
                cardinality=len(sorted_vals),
                min_value=sorted_vals[0].item(),
                max_value=sorted_vals[-1].item(),
                is_sorted=False, single_value=True, has_dictionary=True,
                has_null_vector=has_nulls, total_number_of_entries=n,
            )
        self._fwd[name] = vals.copy()
        return ColumnMetadata(
            name=name, data_type=spec.data_type, encoding=Encoding.RAW,
            cardinality=-1,
            min_value=vals.min().item(), max_value=vals.max().item(),
            is_sorted=False, single_value=True, has_dictionary=False,
            has_null_vector=has_nulls, total_number_of_entries=n,
        )

    # ---- reader protocol -------------------------------------------------
    @property
    def n_docs(self) -> int:
        return self.stop - self.start

    def column_names(self) -> list:
        return list(self.metadata.columns)

    def column_metadata(self, col: str) -> ColumnMetadata:
        return self.metadata.columns[col]

    def dictionary(self, col: str):
        return self._dicts.get(col)

    def forward(self, col: str) -> np.ndarray:
        return self._fwd[col]

    def bloom(self, col: str):
        return None

    def zone_map(self, col: str) -> np.ndarray:
        """(2, n_blocks) per-block [min, max] over this chunklet's forward
        index (local dict ids / raw values), same contract as
        ImmutableSegment.zone_map. Computed lazily from the sealed block —
        chunklets are immutable, so one compute per promotion is the
        "refresh": every new frozen block arrives with fresh zone maps and
        the consuming segment's device batch prunes like sealed data."""
        zm = self._zmaps.get(col)
        if zm is None:
            from pinot_tpu.storage.segment import build_zone_map

            zm = build_zone_map(self._fwd[col])
            self._zmaps[col] = zm
        return zm

    def values(self, col: str) -> np.ndarray:
        return self.flat_values(col)

    def flat_values(self, col: str) -> np.ndarray:
        d = self._dicts.get(col)
        if d is None:
            return self._fwd[col]
        return d.take(self._fwd[col])

    def null_vector(self, col: str):
        return self._nulls.get(col)

    # ---- upsert masking --------------------------------------------------
    def mark_dirty(self) -> None:
        self._dirty = True  # one-way: invalidations never un-flip

    @property
    def is_clean(self) -> bool:
        return not self._dirty

    @property
    def valid_docs_mask(self):
        """None while clean (device-eligible); once an upsert invalidation
        lands in range, a SNAPSHOT slice of the segment's validDocIds —
        the same snapshot-at-query semantics the host path applies to the
        whole mutable segment."""
        if not self._dirty:
            return None
        return np.asarray(self._seg._valid[self.start:self.stop]).copy()


class MutableTailView:
    """The unfrozen row tail [start, stop) of a consuming segment, duck-
    typed for the host executor. ``stop`` pins the reader snapshot at
    split time so every column sees the same doc count."""

    is_mutable = True
    valid_docs_mask = None

    def __init__(self, segment, start: int, stop: int):
        self._seg = segment
        self.start = start
        self._n = stop - start
        self.name = f"{segment.segment_name}__tail{start}"
        self.dir = f"<mutable-tail:{segment.segment_name}:{start}:{stop}>"

    @property
    def n_docs(self) -> int:
        return self._n

    @property
    def metadata(self):
        # segment-wide metadata: min/max are a superset of the tail's,
        # so pruning stays conservative-correct
        return self._seg.metadata

    def column_names(self) -> list:
        return self._seg.column_names()

    def column_metadata(self, col: str) -> ColumnMetadata:
        return self._seg.column_metadata(col)

    def dictionary(self, col: str):
        return None

    def bloom(self, col: str):
        return None

    def values(self, col: str) -> np.ndarray:
        # ranged decode: the tail must not pay a full-segment dictionary
        # take per query — that cost is what promotion removed
        return self._seg._cols[col].values_range(
            self.start, self.start + self._n)

    def valid_docs(self, n: int):
        m = self._seg.valid_docs(self.start + n)
        return None if m is None else m[self.start:]

    def null_vector(self, col: str):
        nv = self._seg.null_vector(col)
        if nv is None:
            return None
        nv = nv[self.start:self.start + self._n]
        return nv if nv.any() else None


class ChunkletIndex:
    """Per-consuming-segment promotion state: the grown-but-frozen prefix
    sealed so far, plus the upsert dirty flags. ``chunklets`` is grow-only
    and appended AFTER a block is fully built — the same volatile-publish
    discipline as the segment's doc counter, so query threads can snapshot
    it lock-free."""

    def __init__(self, segment, config):
        self.segment = segment
        self.rows_per_chunklet = max(1024, int(config.rows_per_chunklet))
        self.device_min_rows = int(config.device_min_rows)
        self.chunklets: list[Chunklet] = []
        self._promote_lock = threading.Lock()

    @property
    def frozen_docs(self) -> int:
        cks = self.chunklets
        return cks[-1].stop if cks else 0

    def promote(self, limit: int = None) -> int:
        """Seal every full chunklet below the published doc count (writer
        thread; the lock only defends against an explicit second caller).
        Returns the number of blocks promoted.

        Failure semantics: chunklets publish append-only AFTER they are
        fully built, so a promotion failure (including an injected one)
        leaves the index consistent — the unfrozen rows simply stay on
        the host tail path and queries remain correct; consume loops
        treat the raise as non-fatal and retry on the next batch."""
        from pinot_tpu.common import faults

        if faults.ACTIVE:
            faults.inject("chunklet.promote",
                          target=getattr(self.segment, "name", None))
        made = 0
        with self._promote_lock:
            while limit is None or made < limit:
                start = self.frozen_docs
                stop = start + self.rows_per_chunklet
                if self.segment.n_docs < stop:
                    break
                ck = Chunklet(self.segment, len(self.chunklets), start, stop)
                self.chunklets.append(ck)  # publish fully-built only
                made += 1
        if made:
            # the chunklet set changed: device batches (and their cached
            # partials) built over the OLD frozen prefix retire, and the
            # table's freshness epoch bumps so broker result caches can't
            # serve answers computed over the old split (ISSUE 10)
            from pinot_tpu.common import freshness

            _invalidate_device_partials(
                f"<chunklet:{self.segment.segment_name}:")
            freshness.bump(self.segment.table_config.table_name)
        return made

    def note_invalidated(self, doc_id: int) -> None:
        i = doc_id // self.rows_per_chunklet
        cks = self.chunklets
        if i < len(cks):
            was_clean = cks[i].is_clean
            cks[i].mark_dirty()
            if was_clean:
                # first upsert into this block: cached partials over any
                # batch containing it are stale-by-construction (the
                # table epoch itself bumps in MutableSegment.invalidate)
                _invalidate_device_partials(cks[i].dir)

    def column_with_tail(self, name: str, n: int) -> np.ndarray:
        """Decoded column over docs [0, n): chunklet blocks for the frozen
        prefix + the mutable decode for the tail — the final seal's reuse
        path (RealtimeSegmentConverter analog input)."""
        cks = list(self.chunklets)
        frozen = cks[-1].stop
        parts = [ck.flat_values(name) for ck in cks]
        if n > frozen:
            parts.append(self.segment._cols[name].values_range(frozen, n))
        return np.concatenate(parts) if len(parts) > 1 else parts[0]


def split_for_query(seg):
    """(device_chunklets, host_parts) for a consuming segment, or None when
    the chunklet path doesn't apply (below the crossover, nothing promoted,
    or every block is upsert-dirty) — the engine then runs the whole
    segment on the host scan path as before.

    Snapshot semantics: the chunklet list and doc count are read once;
    rows and invalidations landing after the split are picked up by the
    next query, exactly like the host path's validDocIds snapshot."""
    ci = getattr(seg, "chunklet_index", None)
    if ci is None:
        return None
    cks = list(ci.chunklets)
    if not cks:
        return None
    frozen = cks[-1].stop
    if frozen < ci.device_min_rows:
        return None
    n = seg.n_docs  # read AFTER the chunklet snapshot: frozen <= n
    device = [ck for ck in cks if ck.is_clean]
    if not device:
        return None
    host = [ck for ck in cks if not ck.is_clean]
    if n > frozen:
        host.append(MutableTailView(seg, frozen, n))
    return device, host


# ---------------------------------------------------------------------------
# per-partition OS-process consume loop (multi-partition ingest harness)
# ---------------------------------------------------------------------------


def consume_stream_batches(segment, consumer, decoder, start_offset,
                           transform=None, on_error=None,
                           promote: bool = True, batch_decoder=None,
                           max_rows: int = 8192):
    """One fetch→decode→index_batch→promote step of a consume loop.
    Returns (rows_indexed, next_offset, fetched_count).

    Fast paths compose when available: ``fetch_payload_batch`` (raw
    payloads, no per-message object construction) and ``batch_decoder``
    (one parser call per fetch). Decode failures skip the row
    (poison-message semantics) by re-decoding the batch row-at-a-time;
    an ``index_batch`` failure likewise falls back to per-row ``index``
    so one bad row can't drop its whole batch."""
    fp = getattr(consumer, "fetch_payload_batch", None)
    if fp is not None:
        payloads, next_offset = fp(start_offset, max_rows)
        fetched = len(payloads)
    else:
        batch = consumer.fetch_messages(start_offset, 100)
        payloads = [m.payload for m in batch.messages]
        next_offset = batch.next_offset
        fetched = len(batch)
    rows = None
    if payloads and batch_decoder is not None and transform is None:
        try:
            rows = batch_decoder(payloads)
        except Exception:  # noqa: BLE001 — isolate below, per payload
            rows = None
    if rows is None:
        rows = []
        for p in payloads:
            try:
                row = decoder(p)
                if transform is not None:
                    row = transform(row)
                    if row is None:
                        continue
                rows.append(row)
            except Exception as e:  # noqa: BLE001 — poison message
                if on_error is not None:
                    on_error(p, e)
    indexed = 0
    if rows:
        try:
            segment.index_batch(rows)
            indexed = len(rows)
        except Exception:  # noqa: BLE001 — isolate the poison row
            for row in rows:
                try:
                    segment.index(row)
                    indexed += 1
                except Exception as e:  # noqa: BLE001
                    if on_error is not None:
                        on_error(None, e)
    if promote and segment.chunklet_index is not None:
        try:
            segment.chunklet_index.promote()
        except Exception:  # noqa: BLE001 — promotion is an optimization
            # a failed promotion must not drop ingested rows or kill the
            # consume loop: the unfrozen rows keep serving from the host
            # tail and the next batch retries the promotion
            log.exception("chunklet promotion failed; rows stay on the "
                          "host tail path")
    return indexed, next_offset, fetched


def ingest_worker_main(spec: dict) -> dict:
    """One partition's consume loop, meant to run in its OWN OS process
    (spawned with ``sys.executable -m pinot_tpu.realtime.chunklet`` — the
    controller-HA test's process-harness pattern): ingests ``rows``
    synthetic events into a MutableSegment via ``index_batch`` with
    chunklet promotion, timing ONLY the ingest phase.

    ``spec["payload"]`` picks the basis:

    - ``"rows"`` (default): pre-decoded dict rows — the SAME basis
      BENCH_r05 measured (its thread workers indexed pre-built rows), so
      the aggregate number is comparable across rounds;
    - ``"json"``: the full stream consume loop — publish serialized JSON
      to an in-process memory stream partition, then fetch→batch-decode→
      index_batch through the stream SPI (decode cost included).

    Returns the rows/s report the parent aggregates."""
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import (
        ChunkletConfig,
        StreamConfig,
        TableConfig,
        TableType,
    )
    from pinot_tpu.stream.memory_stream import TopicRegistry
    from pinot_tpu.stream.spi import (
        StreamPartitionMsgOffset,
        create_consumer_factory,
        get_decoder,
    )

    n = int(spec.get("rows", 1_000_000))
    partition = int(spec.get("partition", 0))
    rows_per_chunklet = int(spec.get("rows_per_chunklet", 65_536))
    distinct_zones = int(spec.get("distinct_zones", 260))
    seed = int(spec.get("seed", 7)) + partition

    schema = Schema.build(
        name="rtm",
        dimensions=[("zone", DataType.STRING), ("hour", DataType.INT)],
        metrics=[("fare", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="rtm", table_type=TableType.REALTIME,
        stream=StreamConfig(stream_type="memory", topic=f"rtm_p{partition}"),
        chunklets=ChunkletConfig(enabled=True,
                                 rows_per_chunklet=rows_per_chunklet,
                                 device_min_rows=0),
    )

    # synthesize a cycle of events once (producer cost, untimed)
    rng = np.random.default_rng(seed)
    cycle = min(n, 65_536)
    zs = rng.integers(0, distinct_zones, cycle)
    hs = rng.integers(0, 24, cycle)
    fs = rng.integers(100, 10_000, cycle)
    events = [
        {"zone": f"zone_{z:03d}", "hour": int(h), "fare": int(f)}
        for z, h, f in zip(zs, hs, fs)
    ]
    from pinot_tpu.storage.mutable import MutableSegment

    seg = MutableSegment(schema, f"rtm__{partition}__0__0", cfg)
    errors = 0

    if spec.get("payload", "rows") == "json":
        # full consume loop: stream fetch + batched JSON decode included
        from pinot_tpu.stream.spi import get_batch_decoder

        payloads = [json.dumps(e).encode("utf-8") for e in events]
        topic = TopicRegistry.create(f"rtm_p{partition}", 1)
        for i in range(n):
            topic.publish(payloads[i % cycle], 0)
        factory = create_consumer_factory(cfg.stream)
        consumer = factory.create_partition_consumer(0)
        decoder = get_decoder("json", cfg.stream)
        batch_decoder = get_batch_decoder("json", cfg.stream)
        offset = StreamPartitionMsgOffset(0)

        def on_error(_msg, _e):
            nonlocal errors
            errors += 1

        t0 = time.perf_counter()
        while seg.n_docs + errors < n:
            _, offset, got = consume_stream_batches(
                seg, consumer, decoder, offset, on_error=on_error,
                batch_decoder=batch_decoder)
            if got == 0:
                break
        elapsed = time.perf_counter() - t0
    else:
        # pre-decoded rows (the BENCH_r05-comparable basis): pure columnar
        # index + promotion
        rows = [events[i % cycle] for i in range(n)]
        batch = 8192
        t0 = time.perf_counter()
        for i in range(0, n, batch):
            seg.index_batch(rows[i:i + batch])
            seg.chunklet_index.promote()
        elapsed = time.perf_counter() - t0
    return {
        "partition": partition,
        "rows": seg.n_docs,
        "errors": errors,
        "seconds": round(elapsed, 4),
        "rows_per_s": round(seg.n_docs / elapsed) if elapsed > 0 else 0,
        "chunklets": len(seg.chunklet_index.chunklets)
        if seg.chunklet_index is not None else 0,
    }


if __name__ == "__main__":
    _spec = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    print(json.dumps(ingest_worker_main(_spec)))
