"""Mesh-parallel execution: shard the segment axis over TPU chips.

This is the distributed-combine layer — the TPU-native replacement for both
of the reference's parallel layers (SURVEY.md §2.9):

- intra-server combine (BaseCombineOperator's thread fan-out + BlockingQueue
  merge, operator/combine/BaseCombineOperator.java:79-145) → the batched
  (S, L) kernel already combines segments in one launch; here the S axis is
  *sharded* over a ``jax.sharding.Mesh`` and partial accumulators merge with
  XLA collectives riding ICI:
    sums/counts → psum, min → pmin, max/presence/HLL-registers → pmax.
- broker scatter-gather across servers stays host-side (broker/), exactly as
  the reference keeps Netty between nodes.

Because group-by accumulators live in *global dictionary id space*
(engine/params.py), the cross-chip psum is a dense elementwise reduce — no
key exchange, no IndexedTable merge, no all-to-all. The one exception is
the sorted/high-cardinality (radix) regime, whose per-shard tables are
keyed, not slot-aligned: those merge by KEY over an all-gather
(_combine_sorted_table — answer-sized work, the IndexedTable-merge analog
done once per query on ICI).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# newer jax exposes shard_map at top level (replication checking spelled
# check_vma); jax <= 0.4.x ships it in experimental as check_rep. Resolve
# once so the combine layer runs on both.
if hasattr(jax, "shard_map"):
    _shard_map, _SM_KW = jax.shard_map, {"check_vma": False}
else:  # pragma: no cover - exercised on jax 0.4.x only
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}

SEG_AXIS = "segments"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the segment axis (data-parallel OLAP scan)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (SEG_AXIS,))


def _combine_out(key: str, v):
    """Collective per output name — the psum-combine replacing the reference's
    blocking-queue merge."""
    if key == "seg_matched":
        return v  # stays per-shard; out_spec P(SEG_AXIS) reassembles (S,)
    if key.endswith(("_min", "_tmin")):
        return jax.lax.pmin(v, SEG_AXIS)
    if key.endswith(("_max", "_tmax", "_pres", "_regs")):
        return jax.lax.pmax(v, SEG_AXIS)
    # doc_count, gcount, *_sum, counts
    return jax.lax.psum(v, SEG_AXIS)


def _combine_sorted_table(outs: dict) -> dict:
    """KEY-ALIGNED merge for the sorted/high-cardinality (radix) regime:
    each shard emits a (K,) group table whose slots are keyed by ``skeys``
    (INT64_SENTINEL empties) with NEUTRAL empty-slot fills, so the same
    group can sit in different slots on different shards and a dense psum
    would be wrong. All-gather the (K,) tables to (D, K) and re-run the
    radix level-2 combine over them (ops/radix_groupby.py merge_tables) —
    answer-sized work, riding ICI. Overflow stays host-detected: if any
    shard's table overflowed (shard_total > K, so its table is truncated
    and the gathered keys are incomplete) the combined total is forced
    past K so the executor's host fallback fires, exactly like
    single-device."""
    from pinot_tpu.ops import radix_groupby as radix_ops

    # per-shard table length is min(shard_rows, sorted_k) — a SHARD-shape
    # quantity. The merged table must hold every gathered entry (D*K), not
    # one shard's length: merged distinct can legitimately exceed any
    # single shard's table. numGroupsLimit semantics stay host-side, via
    # the executor's n_groups_total check against sorted_k.
    # scalar observability leaves ride the ordinary psum combine, not the
    # keyed table merge (they are per-shard counts, not table columns);
    # the list is the SHARED ops/device_reduce.py STAT_KEYS contract plus
    # skeys (consumed by the key merge itself)
    from pinot_tpu.ops.device_reduce import STAT_KEYS

    stat_keys = STAT_KEYS | {"skeys"}
    K = outs["skeys"].shape[-1]
    reds, cols = {}, {}
    for k, v in outs.items():
        if k in stat_keys:
            continue
        reds[k] = "min" if k.endswith("_min") \
            else "max" if k.endswith("_max") else "sum"
        cols[k] = jax.lax.all_gather(v, SEG_AXIS)
    skeys = jax.lax.all_gather(outs["skeys"], SEG_AXIS)
    merged, fk, empty, merged_distinct = radix_ops.merge_tables(
        skeys, cols, reds, skeys.shape[0] * K)
    shard_total = outs["n_groups_total"]
    overflow_total = jax.lax.pmax(
        jnp.where(shard_total > K, shard_total, 0), SEG_AXIS)
    combined = {
        "doc_count": jax.lax.psum(outs["doc_count"], SEG_AXIS),
        "seg_matched": outs["seg_matched"],
        "skeys": jnp.where(empty, radix_ops.INT64_SENTINEL, fk),
        "n_groups_total": jnp.maximum(merged_distinct, overflow_total),
    }
    for k in ("n_alive", "rows_filter", "blocks_total", "blocks_scanned"):
        if k in outs:
            combined[k] = jax.lax.psum(outs[k], SEG_AXIS)
    combined.update(merged)
    return combined


def _combine_outs(outs: dict) -> dict:
    """Combine a pipeline's outputs across shards. Most keys combine
    independently (_combine_out); the FIRSTWITHTIME/LASTWITHTIME value
    planes (``*_vtmin`` / ``*_vtmax``) combine as an argmin/argmax-by-time
    PAIR with their ``*_tmin`` / ``*_tmax`` sibling: resolve the global
    winning time with pmin/pmax, mask each shard's values to rows that
    carry it, then pmax the values — associative, deterministic (ties on
    time break toward the largest value, matching
    engine/aggspec.py FirstLastWithTimeSpec). The sorted/high-cardinality
    regime's keyed group tables take the key-aligned merge instead
    (_combine_sorted_table)."""
    if "skeys" in outs:
        return _combine_sorted_table(outs)
    combined = {}
    for k, v in outs.items():
        if k.endswith("_vtmin") or k.endswith("_vtmax"):
            tkey = k[:-6] + ("_tmin" if k.endswith("_vtmin") else "_tmax")
            t = outs[tkey]
            tg = jax.lax.pmin(t, SEG_AXIS) if k.endswith("_vtmin") \
                else jax.lax.pmax(t, SEG_AXIS)
            combined[k] = jax.lax.pmax(
                jnp.where(t == tg, v, -jnp.inf), SEG_AXIS)
        else:
            combined[k] = _combine_out(k, v)
    return combined


def shard_pipeline(pipeline_fn, mesh: Mesh, cohort: bool = False, post=None):
    """Wrap a device pipeline (engine/device.py build_pipeline inner fn) in
    shard_map over the segment axis.

    Input convention: any param/column whose leading dim == n_segments is
    sharded; everything else (literals, (K,) id lists) is replicated.
    Output convention: 'seg_matched' is gathered back to (S,); all other
    outputs are combined to replicated accumulators via psum/pmin/pmax.

    ``cohort=True``: params carry a LEADING cohort axis — a stack of
    same-template queries coalesced into one launch (engine/inflight.py).
    The per-shard pipeline AND the cross-shard combine are vmapped over
    that axis inside ONE shard_map, so a whole cohort costs one dispatch
    and its collectives batch over ICI. ``post`` (cohort only): a
    replicated post-combine transform ``post(outs, params)`` (device
    sketch finalize and/or the device-reduce trim, which reads its
    ``tr_k`` bound from the member's params) applied per member INSIDE
    the vmap — its per-member semantics (regs → est, table → top-K)
    must see unbatched shapes.
    """

    def one(cols, n_docs, p):
        outs = _combine_outs(pipeline_fn(cols, n_docs, p))
        return post(outs, p) if post is not None else outs

    def sharded(cols, n_docs, params):
        if cohort:
            return jax.vmap(lambda p: one(cols, n_docs, p))(params)
        return one(cols, n_docs, params)

    # global-id design: every param (literals, (C,) LUTs, the per-batch
    # "fo::" frame-of-reference offsets from width planning) is batch-wide
    # and replicated; only columns, n_docs, and "ps"-prefixed per-segment
    # params (e.g. the Level-1 ``ps_alive`` vector) carry the segment
    # axis. Narrow/sub-byte column planes shard like any column — the
    # (S, L//f) packed byte axis is position 1 either way. Cohort stacks
    # add a leading member axis, so the segment axis shifts to position 1
    # there.
    def param_spec(key: str, x) -> P:
        if key.startswith("ps"):
            if cohort:
                return P(None, SEG_AXIS, *([None] * (x.ndim - 2)))
            return P(SEG_AXIS, *([None] * (x.ndim - 1)))
        return P()

    def wrapper(cols, n_docs, params):
        in_specs = (
            {k: P(SEG_AXIS, None) for k in cols},
            P(SEG_AXIS),
            {k: param_spec(k, v) for k, v in params.items()},
        )
        # output KEYS (and ranks) come from the collective-free parts:
        # pipeline_fn (+ post, which only renames sketch leaves) — the
        # combine itself preserves the key set, so eval_shape never has to
        # trace an unbound collective
        shape_params = params
        if cohort:
            shape_params = {
                k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
                for k, v in params.items()
            }
        keys_fn = pipeline_fn if post is None else (
            lambda c, nd, p: post(pipeline_fn(c, nd, p), p))
        outs_shape = jax.eval_shape(keys_fn, cols, n_docs, shape_params)

        def out_spec(k: str) -> P:
            if k != "seg_matched":
                return P()
            # per-shard seg_matched is (S_shard,) — or (N, S_shard) with a
            # leading cohort axis — and reassembles along the segment dim
            return P(None, SEG_AXIS) if cohort else P(SEG_AXIS)

        out_specs = {k: out_spec(k) for k in outs_shape}
        fn = _shard_map(
            sharded, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **_SM_KW,
        )
        return fn(cols, n_docs, params)

    return jax.jit(wrapper)


def pad_to_multiple(cols: dict, n_docs, params: dict, multiple: int):
    """Pad the segment axis so it divides the mesh: extra segments carry
    n_docs = 0, so every kernel masks them out."""
    S = int(n_docs.shape[0])
    rem = S % multiple
    if rem == 0:
        return cols, n_docs, params, S
    pad = multiple - rem

    def pad_arr(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] == S:
            widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths)
        return x

    cols = {k: pad_arr(v) for k, v in cols.items()}
    params = {
        k: (pad_arr(v) if k.startswith("ps") else v) for k, v in params.items()
    }
    n_docs = jnp.pad(n_docs, (0, pad))
    return cols, n_docs, params, S + pad
