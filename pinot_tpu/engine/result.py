"""Result containers: the DataTable / BrokerResponse analogs.

``IntermediateResult`` is the mergeable per-executor result (reference:
DataTable, pinot-core/.../common/datatable/) in *value space* — group keys
are actual values, aggregation states are canonical mergeable partials
(engine/aggspec.py). ``ResultTable`` is the final broker response payload
(reference: BrokerResponseNative's resultTable).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class ExecutionStats:
    """Per-query execution statistics (ExecutionStatistics.java analog)."""

    num_docs_scanned: int = 0
    num_entries_scanned_in_filter: int = 0
    num_entries_scanned_post_filter: int = 0
    num_segments_queried: int = 0
    num_segments_processed: int = 0
    num_segments_matched: int = 0
    num_segments_pruned: int = 0
    # zone-map blocks the device block-skip path never gathered
    # (engine/device.py; 0 when the dense path ran or pruning was off)
    num_blocks_pruned: int = 0
    # cold-tier segments (ISSUE 12, server/tiering.py) this execution
    # routed but could not scan: their planes live only in the deep
    # store, the touch scheduled an async hydration, and the result is
    # an honest in-flight partial (numSegmentsCold in responses)
    num_segments_cold: int = 0
    total_docs: int = 0
    time_used_ms: float = 0.0
    # per-query resource accounting (reference: DataTable V3 metadata
    # threadCpuTimeNs + scheduler wait) — filled by the server's scheduler
    thread_cpu_time_ns: int = 0
    scheduler_wait_ms: float = 0.0
    # groups dropped by numGroupsLimit: the result is plan-dependent
    # partial (reference numGroupsLimitReached response metadata)
    num_groups_limit_reached: bool = False
    # the device partials cache served this execution (engine/device.py):
    # no gather/dispatch/kernel ran — the fetch re-read a cached packed
    # buffer. Surfaces as partialsCacheHit in responses + the query log.
    partials_cache_hit: bool = False
    # load signal piggybacked on every server partial (ISSUE 10): the
    # answering server's scheduler pressure() and in-flight query depth
    # at fetch time. -1 = not a server partial. The broker reads these
    # PER INSTANCE before the reduce merges stats (max survives).
    server_pressure: int = -1
    server_inflight: int = -1
    # the answering server's freshness epoch for the queried table
    # (common/freshness.py): the broker result cache's staleness signal
    table_epoch: int = -1
    # kernel roofline accounting (ISSUE 11): modeled HBM bytes the device
    # pipeline moved (ColPlan-width column planes scaled by the block-skip
    # gather ratio, plus the trimmed fetch buffer) and the measured
    # kernel/link wall — achieved GB/s = bytes / kernel time, computed at
    # export against the per-process HBM peak (ops/roofline.py). Summed
    # across partials on merge; per-flight detail rides
    # IntermediateResult.roofline.
    device_bytes_moved: int = 0
    device_kernel_ms: float = 0.0
    device_link_ms: float = 0.0
    # distributed stage-2 exchange accounting (ISSUE 16,
    # query2/exchange.py): partitions/bytes this worker SHIPPED to peers
    # (self-offers to its own mailbox don't count), payloads its mailbox
    # spilled to the warm tier's spill dir, joined rows its stage-2
    # partials aggregated, and per-alias stage-1 leaf row counts. All
    # sum-merged; the broker surfaces them as numPartitionsShipped /
    # exchangeBytes / exchangeSpillCount response counters.
    exchange_partitions_shipped: int = 0
    exchange_bytes_shipped: int = 0
    exchange_spill_count: int = 0
    stage2_rows: int = 0
    leaf_rows: dict = dataclasses.field(default_factory=dict)
    # plan-advisor decision stamps (ISSUE 17, engine/advisor.py): one
    # "ADVISOR(<decision>: measured=X default=Y)" line per measurement-
    # driven override this execution ran with. Merged with order-
    # preserving dedup (partials of one query repeat the same stamps);
    # surfaced as advisorDecisions in responses, the query log, and
    # EXPLAIN ANALYZE.
    advisor_decisions: list = dataclasses.field(default_factory=list)

    def merge(self, other: "ExecutionStats") -> None:
        self.num_docs_scanned += other.num_docs_scanned
        self.num_entries_scanned_in_filter += other.num_entries_scanned_in_filter
        self.num_entries_scanned_post_filter += other.num_entries_scanned_post_filter
        self.num_segments_queried += other.num_segments_queried
        self.num_segments_processed += other.num_segments_processed
        self.num_segments_matched += other.num_segments_matched
        self.num_segments_pruned += other.num_segments_pruned
        self.num_blocks_pruned += other.num_blocks_pruned
        self.num_segments_cold += other.num_segments_cold
        self.total_docs += other.total_docs
        self.thread_cpu_time_ns += other.thread_cpu_time_ns
        self.scheduler_wait_ms += other.scheduler_wait_ms
        self.num_groups_limit_reached |= other.num_groups_limit_reached
        self.partials_cache_hit |= other.partials_cache_hit
        self.server_pressure = max(self.server_pressure,
                                   other.server_pressure)
        self.server_inflight = max(self.server_inflight,
                                   other.server_inflight)
        self.table_epoch = max(self.table_epoch, other.table_epoch)
        self.device_bytes_moved += other.device_bytes_moved
        self.device_kernel_ms += other.device_kernel_ms
        self.device_link_ms += other.device_link_ms
        self.exchange_partitions_shipped += other.exchange_partitions_shipped
        self.exchange_bytes_shipped += other.exchange_bytes_shipped
        self.exchange_spill_count += other.exchange_spill_count
        self.stage2_rows += other.stage2_rows
        for alias, rows in (other.leaf_rows or {}).items():
            self.leaf_rows[alias] = self.leaf_rows.get(alias, 0) + int(rows)
        for line in (other.advisor_decisions or []):
            if line not in self.advisor_decisions:
                self.advisor_decisions.append(line)


@dataclasses.dataclass
class IntermediateResult:
    """Mergeable executor output. Exactly one of the shapes is populated:

    - aggregation:      ``agg_partials`` (list, one per aggregation)
    - group-by:         ``group_keys`` (tuple of value arrays, one per
                        group-by expr) + ``agg_partials`` (per-group arrays)
    - selection:        ``rows`` (dict col->np array of selected docs)
    - distinct:         ``group_keys`` only
    """

    shape: str  # "aggregation" | "group_by" | "selection" | "distinct"
    agg_partials: Optional[list] = None
    group_keys: Optional[tuple] = None
    rows: Optional[dict] = None
    stats: ExecutionStats = dataclasses.field(default_factory=ExecutionStats)
    trace: Optional[list] = None  # phase spans when SET trace = true
    # per-flight roofline records (ISSUE 11): one dict per device launch
    # this partial folded in ({kernel, bytesMoved, kernelMs, linkMs,
    # gbps, peakGbps, pctOfPeak, cacheHit}) — concatenated across
    # partials, shipped in DataTable metadata like ``trace``
    roofline: Optional[list] = None


@dataclasses.dataclass
class ResultTable:
    column_names: list
    column_types: list  # DataType names (strings)
    rows: list  # list of tuples of python values

    def to_json(self) -> dict:
        return {
            "resultTable": {
                "dataSchema": {
                    "columnNames": self.column_names,
                    "columnDataTypes": self.column_types,
                },
                "rows": [list(r) for r in self.rows],
            }
        }


def py_value(v):
    """numpy scalar → python value for the JSON layer. MV cells (per-doc
    arrays) become JSON lists, the reference's MV response shape."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v
