"""Star-tree query substitution + metadata-only aggregation fast paths.

Reference: AggregationPlanNode.java:186-210 — before planning a scan, try
(a) the metadata-only path (NonScanBasedAggregationOperator, :234-259:
COUNT(*) from segment doc count, MIN/MAX from column metadata) and (b) the
star-tree substitution (StarTreeUtils.isFitForStarTree → swap the plan onto
pre-aggregated docs).

Here (b) re-enters the NORMAL engine over the materialized aggregate segment
(storage/startree.py) with a rewritten query — sum(x) → sum(sum__x),
count(*) → sum(count__star) — then converts the resulting partials back to
the original aggregation's canonical state layout so reduce/merge cannot
tell the difference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from pinot_tpu.engine.result import IntermediateResult
from pinot_tpu.query.context import Expression, QueryContext
from pinot_tpu.storage.startree import SEP, load_star_trees, pair_column, parse_pair

_REWRITABLE = {"count", "sum", "min", "max", "avg", "minmaxrange",
               "distinctcounthll", "percentiletdigest", "percentile",
               "percentileest", "distinctcount", "distinctcountbitmap",
               "sumprecision"}


def _q2_expr(fn: str, col: str, meta: dict) -> Expression:
    """The cube-side aggregation expression for one mapping entry."""
    if fn == "hllmerge":
        # the state column's plane width must be decoded with the SAME m it
        # was built with; carried as a literal arg like HLL's log2m
        return Expression.function(
            "hllmerge", Expression.identifier(col),
            Expression.literal(int(meta["hll_log2m"])),
        )
    if fn == "tdigestmerge":
        # p is irrelevant at merge time (the ORIGINAL agg finalizes);
        # compression governs re-merge compaction. The state column's PAIR
        # FUNCTION (exact match on the name half, not a prefix) identifies
        # which pair built the digests, hence which compression.
        pair_fn = col.split(SEP, 1)[0]
        comp = meta["tdigest_compression"] if pair_fn == "percentiletdigest" \
            else meta["percentileest_compression"]
        return Expression.function(
            "tdigestmerge", Expression.identifier(col),
            Expression.literal(0.5),
            Expression.literal(float(comp)),
        )
    return Expression.function(fn, Expression.identifier(col))


@dataclasses.dataclass
class StarTreePlan:
    q2: QueryContext
    st_segment: object
    # per original agg: list of (q2-agg expression, role) where role names the
    # canonical partial field the q2 partial feeds
    mapping: list
    meta: dict


def _available_pairs(meta: dict) -> set:
    return {tuple(parse_pair(p)) for p in meta["function_column_pairs"]}


def _has_null_predicate(f) -> bool:
    from pinot_tpu.query.context import FilterNodeType, PredicateType

    if f.type is FilterNodeType.PREDICATE:
        return f.predicate.type in (PredicateType.IS_NULL,
                                    PredicateType.IS_NOT_NULL)
    return any(_has_null_predicate(c) for c in f.children or ())


def fit(q: QueryContext, meta: dict) -> Optional[list]:
    """StarTreeUtils.isFitForStarTree analog. Returns the per-agg rewrite
    mapping, or None."""
    if q.distinct or not q.aggregations():
        return None
    if dict(q.options).get("useStarTree") is False:
        return None
    dims = set(meta["dimensions_split_order"])
    if q.filter is not None:
        if not q.filter.columns() <= dims:
            return None
        # null vectors don't survive into the pre-aggregated tree (its rows
        # carry substituted default values), so IS_NULL must scan
        if _has_null_predicate(q.filter):
            return None
    for g in q.group_by:
        if not g.is_identifier or g.name not in dims:
            return None
    pairs = _available_pairs(meta)
    mapping = []
    for a in q.aggregations():
        name = a.name
        if name not in _REWRITABLE:
            return None
        if name == "count":
            if ("count", "*") not in pairs:
                return None
            mapping.append([("sum", pair_column("count", "*"), "count")])
            continue
        arg = a.args[0]
        if not arg.is_identifier:
            return None
        col = arg.name
        if name == "distinctcounthll":
            # sketch pair: cube rows carry register planes, re-merged by
            # HLLMERGE — only if the plane resolution matches the query's
            from pinot_tpu.engine.aggspec import make_spec

            if ("distinctcounthll", col) not in pairs:
                return None
            if meta.get("hll_log2m") != make_spec(a).log2m:
                return None
            mapping.append(
                [("hllmerge", pair_column("distinctcounthll", col), "state")])
            continue
        if name in ("percentiletdigest", "percentile", "percentileest"):
            # digest pairs: cube rows carry serialized t-digests, re-merged
            # by TDIGESTMERGE — only when a pair's digest compression
            # matches the query's (a mismatch would silently change the
            # error bound). All three names share the digest algebra; the
            # PERCENTILETDIGEST pair serves compression-100-family queries
            # and the PERCENTILEEST pair the PERCENTILE/EST default.
            from pinot_tpu.engine.aggspec import make_spec

            want = make_spec(a).compression
            if ("percentiletdigest", col) in pairs \
                    and meta.get("tdigest_compression") == want:
                src = "percentiletdigest"
            elif ("percentileest", col) in pairs \
                    and meta.get("percentileest_compression") == want:
                src = "percentileest"
            else:
                return None
            mapping.append(
                [("tdigestmerge", pair_column(src, col), "state")])
            continue
        if name in ("distinctcount", "distinctcountbitmap"):
            # exact distinct pair: serialized value sets per cube row,
            # re-unioned by BITMAPMERGE (DistinctCountBitmapValueAggregator)
            if ("distinctcountbitmap", col) not in pairs:
                return None
            mapping.append(
                [("bitmapmerge", pair_column("distinctcountbitmap", col),
                  "state")])
            continue
        if name == "sumprecision":
            if ("sumprecision", col) not in pairs:
                return None
            mapping.append(
                [("sumprecisionmerge", pair_column("sumprecision", col),
                  "state")])
            continue
        need = {
            "sum": [("sum", col, "sum")],
            "min": [("min", col, "min")],
            "max": [("max", col, "max")],
            "avg": [("sum", col, "sum"), ("count", "*", "count")],
            "minmaxrange": [("min", col, "min"), ("max", col, "max")],
        }[name]
        for fn, c, _role in need:
            if (fn, c) not in pairs:
                return None
        mapping.append(
            [
                (("sum" if fn == "count" else fn), pair_column(fn, c), role)
                for fn, c, role in need
            ]
        )
    return mapping


def build_plan(q: QueryContext, meta: dict, st_segment) -> Optional[StarTreePlan]:
    mapping = fit(q, meta)
    if mapping is None:
        return None
    # dedup q2 aggregations, preserving order
    q2_aggs: dict = {}
    for entries in mapping:
        for fn, col, _role in entries:
            q2_aggs.setdefault(_q2_expr(fn, col, meta))
    q2 = dataclasses.replace(
        q,
        select_expressions=tuple(q2_aggs),
        aliases=tuple([None] * len(q2_aggs)),
        having=None,
        order_by=(),
    )
    return StarTreePlan(q2=q2, st_segment=st_segment, mapping=mapping,
                        meta=meta)


def convert(result: IntermediateResult, plan: StarTreePlan, q: QueryContext,
            parent_total_docs: int) -> IntermediateResult:
    """q2 partials → the original aggregations' canonical partial layout."""
    q2_aggs = list(plan.q2.aggregations())
    index = {a: i for i, a in enumerate(q2_aggs)}
    out_partials = []
    for orig, entries in zip(q.aggregations(), plan.mapping):
        partial: dict = {}
        for fn, col, role in entries:
            p2 = result.agg_partials[index[_q2_expr(fn, col, plan.meta)]]
            if role == "count":
                partial["count"] = np.rint(p2["sum"]).astype(np.int64)
            elif role == "state":
                # sketch states pass through verbatim (regs — or est when
                # the cube execution finalized on device)
                partial.update(p2)
            else:
                partial[role] = p2[role if role in p2 else "sum"]
        out_partials.append(partial)
    stats = result.stats
    stats.total_docs = parent_total_docs
    return IntermediateResult(
        result.shape,
        agg_partials=out_partials,
        group_keys=result.group_keys,
        stats=stats,
    )


def _trees_for(segment) -> list:
    if getattr(segment, "is_mutable", False):
        return []
    # Upsert guard: the star-tree was pre-aggregated over ALL rows at seal
    # time; a validDocIds mask invalidates those partials (the reference
    # forbids star-tree on upsert tables — TableConfigUtils validation).
    if getattr(segment, "valid_docs_mask", None) is not None:
        return []
    trees = getattr(segment, "_star_trees_cache", None)
    if trees is None:
        try:
            trees = load_star_trees(segment)
        except Exception:
            trees = []
        segment._star_trees_cache = trees
    return trees


def fitting_tree(q: QueryContext, segment):
    """(meta_signature, meta, st_segment) for the first fitting star-tree."""
    for meta, st_seg in _trees_for(segment):
        if fit(q, meta) is not None:
            sig = (
                tuple(meta["dimensions_split_order"]),
                tuple(sorted(meta["function_column_pairs"])),
            )
            return sig, meta, st_seg
    return None


def execute_star_tree_group(engine, q: QueryContext, meta: dict, st_segments: list,
                            parent_total_docs: int,
                            terminal: bool = False) -> IntermediateResult:
    """One batched execution over MANY segments' star-trees sharing a
    signature — a single device launch replaces per-segment tree traversals
    (and per-segment kernel dispatches, which dominate when the pre-agg data
    is tiny). ``terminal``: no upstream merge — sketch re-merges may
    finalize on device (convert passes their 'est' partials through)."""
    plan = build_plan(q, meta, st_segments[0])
    # trim_ok=False: the outer finalize runs under q, not plan.q2 — an
    # in-kernel trim keyed to q2's order/limit could drop cube rows the
    # parent query's reduce still needs
    r2 = engine.execute_segments(plan.q2, st_segments, terminal=terminal,
                                 trim_ok=False)
    return convert(r2, plan, q, parent_total_docs)


# ---------------------------------------------------------------------------
# metadata-only aggregation (NonScanBasedAggregationOperator analog)
# ---------------------------------------------------------------------------


def try_metadata_only(q: QueryContext, segment) -> Optional[IntermediateResult]:
    """COUNT(*)/MIN/MAX with no filter and no group-by answer straight from
    segment metadata — zero scan (AggregationPlanNode.java:234-259)."""
    from pinot_tpu.engine.result import ExecutionStats

    if q.filter is not None or q.group_by or q.distinct:
        return None
    aggs = q.aggregations()
    if not aggs:
        return None
    if getattr(segment, "is_mutable", False) or \
            getattr(segment, "valid_docs_mask", None) is not None:
        return None
    partials = []
    for a in aggs:
        if a.name == "count":
            partials.append({"count": np.array([segment.n_docs], dtype=np.int64)})
            continue
        if a.name not in ("min", "max") or not a.args or not a.args[0].is_identifier:
            return None
        col = a.args[0].name
        if col not in segment.metadata.columns:
            return None
        meta = segment.column_metadata(col)
        v = meta.min_value if a.name == "min" else meta.max_value
        if v is None or isinstance(v, str) or segment.n_docs == 0:
            return None
        partials.append({a.name: np.array([float(v)])})
    stats = ExecutionStats(
        num_docs_scanned=segment.n_docs,  # reference counts docs "matched"
        num_segments_processed=1,
        num_segments_queried=1,
        num_segments_matched=1 if segment.n_docs else 0,
        total_docs=segment.n_docs,
    )
    return IntermediateResult("aggregation", agg_partials=partials, stats=stats)
