"""DataTable wire format: IntermediateResult ↔ bytes.

Equivalent of the reference's versioned binary DataTable
(pinot-core/.../common/datatable/DataTableImplV3.java + ObjectSerDeUtils for
sketch payloads): the server ships mergeable partials to the broker, which
reduces them in value space. Layout:

    [4B magic "PDT1"] [4B header length] [header JSON] [npz blob]

- header: shape, stats, names/dtypes of every array, and per-array role
- arrays: one .npy each inside an uncompressed zip (np.savez) — object-typed
  states (distinct sets, percentile lists, mode maps) are flattened into
  (values, offsets) pairs, the way ObjectSerDeUtils linearizes sketches.
  No pickle crosses the wire.
"""

from __future__ import annotations

import dataclasses
import io
import json

import numpy as np

from pinot_tpu.engine.result import ExecutionStats, IntermediateResult

MAGIC = b"PDT1"
ERROR_MAGIC = b"PERR"


class ServerQueryError(Exception):
    """Query-level error raised server-side and shipped in-band (the
    reference's processing-exception DataTable metadata)."""


class NoSegmentsHosted(ServerQueryError):
    """The server holds none of the requested segments (benign routing/sync
    race; the broker skips this partial without marking a failure)."""


class QueryTimeoutError(ServerQueryError):
    """The server aborted because the query's propagated deadline expired
    (errorCode 250 shape). The server is HEALTHY — the broker reports the
    timeout in-band as a partial, without poisoning its failure
    detector."""


class ServerShuttingDown(ServerQueryError):
    """The server is draining for shutdown and rejected the submit before
    execution. RETRIABLE: the broker should re-send the segment list to a
    replica — the data was never touched."""


def encode_error(kind: str, message: str) -> bytes:
    import json as _json

    payload = _json.dumps({"kind": kind, "message": message}).encode("utf-8")
    return ERROR_MAGIC + payload


# ---------------------------------------------------------------------------
# object-state flattening (sets / lists / dicts / (val,time) pairs)
# ---------------------------------------------------------------------------


def _flatten_obj(name: str, arr: np.ndarray, arrays: dict, meta: dict) -> None:
    """Object array of sets/lists/dicts → (concat values, offsets)."""
    first = next((x for x in arr if x is not None), None)
    if isinstance(first, (set, list, np.ndarray)) or first is None:
        # ndarray rows are MV selection cells; they round-trip as lists
        kind = "set" if isinstance(first, set) else "list"
        offsets = np.zeros(len(arr) + 1, dtype=np.int64)
        chunks = []
        for i, x in enumerate(arr):
            if isinstance(x, set):
                vals = sorted(x)
            elif x is None:
                vals = []
            else:
                vals = list(x)
            chunks.append(np.asarray(vals))
            offsets[i + 1] = offsets[i] + len(vals)
        concat = (
            np.concatenate([c for c in chunks if len(c)])
            if offsets[-1] > 0
            else np.empty(0)
        )
        arrays[f"{name}__values"] = concat
        arrays[f"{name}__offsets"] = offsets
        meta[name] = {"obj": kind}
    elif isinstance(first, dict):
        offsets = np.zeros(len(arr) + 1, dtype=np.int64)
        keys, counts = [], []
        for i, d in enumerate(arr):
            items = sorted((d or {}).items(), key=lambda kv: repr(kv[0]))
            keys.extend(k for k, _ in items)
            counts.extend(c for _, c in items)
            offsets[i + 1] = offsets[i] + len(items)
        arrays[f"{name}__values"] = np.asarray(keys) if keys else np.empty(0)
        arrays[f"{name}__counts"] = np.asarray(counts, dtype=np.int64)
        arrays[f"{name}__offsets"] = offsets
        meta[name] = {"obj": "dict"}
    elif isinstance(first, (int, float, _Decimal(),
                            np.integer, np.floating)):
        # exact scalars (SUMPRECISION; FIRSTWITHTIME/LASTWITHTIME's exact
        # int64 value plane): arbitrary-precision ints/Decimals ride as
        # decimal strings. A per-element type flag (0=None, 1=int,
        # 2=float, 3=Decimal) keeps empty slots and MIXED planes exact —
        # a host exact-int accumulator that merged a device float64
        # partial (FirstLast over host + device segments) carries both
        # ints and floats in one object array.
        flags = np.zeros(len(arr), dtype=np.int8)
        strs = []
        for i, x in enumerate(arr):
            if x is None:
                strs.append("0")
            elif isinstance(x, (float, np.floating)):
                flags[i] = 2
                strs.append(repr(float(x)))
            elif isinstance(x, (int, np.integer)):
                flags[i] = 1
                strs.append(str(int(x)))
            else:
                flags[i] = 3
                strs.append(str(x))
        arrays[f"{name}__values"] = np.asarray(strs, dtype=np.str_)
        arrays[f"{name}__flags"] = flags
        meta[name] = {"obj": "exact_scalar"}
    elif isinstance(first, str):
        # scalar strings with empty slots (FIRSTWITHTIME/LASTWITHTIME over
        # a STRING column): one value per group + a presence flag so a
        # genuinely-empty slot (None) survives the round trip distinct
        # from the empty string
        arrays[f"{name}__values"] = np.asarray(
            [x if x is not None else "" for x in arr], dtype=np.str_)
        arrays[f"{name}__flags"] = np.asarray(
            [x is not None for x in arr], dtype=np.int8)
        meta[name] = {"obj": "scalar_str"}
    elif isinstance(first, tuple) and len(first) == 2 and \
            first[0] in ("set", "hll"):
        # SmartHLL tagged union: flag per group + set entries or registers
        flags = np.zeros(len(arr), dtype=np.int8)
        offsets = np.zeros(len(arr) + 1, dtype=np.int64)
        chunks = []
        m = 0
        for kind, payload in arr:
            if kind == "hll":
                m = max(m, len(payload))
        regs = np.zeros((len(arr), m), dtype=np.int32)
        for i, (kind, payload) in enumerate(arr):
            if kind == "set":
                vals = sorted(payload, key=repr)
                chunks.append(np.asarray(vals) if vals else np.empty(0))
                offsets[i + 1] = offsets[i] + len(vals)
            else:
                flags[i] = 1
                offsets[i + 1] = offsets[i]
                regs[i, : len(payload)] = payload
        concat = (np.concatenate([c for c in chunks if len(c)])
                  if offsets[-1] > 0 else np.empty(0))
        arrays[f"{name}__values"] = concat
        arrays[f"{name}__offsets"] = offsets
        arrays[f"{name}__flags"] = flags
        arrays[f"{name}__regs"] = regs
        meta[name] = {"obj": "smart_hll"}
    else:
        raise TypeError(f"unsupported object state in partial: {type(first)}")


def _Decimal():
    import decimal

    return decimal.Decimal


def _unflatten_obj(name: str, spec: dict, arrays: dict) -> np.ndarray:
    if spec["obj"] == "exact_scalar":
        import decimal

        vals = arrays[f"{name}__values"]
        flags = arrays.get(f"{name}__flags")
        out = np.empty(len(vals), dtype=object)
        for i, s in enumerate(vals.tolist()):
            if flags is None:
                # legacy payload (no type flags): SUMPRECISION semantics
                out[i] = int(s) if "." not in s and "E" not in s.upper() \
                    else decimal.Decimal(s)
            elif flags[i] == 0:
                out[i] = None
            elif flags[i] == 1:
                out[i] = int(s)
            elif flags[i] == 2:
                out[i] = float(s)
            else:
                out[i] = decimal.Decimal(s)
        return out
    if spec["obj"] == "scalar_str":
        vals = arrays[f"{name}__values"]
        flags = arrays[f"{name}__flags"]
        out = np.empty(len(flags), dtype=object)
        for i, (s, f) in enumerate(zip(vals.tolist(), flags.tolist())):
            out[i] = s if f else None
        return out
    if spec["obj"] == "smart_hll":
        offsets = arrays[f"{name}__offsets"]
        flags = arrays[f"{name}__flags"]
        regs = arrays[f"{name}__regs"]
        vals = arrays[f"{name}__values"]
        n = len(flags)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if flags[i]:
                out[i] = ("hll", np.asarray(regs[i], dtype=np.int32))
            else:
                out[i] = ("set", set(vals[offsets[i]: offsets[i + 1]].tolist()))
        return out
    offsets = arrays[f"{name}__offsets"]
    n = len(offsets) - 1
    out = np.empty(n, dtype=object)
    if spec["obj"] in ("set", "list"):
        vals = arrays[f"{name}__values"]
        for i in range(n):
            chunk = vals[offsets[i] : offsets[i + 1]]
            out[i] = set(chunk.tolist()) if spec["obj"] == "set" else list(chunk.tolist())
    else:
        vals = arrays[f"{name}__values"]
        counts = arrays[f"{name}__counts"]
        for i in range(n):
            sl = slice(offsets[i], offsets[i + 1])
            out[i] = dict(zip(vals[sl].tolist(), counts[sl].tolist()))
    return out


# ---------------------------------------------------------------------------
# encode / decode
# ---------------------------------------------------------------------------


def encode(result: IntermediateResult) -> bytes:
    arrays: dict[str, np.ndarray] = {}
    meta: dict = {
        "shape": result.shape,
        "stats": dataclasses.asdict(result.stats),
        "objects": {},
        "partials": None,
        "n_keys": None,
        "trace": result.trace,
        # per-flight roofline records (ISSUE 11) ride like trace spans
        "roofline": result.roofline,
    }

    if result.group_keys is not None:
        meta["n_keys"] = len(result.group_keys)
        for i, k in enumerate(result.group_keys):
            arrays[f"key{i}"] = np.asarray(k)

    if result.agg_partials is not None:
        layout = []
        for pi, partial in enumerate(result.agg_partials):
            fields = []
            for fname, arr in partial.items():
                arr = np.asarray(arr)
                slot = f"agg{pi}__{fname}"
                if arr.dtype == object:
                    _flatten_obj(slot, arr, arrays, meta["objects"])
                else:
                    arrays[slot] = arr
                fields.append(fname)
            layout.append(fields)
        meta["partials"] = layout

    if result.rows is not None:
        meta["row_keys"] = [str(k) for k in result.rows]
        for k, v in result.rows.items():
            v = np.asarray(v)
            if v.dtype == object:  # MV selection column → (values, offsets)
                _flatten_obj(f"row__{k}", v, arrays, meta["objects"])
            else:
                arrays[f"row__{k}"] = v

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    header = json.dumps(meta).encode("utf-8")
    return MAGIC + len(header).to_bytes(4, "big") + header + buf.getvalue()


def decode(data: bytes) -> IntermediateResult:
    if data[:4] == ERROR_MAGIC:
        info = json.loads(data[4:].decode("utf-8"))
        if info.get("kind") == "no_segments":
            raise NoSegmentsHosted(info["message"])
        if info.get("kind") == "query_timeout":
            raise QueryTimeoutError(info["message"])
        if info.get("kind") == "server_shutting_down":
            raise ServerShuttingDown(info["message"])
        raise ServerQueryError(info["message"])
    if data[:4] != MAGIC:
        raise ValueError("bad DataTable magic")
    hlen = int.from_bytes(data[4:8], "big")
    meta = json.loads(data[8 : 8 + hlen].decode("utf-8"))
    npz = np.load(io.BytesIO(data[8 + hlen :]), allow_pickle=False)
    arrays = {k: npz[k] for k in npz.files}

    stats = ExecutionStats(**meta["stats"])

    group_keys = None
    if meta["n_keys"] is not None:
        group_keys = tuple(arrays[f"key{i}"] for i in range(meta["n_keys"]))

    agg_partials = None
    if meta["partials"] is not None:
        agg_partials = []
        for pi, fields in enumerate(meta["partials"]):
            partial = {}
            for fname in fields:
                slot = f"agg{pi}__{fname}"
                if slot in meta["objects"]:
                    partial[fname] = _unflatten_obj(slot, meta["objects"][slot], arrays)
                else:
                    partial[fname] = arrays[slot]
            agg_partials.append(partial)

    rows = None
    if "row_keys" in meta:
        rows = {}
        for k in meta["row_keys"]:
            # selection row keys are select-position ints or "__ob{j}" strings
            key = int(k) if k.lstrip("-").isdigit() else k
            slot = f"row__{k}"
            if slot in meta["objects"]:
                lists = _unflatten_obj(slot, meta["objects"][slot], arrays)
                for i in range(len(lists)):
                    lists[i] = np.asarray(lists[i])
                rows[key] = lists
            else:
                rows[key] = arrays[slot]

    return IntermediateResult(
        meta["shape"],
        agg_partials=agg_partials,
        group_keys=group_keys,
        rows=rows,
        stats=stats,
        trace=meta.get("trace"),
        roofline=meta.get("roofline"),
    )
