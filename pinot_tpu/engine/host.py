"""Host (numpy) query executor: the complete-coverage fallback path.

Architecturally this replaces the reference's per-segment operator chain
(filter → project → transform → aggregate, §3.1 of SURVEY.md) for query
shapes the device pipeline doesn't accelerate — the same role the reference's
scan-based operators play when no index fits. It is vectorized numpy over the
segment's mmap'd columns, not a row-at-a-time interpreter.

Dictionary-space predicate trick: for DICT columns, value predicates
(EQ/IN/RANGE/LIKE/REGEXP) evaluate once per *dictionary entry* and map through
the forward index — the reference's dictionary-based predicate evaluators
(pinot-core/.../operator/filter/predicate/) do exactly this.
"""

from __future__ import annotations

import re

import numpy as np

from pinot_tpu.engine import aggspec
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
    QueryContext,
)
from pinot_tpu.ops.transform import get_function
from pinot_tpu.storage.segment import Encoding, ImmutableSegment

DEFAULT_NUM_GROUPS_LIMIT = 100_000  # InstancePlanMakerImplV2 numGroupsLimit


def like_to_regex(pattern: str) -> str:
    """SQL LIKE → anchored regex (reference: RegexpPatternConverterUtils)."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


INVERTED_MAX_IDS = 64  # above this, slicing doc-lists loses to the LUT scan


def filter_operator_for(seg, p: Predicate) -> str:
    """Which filter operator a predicate gets on this segment — the
    index-priority ordering of FilterOperatorUtils.java:165-194 (sorted >
    inverted > scan), shared by the evaluator and EXPLAIN."""
    lhs = p.lhs
    if not (lhs.is_identifier and lhs.name in seg.metadata.columns):
        return "FULL_SCAN"
    meta = seg.column_metadata(lhs.name)
    if p.type is PredicateType.JSON_MATCH:
        return "JSON_INDEX" if getattr(meta, "has_json_index", False) \
            else "FULL_SCAN"
    if p.type is PredicateType.TEXT_MATCH:
        return "TEXT_INDEX" if getattr(meta, "has_text_index", False) \
            else "FULL_SCAN"
    if p.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
        return "FULL_SCAN"
    if meta.encoding != Encoding.DICT or not meta.single_value:
        if meta.encoding == Encoding.RAW and meta.single_value and \
                meta.has_range and p.type in (PredicateType.EQ, PredicateType.RANGE):
            return "RANGE_INDEX"
        return "FULL_SCAN"
    if meta.is_sorted and p.type in (
        PredicateType.EQ, PredicateType.IN, PredicateType.RANGE
    ):
        return "SORTED_INDEX"
    if meta.has_inverted and p.type in (
        PredicateType.EQ, PredicateType.IN, PredicateType.RANGE
    ):
        return "INVERTED_INDEX"
    return "FULL_SCAN"


class SegmentEvaluator:
    """Evaluates expressions / filters over one segment in value space."""

    def __init__(self, segment: ImmutableSegment, lookup_resolver=None):
        self.seg = segment
        # snapshot the doc count ONCE: mutable (consuming) segments grow
        # concurrently under a single-writer/multi-reader contract
        # (MutableSegmentImpl volatile counter analog)
        self.n = segment.n_docs
        self.lookup_resolver = lookup_resolver
        self._cache: dict = {}
        # entries actually read while filtering: index-served predicates add
        # 0, scans add n (reference: numEntriesScannedInFilter is 0 when the
        # filter is fully index-resolved)
        self.entries_scanned_in_filter = 0

    def n_docs(self) -> int:
        return self.n

    # ---- expression evaluation ------------------------------------------
    def eval(self, expr: Expression, doc_idx=None):
        """Evaluate an expression to a value array (over all docs, or the
        given doc indices)."""
        arr = self._eval_all(expr)
        if doc_idx is None:
            return arr
        if np.isscalar(arr) or arr.ndim == 0:
            return np.broadcast_to(arr, (len(doc_idx),))
        return arr[doc_idx]

    def _eval_all(self, expr: Expression):
        key = expr
        if key in self._cache:
            return self._cache[key]
        out = self._eval_uncached(expr)
        self._cache[key] = out
        return out

    def _eval_uncached(self, expr: Expression):
        if expr.is_literal:
            return np.asarray(expr.value)
        if expr.is_identifier:
            if expr.name.startswith("$"):
                return self._virtual_column(expr.name)
            if expr.name not in self.seg.metadata.columns:
                evolved = self._evolved_default(expr.name)
                if evolved is not None:
                    return evolved
            return np.asarray(self.seg.values(expr.name))[: self.n]
        if expr.name == "lookup":
            return self._lookup(expr)
        fn = get_function(expr.name)
        if expr.name == "cast":
            arg = self._eval_all(expr.args[0])
            return fn.np_fn(arg, expr.args[1].value)
        args = [self._eval_all(a) for a in expr.args]
        return fn.np_fn(*args)

    def _lookup(self, expr: Expression) -> np.ndarray:
        """LOOKUP('dimTable', 'valueCol', 'pkCol', keyExpr) — per-row join
        against a replicated dimension table (LookupTransformFunction
        analog; misses yield the value column's type default)."""
        if len(expr.args) != 4:
            raise ValueError(
                "LOOKUP takes (dimTable, valueColumn, pkColumn, keyExpr)")
        if self.lookup_resolver is None:
            raise ValueError("LOOKUP needs an engine with dimension tables")
        names = []
        for a in expr.args[:3]:
            if not (a.is_literal and isinstance(a.value, str)):
                raise ValueError("LOOKUP's first three args are string literals")
            names.append(a.value)
        dim_table, value_col, pk_col = names
        mapping, default = self.lookup_resolver(dim_table, value_col, pk_col)
        keys = np.asarray(self.eval(expr.args[3]))
        if keys.ndim == 0:
            # literal key: scalar result, broadcast downstream like other
            # literal expressions
            return np.asarray(mapping.get(keys.item(), default))
        out = [mapping.get(k, default) for k in keys.tolist()]
        return np.asarray(out)

    def _evolved_spec(self, name: str):
        """FieldSpec for a schema-evolved column this segment predates
        (present in the attached table schema, absent from the segment),
        or None. Cheap membership check — no allocation."""
        if name in self.seg.metadata.columns:
            return None
        schema = getattr(self.seg, "table_schema", None)
        if schema is None:
            return None
        return getattr(schema, "fields", {}).get(name)

    def _evolved_default(self, name: str):
        """Default-filled column for a schema-evolved column (the reference
        synthesizes default null values for columns added after a segment
        was built, post reload), or None."""
        spec = self._evolved_spec(name)
        if spec is None:
            return None
        if not spec.single_value:
            out = np.empty(self.n, dtype=object)
            for i in range(self.n):
                out[i] = np.empty(0, dtype=spec.data_type.np_dtype)
            return out
        return np.full(self.n, spec.null_value())

    def is_mv_column(self, name: str) -> bool:
        """MV-ness of a column, consulting the evolved schema for columns
        the segment predates."""
        if name in self.seg.metadata.columns:
            return not self.seg.column_metadata(name).single_value
        spec = self._evolved_spec(name)
        return spec is not None and not spec.single_value

    def _virtual_column(self, name: str) -> np.ndarray:
        """Built-in virtual columns (segment/virtualcolumn/ analog:
        DocIdVirtualColumnProvider etc.) — synthesized, never stored."""
        if name == "$docId":
            return np.arange(self.n, dtype=np.int64)
        if name == "$segmentName":
            return np.full(self.n, str(getattr(self.seg, "name", "")))
        if name == "$hostName":
            host = getattr(self.seg, "host_name", None)
            if host is None:
                import socket

                host = socket.gethostname()
            return np.full(self.n, str(host))
        raise KeyError(f"unknown virtual column {name!r}")

    # ---- filter evaluation ----------------------------------------------
    def filter_mask(self, f: FilterNode) -> np.ndarray:
        n = self.n
        if f is None:
            return np.ones(n, dtype=bool)
        t = f.type
        if t is FilterNodeType.CONSTANT_TRUE:
            return np.ones(n, dtype=bool)
        if t is FilterNodeType.CONSTANT_FALSE:
            return np.zeros(n, dtype=bool)
        if t is FilterNodeType.AND:
            m = self.filter_mask(f.children[0])
            for c in f.children[1:]:
                m = m & self.filter_mask(c)
            return m
        if t is FilterNodeType.OR:
            m = self.filter_mask(f.children[0])
            for c in f.children[1:]:
                m = m | self.filter_mask(c)
            return m
        if t is FilterNodeType.NOT:
            return ~self.filter_mask(f.children[0])
        return self.predicate_mask(f.predicate)

    # ---- multi-value access ---------------------------------------------
    def mv_parts(self, col: str):
        """(flat, lens, dictionary_or_None) snapshot for an MV column —
        ``flat`` is dict ids when a dictionary exists, else raw values.
        The vectorized MV read path (FixedBitMVForwardIndexReader analog)."""
        seg = self.seg
        spec = self._evolved_spec(col)
        if spec is not None:
            # schema-evolved MV column: every doc has zero entries
            return (np.empty(0, dtype=spec.data_type.np_dtype),
                    np.zeros(self.n, dtype=np.int64), None)
        meta = seg.column_metadata(col)
        if hasattr(seg, "mv_offsets") and not getattr(seg, "is_mutable", False):
            off = np.asarray(seg.mv_offsets(col))[: self.n + 1]
            flat = np.asarray(seg.forward(col))[: off[-1]]
            return flat, np.diff(off), seg.dictionary(col)
        rows = seg.values(col)[: self.n]
        lens = np.fromiter((len(r) for r in rows), dtype=np.int64, count=len(rows))
        if lens.sum():
            flat = np.concatenate([np.asarray(r) for r in rows if len(r)])
        else:
            flat = np.empty(0, dtype=meta.data_type.np_dtype)
        return flat, lens, None

    def eval_mv(self, expr: Expression, doc_idx: np.ndarray):
        """(entry_values, per_doc_lens) of an MV column over doc_idx — the
        arg form MV aggregation specs consume."""
        if not expr.is_identifier:
            raise NotImplementedError("MV aggregations take a bare MV column")
        flat, lens, d = self.mv_parts(expr.name)
        off = np.zeros(len(lens) + 1, dtype=np.int64)
        np.cumsum(lens, out=off[1:])
        dl = lens[doc_idx]
        vals = flat[concat_ranges(off[doc_idx], dl)]
        if d is not None:
            vals = d.take(vals)
        return vals, dl

    def _mv_predicate_mask(self, col: str, p: Predicate) -> np.ndarray:
        """Match-any semantics: a doc matches if ANY of its entries satisfies
        the predicate (reference per-entry ValueMatcher / aggregateGroupByMV
        contract)."""
        flat, lens, d = self.mv_parts(col)
        self.entries_scanned_in_filter += int(lens.sum())
        if d is not None:
            lut = self._predicate_over_values(p, d.values)
            per_entry = lut[flat]
        elif len(flat):
            per_entry = self._predicate_over_values(p, np.asarray(flat))
        else:
            per_entry = np.zeros(0, dtype=bool)
        mask = np.zeros(self.n, dtype=bool)
        nz = lens > 0
        if nz.any():
            off = np.zeros(self.n + 1, dtype=np.int64)
            np.cumsum(lens, out=off[1:])
            starts = off[:-1][nz]
            mask[nz] = np.logical_or.reduceat(per_entry, starts)
        return mask

    def predicate_mask(self, p: Predicate) -> np.ndarray:
        lhs = p.lhs
        if p.type is PredicateType.JSON_MATCH:
            return self._json_match_mask(p)
        if p.type is PredicateType.TEXT_MATCH:
            return self._text_match_mask(p)
        if p.type is PredicateType.RANGE and p.upper is not None:
            m = self._geo_distance_mask(p)
            if m is not None:
                return m
        if lhs.is_identifier and lhs.name not in self.seg.metadata.columns \
                and self.is_mv_column(lhs.name) and \
                p.type not in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            # evolved MV column: zero entries per doc, match-any matches none
            return np.zeros(self.n, dtype=bool)
        # bloom short-circuit: EQ/IN on a bloom-indexed column can prove the
        # segment empty BEFORE the dictionary or forward index is ever
        # decoded (ColumnValueSegmentPruner's bloom check, applied at the
        # predicate level so OR branches benefit too — the segment-level
        # pruner only sees top-level conjuncts)
        if lhs.is_identifier and lhs.name in self.seg.metadata.columns and \
                p.type in (PredicateType.EQ, PredicateType.IN) and \
                getattr(self.seg.column_metadata(lhs.name), "has_bloom",
                        False):
            from pinot_tpu.common.pruning import provably_absent

            vals = [p.value] if p.type is PredicateType.EQ \
                else list(p.values)
            if vals and provably_absent(self.seg, lhs.name, vals):
                return np.zeros(self.n, dtype=bool)
        # dictionary-space fast path
        if lhs.is_identifier and lhs.name in self.seg.metadata.columns:
            meta = self.seg.column_metadata(lhs.name)
            if not meta.single_value and \
                    p.type not in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
                return self._mv_predicate_mask(lhs.name, p)
            if meta.encoding == Encoding.DICT and meta.single_value and \
                    p.type not in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
                d = self.seg.dictionary(lhs.name)
                lut = self._regex_indexed_lut(lhs.name, p, d.values)
                if lut is None:
                    lut = self._predicate_over_values(p, d.values)
                m = self._indexed_mask(lhs.name, meta, p, np.nonzero(lut)[0])
                if m is not None:
                    return m
                self.entries_scanned_in_filter += self.n
                fwd = np.asarray(self.seg.forward(lhs.name))[: self.n]
                return lut[fwd]
        if lhs.is_identifier and lhs.name in self.seg.metadata.columns \
                and filter_operator_for(self.seg, p) == "RANGE_INDEX":
            m = self._range_index_mask(lhs.name, p)
            if m is not None:
                return m
        if p.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            # null-vector semantics (NullValueVectorReader): the forward
            # index stores default values for nulls; nullness lives in the
            # per-column bitmap. Expressions over columns are never null
            # (defaults flow through), matching basic null handling.
            null_mask = np.zeros(self.n, dtype=bool)
            if lhs.is_identifier and lhs.name not in self.seg.metadata.columns:
                if self._evolved_spec(lhs.name) is None:
                    # unknown column: an error, not a silent all/none match
                    raise KeyError(f"column {lhs.name!r} not found")
                # schema-evolved column this segment predates: all null
                null_mask[:] = True
            elif lhs.is_identifier and hasattr(self.seg, "null_vector"):
                nv = self.seg.null_vector(lhs.name)
                if nv is not None:
                    nv = np.asarray(nv)[: self.n]
                    null_mask[: len(nv)] = nv
            return null_mask if p.type is PredicateType.IS_NULL else ~null_mask
        self.entries_scanned_in_filter += self.n
        values = self.eval(lhs)
        return self._predicate_over_values(p, np.asarray(values))

    def _json_match_mask(self, p: Predicate) -> np.ndarray:
        """JSON_MATCH(col, '<expr>'): posting-list evaluation when the
        segment has a JSON index, flatten-per-doc scan otherwise — identical
        flat-row semantics either way (ImmutableJsonIndexReader analog)."""
        from pinot_tpu.storage import jsonindex

        if not p.lhs.is_identifier:
            raise ValueError("JSON_MATCH takes a column as its first arg")
        col = p.lhs.name
        f = jsonindex.parse_match_expression(p.value)
        idx = None
        if hasattr(self.seg, "json_index"):
            idx = self.seg.json_index(col)
        if idx is not None:
            return idx.match(f, self.n)[: self.n]
        self.entries_scanned_in_filter += self.n
        values = np.asarray(self.seg.values(col))[: self.n]
        return jsonindex.match_scan(values, f, self.n)

    def _text_match_mask(self, p: Predicate) -> np.ndarray:
        """TEXT_MATCH(col, '<lucene-subset query>'): posting-list evaluation
        on the text index, tokenized scan otherwise — identical term/phrase
        semantics either way (LuceneTextIndexReader analog)."""
        from pinot_tpu.storage import textindex

        if not p.lhs.is_identifier:
            raise ValueError("TEXT_MATCH takes a column as its first arg")
        col = p.lhs.name
        idx = None
        if hasattr(self.seg, "text_index"):
            idx = self.seg.text_index(col)
        if idx is None:
            self.entries_scanned_in_filter += self.n
            values = np.asarray(self.seg.values(col))[: self.n]
            idx = textindex.ScanTextIndex(values)
        return idx.match(p.value, self.n)

    def _geo_distance_mask(self, p: Predicate):
        """ST_DISTANCE(col, point) < r through the grid geo index
        (H3IndexFilterOperator role): candidate docs from the cells
        covering the query circle, exact haversine verify on candidates
        only. None → shape doesn't fit / no index → generic expression
        evaluation."""
        e = p.lhs
        if not (e.is_function and e.name == "st_distance" and len(e.args) == 2):
            return None

        def constant(x):
            if x.is_literal:
                return True
            if x.is_function:
                return all(constant(a) for a in x.args)
            return False

        col_arg = qpt_arg = None
        for a, b in ((e.args[0], e.args[1]), (e.args[1], e.args[0])):
            if a.is_identifier and constant(b):
                col_arg, qpt_arg = a, b
                break
        if col_arg is None or col_arg.name not in self.seg.metadata.columns:
            return None
        idx = None
        if hasattr(self.seg, "geo_index"):
            try:
                idx = self.seg.geo_index(col_arg.name)
            except Exception:  # noqa: BLE001 — absent/corrupt index: scan
                idx = None
        if idx is None:
            return None
        from pinot_tpu.ops.geo import haversine_m, parse_points

        qlon, qlat = parse_points(self.eval(qpt_arg))
        if len(qlon) != 1 or not np.isfinite(qlon[0]):
            return None
        radius = float(p.upper)
        cand = idx.candidate_docs(float(qlon[0]), float(qlat[0]), radius)
        if cand is None:
            # antimeridian/pole bbox: the grid can't promise a superset —
            # fall back to the generic full-column evaluation
            return None
        cand = cand[cand < self.n]
        mask = np.zeros(self.n, dtype=bool)
        if len(cand) == 0:
            return mask
        self.entries_scanned_in_filter += len(cand)
        vals = np.asarray(self.seg.values(col_arg.name))[cand]
        lon, lat = parse_points(vals)
        d = haversine_m(lon, lat, qlon[0], qlat[0])
        ok = (d <= radius) if p.upper_inclusive else (d < radius)
        if p.lower is not None:
            lo = float(p.lower)
            ok &= (d >= lo) if p.lower_inclusive else (d > lo)
        mask[cand[ok]] = True
        return mask

    def _regex_indexed_lut(self, col: str, p: Predicate, values):
        """Dict-id LUT for LIKE/REGEXP_LIKE through the trigram (FST-role)
        index: intersected posting lists narrow the candidate entries, the
        real pattern verifies survivors. None → no index / no narrowing →
        caller evaluates every dictionary entry (O(C) regex evals, the
        pre-index behavior)."""
        if p.type not in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
            return None
        idx = None
        if hasattr(self.seg, "fst_index"):
            try:
                idx = self.seg.fst_index(col)
            except Exception:  # noqa: BLE001 — absent/corrupt index: scan
                idx = None
        if idx is None:
            return None
        pat = p.value if p.type is not PredicateType.LIKE \
            else like_to_regex(p.value)
        cand = idx.candidates(pat, len(values))
        if cand is None:
            return None
        lut = np.zeros(len(values), dtype=bool)
        if len(cand):
            # one source of truth for LIKE/REGEXP semantics: evaluate the
            # generic predicate over the candidate SUBSET
            lut[cand] = self._predicate_over_values(
                p, np.asarray(values)[cand])
        return lut

    def _indexed_mask(self, col: str, meta, p: Predicate, ids: np.ndarray):
        """Index-served mask for a dict predicate whose matching dict ids are
        ``ids``, or None → caller scans. Priority mirrors
        FilterOperatorUtils.java:165-194: sorted column (binary-search doc
        runs, O(k log n)) beats inverted (doc-list slices, O(matched docs))
        beats the O(n) forward-index scan."""
        op = filter_operator_for(self.seg, p)
        if op == "SORTED_INDEX":
            mask = np.zeros(self.n, dtype=bool)
            if len(ids) == 0:
                return mask
            fwd = self.seg.forward(col)  # mmap; searchsorted touches O(log n)
            contiguous = ids[-1] - ids[0] + 1 == len(ids)
            if contiguous:
                lo = np.searchsorted(fwd[: self.n], ids[0], "left")
                hi = np.searchsorted(fwd[: self.n], ids[-1], "right")
                mask[lo:hi] = True
            else:
                if len(ids) > INVERTED_MAX_IDS:
                    return None
                for i in ids:
                    lo = np.searchsorted(fwd[: self.n], i, "left")
                    hi = np.searchsorted(fwd[: self.n], i, "right")
                    mask[lo:hi] = True
            return mask
        if op == "INVERTED_INDEX" and len(ids) <= INVERTED_MAX_IDS:
            inv = self.seg.inverted(col)
            if inv is None:
                return None
            docs, off = inv
            mask = np.zeros(self.n, dtype=bool)
            for i in ids:
                mask[docs[off[i]: off[i + 1]]] = True
            return mask
        return None

    def _range_index_mask(self, col: str, p: Predicate):
        """RAW-column range/EQ via the sorted-projection range index: two
        binary searches on the sorted values, then a doc-id slice — or None
        when the segment lacks the index files (caller scans)."""
        idx = self.seg.range_index(col) if hasattr(self.seg, "range_index") \
            else None
        if idx is None:
            return None
        docs, vals = idx
        if p.type is PredicateType.EQ:
            lo = np.searchsorted(vals, p.value, "left")
            hi = np.searchsorted(vals, p.value, "right")
        else:
            lo = 0 if p.lower is None else np.searchsorted(
                vals, p.lower, "left" if p.lower_inclusive else "right")
            hi = len(vals) if p.upper is None else np.searchsorted(
                vals, p.upper, "right" if p.upper_inclusive else "left")
        mask = np.zeros(self.n, dtype=bool)
        if hi > lo:
            sel = np.asarray(docs[lo:hi])
            mask[sel[sel < self.n]] = True
        return mask

    def _predicate_over_values(self, p: Predicate, v: np.ndarray) -> np.ndarray:
        t = p.type
        if t is PredicateType.EQ:
            return v == self._coerce(p.value, v)
        if t is PredicateType.NOT_EQ:
            return v != self._coerce(p.value, v)
        if t is PredicateType.IN:
            return np.isin(v, self._coerce_list(p.values, v))
        if t is PredicateType.NOT_IN:
            return ~np.isin(v, self._coerce_list(p.values, v))
        if t is PredicateType.RANGE:
            m = np.ones(len(v), dtype=bool)
            if p.lower is not None:
                lo = self._coerce(p.lower, v)
                m &= (v >= lo) if p.lower_inclusive else (v > lo)
            if p.upper is not None:
                hi = self._coerce(p.upper, v)
                m &= (v <= hi) if p.upper_inclusive else (v < hi)
            return m
        if t in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
            pat = p.value if t is not PredicateType.LIKE else like_to_regex(p.value)
            rx = re.compile(pat)
            search = rx.search if t is not PredicateType.LIKE else rx.match
            return np.fromiter(
                (bool(search(s)) for s in v.astype(str)), dtype=bool, count=len(v)
            )
        raise NotImplementedError(f"predicate {t} on host path")

    @staticmethod
    def _coerce(value, v: np.ndarray):
        if v.dtype.kind in ("U", "S"):
            return str(value)
        return value

    @staticmethod
    def _coerce_list(values, v: np.ndarray):
        if v.dtype.kind in ("U", "S"):
            return np.asarray([str(x) for x in values])
        return np.asarray(list(values))


def concat_ranges(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Vectorized concatenation of [starts[i], starts[i]+lens[i]) ranges."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    cum = np.cumsum(lens) - lens  # output start of each range
    return (
        np.arange(total, dtype=np.int64)
        - np.repeat(cum, lens)
        + np.repeat(starts.astype(np.int64), lens)
    )


def factorize_multi(cols: list) -> tuple:
    """(unique_key_arrays, group_idx) for multi-column group keys.

    Pairwise chained np.unique keeps combined codes < n_rows * card so no
    int64 overflow — the host stand-in for the reference's 4-regime
    DictionaryBasedGroupKeyGenerator.
    """
    if not cols:
        raise ValueError("no group-by columns")
    uniqs = []
    codes = []
    for col in cols:
        u, inv = np.unique(np.asarray(col), return_inverse=True)
        uniqs.append(u)
        codes.append(inv.astype(np.int64))
    combined = codes[0]
    for c, u in zip(codes[1:], uniqs[1:]):
        combined = combined * len(u) + c
        _, combined = np.unique(combined, return_inverse=True)
    # group keys decode from the first row of each group
    _, first_rows, ginv = np.unique(
        combined, return_index=True, return_inverse=True
    )
    keys = tuple(np.asarray(c)[first_rows] for c in cols)
    return keys, ginv


# bincount table bound for the dictionary group-key fast path: the combined
# code space (product of group-column cardinalities) must stay small enough
# that one flat int64 count array beats sorting (8 MB at the bound)
DICT_GROUP_MAX_PRODUCT = 1 << 20


def dict_factorize_multi(ev, group_exprs, doc_idx):
    """(unique_key_arrays, group_idx, n_groups) via dictionary ids, or None
    when any group expression can't ride the fast path.

    The reference's DictionaryBasedGroupKeyGenerator regime: when every
    group key is a single-value DICT column, group on the forward-index
    ids directly — one bincount over the combined code space instead of a
    value-space sort — and decode ONLY the surviving group keys through
    the dictionary. Immutable dictionaries are sorted (id order == value
    order), so ascending combined codes enumerate exactly the same
    (lexicographically sorted) key tuples ``factorize_multi`` produces:
    the two paths are interchangeable bit-exactly."""
    seg = ev.seg
    if not isinstance(seg, ImmutableSegment):
        return None  # mutable dictionaries grow in insert order: unsorted
    cards = []
    dicts = []
    for g in group_exprs:
        if not g.is_identifier or g.name.startswith("$"):
            return None
        meta = seg.metadata.columns.get(g.name)
        if meta is None or not meta.single_value or not meta.has_dictionary:
            return None
        d = seg.dictionary(g.name)
        if d is None or len(d) == 0:
            return None
        dicts.append(d)
        cards.append(len(d))
    product = 1
    for c in cards:
        product *= c
        if product > DICT_GROUP_MAX_PRODUCT:
            return None
    combined = None
    for g, card in zip(group_exprs, cards):
        ids = np.asarray(seg.forward(g.name))[: ev.n][doc_idx]
        ids = ids.astype(np.int64, copy=False)
        combined = ids if combined is None else combined * card + ids
    present = np.flatnonzero(np.bincount(combined, minlength=product))
    lut = np.empty(product, dtype=np.int64)
    lut[present] = np.arange(len(present), dtype=np.int64)
    ginv = lut[combined]
    keys = []
    rem = present
    for card, d in zip(reversed(cards), reversed(dicts)):
        keys.append(d.take(rem % card))
        rem = rem // card
    keys.reverse()
    return tuple(keys), ginv, len(present)


class HostExecutor:
    """Executes one query over a list of segments, returning per-segment
    IntermediateResults (merged by engine/reduce.py)."""

    def __init__(self, num_groups_limit: int = DEFAULT_NUM_GROUPS_LIMIT):
        self.num_groups_limit = num_groups_limit
        self.lookup_resolver = None  # set by QueryEngine (dim tables)

    def execute_segment(self, q: QueryContext, seg: ImmutableSegment) -> IntermediateResult:
        ev = SegmentEvaluator(seg, lookup_resolver=self.lookup_resolver)
        stats = ExecutionStats(
            num_segments_processed=1, num_segments_queried=1, total_docs=ev.n
        )
        # upsert validDocIds: snapshot BEFORE evaluating the filter
        # (FilterPlanNode.java:85-88 ordering)
        vd = getattr(seg, "valid_docs_mask", None)
        if vd is not None:
            vd = np.asarray(vd)[: ev.n].copy()
        elif hasattr(seg, "valid_docs"):
            m = seg.valid_docs(ev.n)
            vd = None if m is None else np.asarray(m).copy()
        mask = ev.filter_mask(q.filter)
        if vd is not None:
            mask = mask & vd
        doc_idx = np.nonzero(mask)[0]
        stats.num_docs_scanned = int(len(doc_idx))
        if q.filter is not None:
            # actual entries read: 0 for fully index-served filters
            stats.num_entries_scanned_in_filter = ev.entries_scanned_in_filter
        if len(doc_idx) > 0:
            stats.num_segments_matched = 1

        if q.distinct:
            return self._distinct(q, ev, doc_idx, stats)
        aggs = q.aggregations()
        if aggs and q.group_by:
            return self._group_by(q, ev, doc_idx, stats, aggs)
        if aggs:
            return self._aggregation(q, ev, doc_idx, stats, aggs)
        return self._selection(q, ev, doc_idx, stats)

    # ---- shapes ----------------------------------------------------------
    @staticmethod
    def _agg_partial(spec, ev, doc_idx, group_idx, n_groups, stats):
        """One spec's partial over the matched docs; MV specs get the
        (entry_values, lens) arg form."""
        if spec.mv:
            vals, lens = ev.eval_mv(spec.args[0], doc_idx)
            stats.num_entries_scanned_post_filter += int(lens.sum())
            return spec.host_groups([(vals, lens)], group_idx, n_groups)
        arg_values = [ev.eval(arg, doc_idx) for arg in spec.args]
        stats.num_entries_scanned_post_filter += len(doc_idx) * len(spec.args)
        return spec.host_groups(arg_values, group_idx, n_groups)

    def _aggregation(self, q, ev, doc_idx, stats, aggs) -> IntermediateResult:
        partials = []
        idx = np.zeros(len(doc_idx), dtype=np.int64)
        for a in aggs:
            spec = aggspec.make_spec(a)
            partials.append(self._agg_partial(spec, ev, doc_idx, idx, 1, stats))
        return IntermediateResult("aggregation", agg_partials=partials, stats=stats)

    @staticmethod
    def _expand_mv_groups(ev, group_exprs, doc_idx):
        """Expand matched docs so each doc contributes one row per MV entry
        of each MV group-by column (cartesian across MV columns — the
        reference's aggregateGroupByMV per-entry group keys).

        Returns (rep, mv_vals): ``rep`` maps expanded rows → positions in
        doc_idx; ``mv_vals[gi]`` holds the expanded entry values for MV
        group expression gi."""
        rep = np.arange(len(doc_idx))
        mv_vals: dict = {}
        for gi, g in enumerate(group_exprs):
            if not (g.is_identifier and ev.is_mv_column(g.name)):
                continue
            flat, lens, d = ev.mv_parts(g.name)
            off = np.zeros(len(lens) + 1, dtype=np.int64)
            np.cumsum(lens, out=off[1:])
            docs = doc_idx[rep]
            dl = lens[docs]
            vals = flat[concat_ranges(off[docs], dl)]
            if d is not None:
                vals = d.take(vals)
            newrep = np.repeat(np.arange(len(rep)), dl)
            for k in mv_vals:
                mv_vals[k] = mv_vals[k][newrep]
            mv_vals[gi] = vals
            rep = rep[newrep]
        return rep, mv_vals

    def _group_by(self, q, ev, doc_idx, stats, aggs) -> IntermediateResult:
        has_mv = any(
            g.is_identifier and ev.is_mv_column(g.name) for g in q.group_by
        )
        fast = None
        if has_mv:
            rep, mv_vals = self._expand_mv_groups(ev, q.group_by, doc_idx)
            doc_idx = doc_idx[rep]
            key_cols = [
                mv_vals[gi] if gi in mv_vals else ev.eval(g, doc_idx)
                for gi, g in enumerate(q.group_by)
            ]
        else:
            # dictionary group-key fast path: group on forward-index ids
            # (no value decode, no sort) when every key is a SV DICT
            # column — bit-exact with the value-space factorization
            fast = dict_factorize_multi(ev, q.group_by, doc_idx) \
                if len(doc_idx) else None
            key_cols = None if fast is not None \
                else [ev.eval(g, doc_idx) for g in q.group_by]
        if len(doc_idx) == 0:
            empty_keys = tuple(np.asarray(k)[:0] for k in key_cols)
            specs = [aggspec.make_spec(a) for a in aggs]
            return IntermediateResult(
                "group_by",
                group_keys=empty_keys,
                agg_partials=[s.empty(0) for s in specs],
                stats=stats,
            )
        if fast is not None:
            keys, ginv, n_groups = fast
        else:
            keys, ginv = factorize_multi(key_cols)
            n_groups = len(keys[0])
        # per-query override (SET numGroupsLimit = N, the reference's
        # query option) over the engine default
        limit = self.num_groups_limit
        opts = q.options_ci()
        if "numgroupslimit" in opts:
            limit = max(1, int(opts["numgroupslimit"]))
        if n_groups > limit:
            # keep the first `limit` groups *encountered*, by doc order
            # (reference numGroupsLimit semantics: excess groups dropped);
            # the flag tells callers the result is plan-dependent-partial
            # (reference numGroupsLimitReached response metadata)
            stats.num_groups_limit_reached = True
            if key_cols is None:
                key_cols = [ev.eval(g, doc_idx) for g in q.group_by]
            _, first_idx = np.unique(ginv, return_index=True)
            keep = np.argsort(first_idx)[:limit]
            keep_mask = np.isin(ginv, keep)
            doc_idx = doc_idx[keep_mask]
            key_cols = [np.asarray(k)[keep_mask] for k in key_cols]
            keys, ginv = factorize_multi(key_cols)
            n_groups = len(keys[0])
        partials = []
        for a in aggs:
            spec = aggspec.make_spec(a)
            partials.append(self._agg_partial(spec, ev, doc_idx, ginv, n_groups, stats))
        return IntermediateResult(
            "group_by", group_keys=keys, agg_partials=partials, stats=stats
        )

    def _selection(self, q, ev, doc_idx, stats) -> IntermediateResult:
        limit = q.limit + q.offset
        if not q.order_by:
            doc_idx = doc_idx[:limit]
        else:
            # per-segment trim: sort matched docs by the order-by keys
            doc_idx = doc_idx[_order_indices(
                [(ev.eval(ob.expression, doc_idx), ob.ascending) for ob in q.order_by]
            )][:limit]
        rows = {}
        for i, e in enumerate(q.select_expressions):
            rows[i] = ev.eval(e, doc_idx)
        # order-by keys ride along for the reduce-side merge re-sort
        for j, ob in enumerate(q.order_by):
            rows[f"__ob{j}"] = ev.eval(ob.expression, doc_idx)
        stats.num_entries_scanned_post_filter += len(doc_idx) * len(q.select_expressions)
        return IntermediateResult("selection", rows=rows, stats=stats)

    def _distinct(self, q, ev, doc_idx, stats) -> IntermediateResult:
        cols = [ev.eval(e, doc_idx) for e in q.select_expressions]
        if len(doc_idx) == 0:
            return IntermediateResult(
                "distinct", group_keys=tuple(np.asarray(c)[:0] for c in cols), stats=stats
            )
        keys, _ = factorize_multi(cols)
        return IntermediateResult("distinct", group_keys=keys, stats=stats)


def _order_indices(keys: list) -> np.ndarray:
    """Stable lexicographic ordering over (values, ascending) keys; string
    keys order via factorized codes (sorted-unique rank == value order)."""
    sort_cols = []
    for vals, asc in keys:
        v = np.asarray(vals)
        if v.dtype.kind in ("U", "S", "O"):
            u, inv = np.unique(v, return_inverse=True)
            v = inv.astype(np.int64)
        if not asc:
            v = _negate(v)
        sort_cols.append(v)
    # np.lexsort: last key is primary
    return np.lexsort(list(reversed(sort_cols)))


def _negate(v: np.ndarray) -> np.ndarray:
    if v.dtype.kind == "b":
        return ~v
    return -v.astype(np.float64) if v.dtype.kind == "f" else -v.astype(np.int64)
