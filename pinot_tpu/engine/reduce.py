"""Reduce: merge IntermediateResults and produce the final ResultTable.

The broker-side reduce of the reference (pinot-core/.../query/reduce/
BrokerReduceService.java + GroupByDataTableReducer / AggregationDataTableReducer /
SelectionDataTableReducer, HavingFilterHandler, PostAggregationHandler):
merges mergeable partials in value space, applies HAVING, evaluates
post-aggregation select expressions, orders, trims, and types the result.

Works over results from any executor backend (host numpy, device batch,
remote server) because partials are canonical (engine/aggspec.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pinot_tpu.engine import aggspec
from pinot_tpu.engine.host import _order_indices, factorize_multi
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult, ResultTable, py_value
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    PredicateType,
    QueryContext,
)


def merge_intermediates(q: QueryContext, results: list) -> IntermediateResult:
    results = [r for r in results if r is not None]
    if not results:
        raise ValueError("no results to merge")
    shape = results[0].shape
    if len(results) == 1 and shape in ("aggregation", "group_by", "distinct"):
        # single partial: its keys are already unique (dense/sorted device
        # tables and host group tables are deduped per execution), so the
        # factorize + scatter_merge round is identity work — and on sketch
        # partials it was the most expensive host step of the whole query
        # (np.maximum.at over G×m registers)
        return results[0]
    stats = ExecutionStats()
    for r in results:
        stats.merge(r.stats)

    if shape == "aggregation":
        specs = [aggspec.make_spec(a) for a in q.aggregations()]
        acc = [s.empty(1) for s in specs]
        zero = np.zeros(1, dtype=np.int64)
        for r in results:
            for s, a, p in zip(specs, acc, r.agg_partials):
                s.scatter_merge(a, zero, p)
        return IntermediateResult(shape, agg_partials=acc, stats=stats)

    if shape == "group_by":
        specs = [aggspec.make_spec(a) for a in q.aggregations()]
        nonempty = [r for r in results if len(r.group_keys[0]) > 0]
        if not nonempty:
            return IntermediateResult(
                shape,
                group_keys=results[0].group_keys,
                agg_partials=[s.empty(0) for s in specs],
                stats=stats,
            )
        concat_keys = [
            np.concatenate([np.asarray(r.group_keys[i]) for r in nonempty])
            for i in range(len(q.group_by))
        ]
        keys, ginv = factorize_multi(concat_keys)
        n_merged = len(keys[0])
        acc = [s.empty(n_merged) for s in specs]
        off = 0
        for r in nonempty:
            n_r = len(r.group_keys[0])
            idx = ginv[off : off + n_r]
            off += n_r
            for s, a, p in zip(specs, acc, r.agg_partials):
                s.scatter_merge(a, idx, p)
        return IntermediateResult(shape, group_keys=keys, agg_partials=acc, stats=stats)

    if shape == "selection":
        keys = results[0].rows.keys()
        rows = {
            k: np.concatenate([np.asarray(r.rows[k]) for r in results]) for k in keys
        }
        return IntermediateResult(shape, rows=rows, stats=stats)

    if shape == "distinct":
        concat_keys = [
            np.concatenate([np.asarray(r.group_keys[i]) for r in results])
            for i in range(len(results[0].group_keys))
        ]
        if len(concat_keys[0]) == 0:
            keys = tuple(concat_keys)
        else:
            keys, _ = factorize_multi(concat_keys)
        return IntermediateResult(shape, group_keys=keys, stats=stats)

    raise ValueError(f"unknown result shape {shape}")


def trim_bound(q: QueryContext, min_trim_size: int = 5000) -> int:
    """The server-partial keep bound: ``max(5 * (offset+limit),
    min_trim_size)``. The 5x headroom is the reference's guard against a
    group that is globally top-K but not locally top-K on this server.
    ONE copy of the policy — the host trim below and the device trim
    (ops/device_reduce.py) both read it, so they cannot drift."""
    return max(5 * (q.offset + q.limit), min_trim_size)


def trim_group_by(q: QueryContext, merged: IntermediateResult,
                  min_trim_size: int = 5000) -> IntermediateResult:
    """Server-side order-by-aware group trim before the DataTable ships
    (data/table/TableResizer.java analog): keep the top ``trim_bound``
    groups by the query's ORDER BY, evaluated on finalized local
    partials. HAVING queries are not trimmed (the broker filters groups
    after the merge, so any local trim could starve it of survivors).
    When the device already trimmed the sole partial in-kernel
    (ops/device_reduce.py, same bound), n <= trim_size and this is a
    no-op."""
    if merged.shape != "group_by" or not q.order_by or q.having is not None:
        return merged
    n = len(merged.group_keys[0])
    trim_size = trim_bound(q, min_trim_size)
    if n <= trim_size:
        return merged
    specs = [aggspec.make_spec(a) for a in q.aggregations()]
    env = _group_env(q, merged, specs)
    order = _order_indices(
        [(np.broadcast_to(np.asarray(eval_post(ob.expression, env)), (n,)),
          ob.ascending)
         for ob in q.order_by]
    )[:trim_size]
    return IntermediateResult(
        "group_by",
        group_keys=tuple(np.asarray(k)[order] for k in merged.group_keys),
        agg_partials=[s.take(p, order)
                      for s, p in zip(specs, merged.agg_partials)],
        stats=merged.stats,
    )


# ---------------------------------------------------------------------------
# post-aggregation expression evaluation
# ---------------------------------------------------------------------------


def eval_post(expr: Expression, env: dict):
    """Evaluate a select/having/order expression in post-aggregation space:
    ``env`` maps group-by expressions and aggregation expressions to value
    arrays (PostAggregationHandler analog)."""
    if expr in env:
        return env[expr]
    if expr.is_literal:
        return np.asarray(expr.value)
    if expr.is_identifier:
        raise KeyError(
            f"column {expr.name!r} must appear in GROUP BY to be selected"
        )
    fn = get_function(expr.name)
    if expr.name == "cast":
        return fn.np_fn(eval_post(expr.args[0], env), expr.args[1].value)
    args = [eval_post(a, env) for a in expr.args]
    return fn.np_fn(*args)


def _having_mask(f: FilterNode, env: dict, n: int) -> np.ndarray:
    t = f.type
    if t is FilterNodeType.CONSTANT_TRUE:
        return np.ones(n, dtype=bool)
    if t is FilterNodeType.CONSTANT_FALSE:
        return np.zeros(n, dtype=bool)
    if t is FilterNodeType.AND:
        m = _having_mask(f.children[0], env, n)
        for c in f.children[1:]:
            m &= _having_mask(c, env, n)
        return m
    if t is FilterNodeType.OR:
        m = _having_mask(f.children[0], env, n)
        for c in f.children[1:]:
            m |= _having_mask(c, env, n)
        return m
    if t is FilterNodeType.NOT:
        return ~_having_mask(f.children[0], env, n)
    p = f.predicate
    v = np.broadcast_to(np.asarray(eval_post(p.lhs, env)), (n,))
    if p.type is PredicateType.EQ:
        return v == p.value
    if p.type is PredicateType.NOT_EQ:
        return v != p.value
    if p.type is PredicateType.IN:
        return np.isin(v, list(p.values))
    if p.type is PredicateType.NOT_IN:
        return ~np.isin(v, list(p.values))
    if p.type is PredicateType.RANGE:
        m = np.ones(n, dtype=bool)
        if p.lower is not None:
            m &= (v >= p.lower) if p.lower_inclusive else (v > p.lower)
        if p.upper is not None:
            m &= (v <= p.upper) if p.upper_inclusive else (v < p.upper)
        return m
    raise NotImplementedError(f"HAVING predicate {p.type}")


# ---------------------------------------------------------------------------
# finalization per shape
# ---------------------------------------------------------------------------


def finalize(q: QueryContext, merged: IntermediateResult) -> ResultTable:
    if merged.shape == "aggregation":
        return _finalize_aggregation(q, merged)
    if merged.shape == "group_by":
        return _finalize_group_by(q, merged)
    if merged.shape == "selection":
        return _finalize_selection(q, merged)
    if merged.shape == "distinct":
        return _finalize_distinct(q, merged)
    raise ValueError(merged.shape)


def _np_type_name(arr: np.ndarray) -> str:
    k = arr.dtype.kind
    if k == "b":
        return "BOOLEAN"
    if k in ("i", "u"):
        return "LONG" if arr.dtype.itemsize >= 8 else "INT"
    if k == "f":
        return "DOUBLE"
    return "STRING"


def _finalize_aggregation(q, merged) -> ResultTable:
    aggs = q.aggregations()
    specs = [aggspec.make_spec(a) for a in aggs]
    env = {}
    no_rows = merged.stats.num_docs_scanned == 0
    for a, s, p in zip(aggs, specs, merged.agg_partials):
        if no_rows:
            # SQL semantics over zero rows: COUNT = 0, everything else NULL;
            # NaN propagates through post-aggregation arithmetic like NULL
            env[a] = np.asarray([0], dtype=np.int64) if s.name == "count" \
                else np.asarray([np.nan])
        else:
            env[a] = s.finalize(p)
    names, types, cols = [], [], []
    for i, e in enumerate(q.select_expressions):
        v = np.asarray(eval_post(e, env)).reshape(-1)
        names.append(q.column_name(i))
        types.append(_np_type_name(v))
        val = py_value(v[0]) if len(v) else None
        if isinstance(val, float) and np.isnan(val):
            val = None
        cols.append(val)
    return ResultTable(names, types, [tuple(cols)])


def _group_env(q, merged, specs):
    env = {}
    for g, k in zip(q.group_by, merged.group_keys):
        env[g] = np.asarray(k)
    for a, s, p in zip(q.aggregations(), specs, merged.agg_partials):
        env[a] = s.finalize(p)
    return env


def _gapfill_options(q) -> Optional[dict]:
    """SET-driven gapfill config (GapfillProcessor analog, option-shaped:
    SET gapfillBucketMs = 3600000; [gapfillStart/gapfillEnd/gapfillFill]).
    Returns None when gapfill is off."""
    opts = q.options_ci()
    bucket = opts.get("gapfillbucketms")
    if bucket is None:
        return None
    if len(q.group_by) != 1:
        raise ValueError("gapfill needs exactly one GROUP BY time bucket")
    return {
        "bucket": int(bucket),
        "start": opts.get("gapfillstart"),
        "end": opts.get("gapfillend"),
        "fill": str(opts.get("gapfillfill", "zero")).lower(),
    }


def _apply_gapfill(q, env, n, cfg, specs):
    """Insert missing time buckets into the group env: COUNT-like aggs get
    the fill value (zero/null/previous); group keys become the full bucket
    range [start, end) at bucket intervals."""
    key_expr = q.group_by[0]
    keys = np.asarray(env[key_expr], dtype=np.int64)
    bucket = cfg["bucket"]
    if bucket <= 0:
        raise ValueError("gapfillBucketMs must be positive")
    start = int(cfg["start"]) if cfg["start"] is not None else \
        (int(keys.min()) if n else 0)
    end = int(cfg["end"]) if cfg["end"] is not None else \
        (int(keys.max()) + bucket if n else 0)
    if end <= start:
        return env, n
    n_buckets = (end - start + bucket - 1) // bucket
    if n_buckets > 1_000_000:
        raise ValueError(f"gapfill range too large ({n_buckets} buckets)")
    in_range = (keys >= start) & (keys < end)
    if n and np.any((keys[in_range] - start) % bucket != 0):
        # off-grid group keys would otherwise be silently replaced by fill
        # values — reject like the reference rejects misaligned buckets
        raise ValueError(
            "gapfill group keys are not aligned to gapfillBucketMs from "
            "gapfillStart; bucket the GROUP BY expression accordingly")
    full = start + np.arange(n_buckets, dtype=np.int64) * bucket
    pos = np.searchsorted(full, keys)
    hit = np.zeros(n_buckets, dtype=bool)
    src = np.zeros(n_buckets, dtype=np.int64)
    hit[pos[in_range]] = True
    src[pos[in_range]] = np.nonzero(in_range)[0]
    fill = cfg["fill"]
    out = {key_expr: full}
    for a, s in zip(q.aggregations(), specs):
        vals = np.asarray(env[a])
        # zero-fill preserves integer aggregate types (COUNT stays LONG);
        # null/previous fills need NaN, so they widen to float
        if fill == "zero" and vals.dtype.kind in ("i", "u"):
            filled = np.zeros(n_buckets, dtype=np.int64)
        else:
            filled = np.zeros(n_buckets, dtype=np.float64)
            if fill == "null":
                filled[:] = np.nan
        if n:
            filled[hit] = vals[src[hit]].astype(filled.dtype)
        if fill == "previous" and n_buckets:
            # carry the last seen value forward (reference FILL(...,
            # 'FILL_PREVIOUS_VALUE')); leading gaps stay null
            idx = np.where(hit, np.arange(n_buckets), -1)
            idx = np.maximum.accumulate(idx)
            filled = np.where(idx >= 0, filled[np.maximum(idx, 0)], np.nan)
        out[a] = filled
    return out, n_buckets


def _finalize_group_by(q, merged) -> ResultTable:
    specs = [aggspec.make_spec(a) for a in q.aggregations()]
    env = _group_env(q, merged, specs)
    n = len(merged.group_keys[0])

    if q.having is not None and n > 0:
        mask = _having_mask(q.having, env, n)
        env = {k: np.asarray(v)[mask] if np.asarray(v).ndim else v for k, v in env.items()}
        n = int(mask.sum())

    gf = _gapfill_options(q)
    if gf is not None:
        env, n = _apply_gapfill(q, env, n, gf, specs)

    if q.order_by and n > 0:
        order = _order_indices(
            [(np.broadcast_to(np.asarray(eval_post(ob.expression, env)), (n,)), ob.ascending)
             for ob in q.order_by]
        )
        env = {k: (np.asarray(v)[order] if np.asarray(v).ndim else v) for k, v in env.items()}

    sel = q.offset, q.offset + q.limit
    out_cols = []
    names, types = [], []
    for i, e in enumerate(q.select_expressions):
        v = np.broadcast_to(np.asarray(eval_post(e, env)), (n,))[sel[0]: sel[1]]
        names.append(q.column_name(i))
        types.append(_np_type_name(v))
        out_cols.append(v)
    rows = [tuple(py_value(c[i]) for c in out_cols) for i in range(len(out_cols[0]) if out_cols else 0)]
    if gf is not None:
        # null-filled buckets surface as SQL NULLs, not NaN
        rows = [tuple(None if isinstance(x, float) and np.isnan(x) else x
                      for x in r) for r in rows]
    return ResultTable(names, types, rows)


def _finalize_selection(q, merged) -> ResultTable:
    n = len(next(iter(merged.rows.values()))) if merged.rows else 0
    idx = np.arange(n)
    if q.order_by and n > 0:
        order = _order_indices(
            [(merged.rows[f"__ob{j}"], ob.ascending) for j, ob in enumerate(q.order_by)]
        )
        idx = idx[order]
    idx = idx[q.offset : q.offset + q.limit]
    names, types, cols = [], [], []
    for i in range(len(q.select_expressions)):
        v = np.asarray(merged.rows[i])[idx]
        names.append(q.column_name(i))
        types.append(_np_type_name(v))
        cols.append(v)
    rows = [tuple(py_value(c[j]) for c in cols) for j in range(len(idx))]
    return ResultTable(names, types, rows)


def _finalize_distinct(q, merged) -> ResultTable:
    keys = [np.asarray(k) for k in merged.group_keys]
    n = len(keys[0])
    idx = np.arange(n)
    if q.order_by and n > 0:
        env = {e: k for e, k in zip(q.select_expressions, keys)}
        order = _order_indices(
            [(np.broadcast_to(np.asarray(eval_post(ob.expression, env)), (n,)), ob.ascending)
             for ob in q.order_by]
        )
        idx = idx[order]
    idx = idx[q.offset : q.offset + q.limit]
    names, types, cols = [], [], []
    for i, e in enumerate(q.select_expressions):
        v = keys[i][idx]
        names.append(q.column_name(i))
        types.append(_np_type_name(v))
        cols.append(v)
    rows = [tuple(py_value(c[j]) for c in cols) for j in range(len(idx))]
    return ResultTable(names, types, rows)
