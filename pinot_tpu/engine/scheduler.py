"""Query scheduler: bounded admission for server query execution.

Equivalent of the reference's ``QueryScheduler`` hierarchy
(pinot-core/.../query/scheduler/QueryScheduler.java:56 +
BoundedAccountingExecutor / FCFSQueryScheduler): a hard cap on concurrently
executing queries plus a bounded wait queue; past both, the query is
rejected immediately with an in-band error rather than piling onto gRPC
threads — one runaway high-cardinality query can no longer starve the
server. (Per-query resource accounting lives in the stats the engine
already returns; token-bucket priority across tables is not modeled.)
"""

from __future__ import annotations

import threading


class SchedulerSaturated(Exception):
    """Queue full: the caller should surface QUERY_SCHEDULING_TIMEOUT."""


class QueryScheduler:
    def __init__(self, max_concurrent: int = 8, max_queued: int = 32,
                 queue_timeout_s: float = 5.0):
        # queue_timeout_s must stay below the broker's query timeout (10s
        # default): a slot granted after the broker abandoned the request
        # would burn a worker doing work nobody reads.
        self.max_concurrent = max_concurrent
        self.max_queued = max_queued
        self.queue_timeout_s = queue_timeout_s
        self._sem = threading.Semaphore(max_concurrent)
        self._lock = threading.Lock()
        self._waiting = 0
        self.num_rejected = 0
        self.num_executed = 0

    def run(self, fn, queue_timeout_s=None):
        """Execute ``fn`` under the concurrency cap; raises
        SchedulerSaturated when the wait queue is full or the slot wait
        times out. ``queue_timeout_s`` lets a per-query deadline (SET
        timeoutMs) shrink the admission wait: a query whose budget elapsed
        queueing must not start and burn a worker nobody reads."""
        wait_s = self.queue_timeout_s if queue_timeout_s is None \
            else min(self.queue_timeout_s, queue_timeout_s)
        with self._lock:
            if self._waiting >= self.max_queued:
                self.num_rejected += 1
                raise SchedulerSaturated(
                    f"query queue full ({self._waiting} waiting, "
                    f"{self.max_concurrent} running)"
                )
            self._waiting += 1
        try:
            if not self._sem.acquire(timeout=wait_s):
                with self._lock:
                    self.num_rejected += 1
                raise SchedulerSaturated(
                    f"no execution slot within {wait_s}s"
                )
        finally:
            with self._lock:
                self._waiting -= 1
        try:
            with self._lock:
                self.num_executed += 1
            return fn()
        finally:
            self._sem.release()
