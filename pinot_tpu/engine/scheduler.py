"""Query schedulers: bounded admission + token-bucket priority.

Equivalent of the reference's ``QueryScheduler`` hierarchy
(pinot-core/.../query/scheduler/QueryScheduler.java:56):

- ``QueryScheduler`` — FCFS with a hard concurrency cap and a bounded wait
  queue (FCFSQueryScheduler + BoundedAccountingExecutor): past both, the
  query is rejected immediately with an in-band error rather than piling
  onto gRPC threads.
- ``TokenBucketScheduler`` — per-group (per-table) token buckets with
  priority pick (tokenbucket/TokenPriorityScheduler.java:1 +
  TableBasedGroupMapper + MultiLevelPriorityQueue): each group accrues
  execution-time budget at a fixed rate; when queries contend for slots,
  the group with the most remaining budget runs first and every query
  charges its wall-time to its group — a heavy tenant drains its bucket
  and yields to light tenants instead of starving them.

Both record per-query resource accounting (scheduler wait + thread CPU
time), surfaced through ExecutionStats into the broker response like the
reference's DataTable V3 ``threadCpuTimeNs`` metadata.
"""

from __future__ import annotations

import threading
import time


class SchedulerSaturated(Exception):
    """Queue full: the caller should surface QUERY_SCHEDULING_TIMEOUT."""


# priority class -> weighted-fair slot weight (ISSUE 14): one contract
# end to end — the broker's admission controller scales tenant bucket
# refill by these, ships the class in every instance request, and the
# server's TokenBucketScheduler uses the same weight as the group's fair
# slot share. interactive > dashboard > adhoc.
PRIORITY_WEIGHTS = {"interactive": 4.0, "dashboard": 2.0, "adhoc": 1.0}


class QueryScheduler:
    def __init__(self, max_concurrent: int = 8, max_queued: int = 32,
                 queue_timeout_s: float = 5.0):
        # queue_timeout_s must stay below the broker's query timeout (10s
        # default): a slot granted after the broker abandoned the request
        # would burn a worker doing work nobody reads.
        self.max_concurrent = max_concurrent
        self.max_queued = max_queued
        self.queue_timeout_s = queue_timeout_s
        self._sem = threading.Semaphore(max_concurrent)
        self._lock = threading.Lock()
        self._waiting = 0
        self._running = 0
        self.num_rejected = 0
        self.num_executed = 0

    def pressure(self) -> int:
        """Admitted + queued query count — the device launch coalescer's
        gate (engine/inflight.py): a micro-batch window only opens when
        concurrent demand makes a cohort partner likely."""
        with self._lock:
            return self._running + self._waiting

    def run(self, fn, queue_timeout_s=None, group: str = "default",
            stats_out=None, weight: float = 1.0):
        """Execute ``fn`` under the concurrency cap; raises
        SchedulerSaturated when the wait queue is full or the slot wait
        times out. ``queue_timeout_s`` lets a per-query deadline (SET
        timeoutMs) shrink the admission wait: a query whose budget elapsed
        queueing must not start and burn a worker nobody reads. ``group``
        and ``weight`` are ignored (FCFS); ``stats_out`` (dict) receives
        per-query accounting: scheduler_wait_ms + thread_cpu_time_ns."""
        wait_s = self.queue_timeout_s if queue_timeout_s is None \
            else min(self.queue_timeout_s, queue_timeout_s)
        t_enq = time.perf_counter()
        with self._lock:
            if self._waiting >= self.max_queued:
                self.num_rejected += 1
                raise SchedulerSaturated(
                    f"query queue full ({self._waiting} waiting, "
                    f"{self.max_concurrent} running)"
                )
            self._waiting += 1
        try:
            if not self._sem.acquire(timeout=wait_s):
                with self._lock:
                    self.num_rejected += 1
                raise SchedulerSaturated(
                    f"no execution slot within {wait_s}s"
                )
        finally:
            with self._lock:
                self._waiting -= 1
        try:
            with self._lock:
                self.num_executed += 1
                self._running += 1
            # wait is over — publish it BEFORE fn so fn can fold it into
            # the stats it serializes (fn measures its own thread CPU: a
            # post-fn write here could never reach an already-encoded
            # response)
            if stats_out is not None:
                stats_out["scheduler_wait_ms"] = \
                    (time.perf_counter() - t_enq) * 1e3
            return fn()
        finally:
            with self._lock:
                self._running -= 1
            self._sem.release()


class SchedulerGroup:
    """One tenant's bucket (SchedulerGroup + TokenSchedulerGroup analog).

    ``weight`` (ISSUE 14, priority classes): the group's weighted-fair
    slot share — a weight-4 (interactive) tenant is entitled to 4x the
    running slots of a weight-1 (adhoc) one before yielding. Updated to
    the latest value each admission (the broker ships the query's
    priority-class weight per request)."""

    def __init__(self, name: str, rate_ms_per_s: float, burst_ms: float):
        self.name = name
        self.rate = rate_ms_per_s
        self.burst = burst_ms
        self.tokens = burst_ms  # start full: cold tenants get full burst
        self.last_refill = time.perf_counter()
        self.weight = 1.0
        self.num_executed = 0
        self.num_rejected = 0
        self.cpu_ms_total = 0.0
        self.wall_ms_total = 0.0

    def refill(self, now: float) -> None:
        dt = now - self.last_refill
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + self.rate * dt)
            self.last_refill = now

    def charge(self, wall_ms: float) -> None:
        # tokens may go negative (the reference lets a long query overdraw;
        # the group then sits out until refill catches up)
        self.tokens -= wall_ms


class TokenBucketScheduler:
    """Priority admission by per-group execution-time budget.

    tokenbucket/TokenPriorityScheduler.java:1 re-shaped for this engine:
    instead of reserving JVM threads per group, each group owns a bucket of
    execution milliseconds refilled at ``rate_ms_per_s``; a slot goes to
    the waiting query whose group holds the most tokens (FIFO within a
    group). Groups are created on first use (TableBasedGroupMapper: group
    == table name)."""

    def __init__(self, max_concurrent: int = 8, max_queued: int = 32,
                 queue_timeout_s: float = 5.0,
                 rate_ms_per_s: float = 2_000.0, burst_ms: float = 4_000.0,
                 per_group_hard_limit: int = None):
        self.max_concurrent = max_concurrent
        self.max_queued = max_queued
        self.queue_timeout_s = queue_timeout_s
        self.rate_ms_per_s = rate_ms_per_s
        self.burst_ms = burst_ms
        # UNCONDITIONAL per-group slot cap (ResourceManager hard limit /
        # BoundedAccountingExecutor): priority alone can't protect a light
        # tenant arriving while a heavy one occupies every slot — without
        # preemption, the only guarantee is never letting one group hold
        # them all
        self.per_group_hard_limit = per_group_hard_limit if \
            per_group_hard_limit is not None else \
            max(1, int(max_concurrent * 0.75))
        self._cond = threading.Condition()
        self._groups: dict[str, SchedulerGroup] = {}
        self._waiters: list = []  # [(seq, group_name)] in arrival order
        self._running_by_group: dict[str, int] = {}
        self._seq = 0
        self._running = 0
        self.num_rejected = 0
        self.num_executed = 0

    MAX_GROUPS = 1024  # arbitrary-SQL servers must not grow state unboundedly

    def pressure(self) -> int:
        """Admitted + queued query count (see QueryScheduler.pressure)."""
        with self._cond:
            return self._running + len(self._waiters)

    def _group(self, name: str) -> SchedulerGroup:
        g = self._groups.get(name)
        if g is None:
            if len(self._groups) >= self.MAX_GROUPS:
                # overflow tenants share one bucket rather than minting
                # fresh full-burst groups forever
                return self._groups.setdefault(
                    "__overflow__", SchedulerGroup(
                        "__overflow__", self.rate_ms_per_s, self.burst_ms))
            g = self._groups[name] = SchedulerGroup(
                name, self.rate_ms_per_s, self.burst_ms)
        return g

    def _my_turn(self, seq: int, name: str) -> bool:
        """Weighted-fair slot pick (ISSUE 14): among waiters, the group
        holding the smallest share of running slots RELATIVE TO ITS
        WEIGHT goes first (running/weight — a weight-4 interactive tenant
        may hold 4x the slots of a weight-1 adhoc one before yielding);
        ties break by most remaining tokens, then FIFO inside a group.
        Waiters whose group is at its hard slot cap are not candidates;
        waiters whose group is overdrawn sit out until refill unless EVERY
        remaining group is overdrawn — then the weighted-fair order still
        applies so slots the hardware could use never idle."""
        if self._running >= self.max_concurrent:
            return False
        now = time.perf_counter()
        for g in self._groups.values():
            g.refill(now)
        under_cap = [
            (s, n) for s, n in self._waiters
            if self._running_by_group.get(n, 0) < self.per_group_hard_limit
        ]
        if not under_cap:
            return False
        candidates = [(s, n) for s, n in under_cap
                      if self._groups[n].tokens > 0]
        if not candidates:
            candidates = under_cap

        def share(n: str) -> float:
            g = self._groups[n]
            return self._running_by_group.get(n, 0) / max(g.weight, 1e-9)

        best = min(candidates,
                   key=lambda e: (share(e[1]),
                                  -self._groups[e[1]].tokens, e[0]))
        return best == (seq, name)

    def run(self, fn, queue_timeout_s=None, group: str = "default",
            stats_out=None, weight: float = 1.0):
        wait_s = self.queue_timeout_s if queue_timeout_s is None \
            else min(self.queue_timeout_s, queue_timeout_s)
        deadline = time.perf_counter() + wait_s
        with self._cond:
            # resolve to the EFFECTIVE group once (overflow sharing) so all
            # later lookups agree; the query's priority-class weight
            # becomes the group's weighted-fair share (latest wins)
            g0 = self._group(group)
            group = g0.name
            g0.weight = max(float(weight), 1e-9)
            if len(self._waiters) >= self.max_queued:
                self.num_rejected += 1
                self._groups[group].num_rejected += 1
                raise SchedulerSaturated(
                    f"query queue full ({len(self._waiters)} waiting, "
                    f"{self._running} running)")
            seq = self._seq
            self._seq += 1
            me = (seq, group)
            self._waiters.append(me)
            try:
                while not self._my_turn(seq, group):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        self.num_rejected += 1
                        self._groups[group].num_rejected += 1
                        raise SchedulerSaturated(
                            f"no execution slot within {wait_s}s "
                            f"(group {group!r} tokens "
                            f"{self._groups[group].tokens:.0f}ms)")
                    # bounded wait: token refill is time-driven, so waiters
                    # must wake periodically even without a notify
                    self._cond.wait(min(left, 0.02))
            finally:
                self._waiters.remove(me)
            self._running += 1
            self._running_by_group[group] = \
                self._running_by_group.get(group, 0) + 1
            self.num_executed += 1
            self._groups[group].num_executed += 1
            # other waiters may now also be eligible (free slots remain);
            # without this they idle until their 20ms poll expires
            self._cond.notify_all()
        if stats_out is not None:
            stats_out["scheduler_wait_ms"] = \
                (time.perf_counter() - (deadline - wait_s)) * 1e3
        t0 = time.perf_counter()
        t_cpu = time.thread_time_ns()
        try:
            return fn()
        finally:
            wall_ms = (time.perf_counter() - t0) * 1e3
            cpu_ns = time.thread_time_ns() - t_cpu
            if stats_out is not None:
                stats_out["thread_cpu_time_ns"] = cpu_ns
            with self._cond:
                g = self._groups[group]
                g.charge(wall_ms)
                g.cpu_ms_total += cpu_ns / 1e6
                g.wall_ms_total += wall_ms
                self._running -= 1
                self._running_by_group[group] -= 1
                self._cond.notify_all()

    def group_stats(self) -> dict:
        """Per-tenant accounting snapshot (the reference's per-group
        metrics on SchedulerGroup)."""
        with self._cond:
            now = time.perf_counter()
            out = {}
            for name, g in self._groups.items():
                g.refill(now)
                out[name] = {
                    "tokens_ms": round(g.tokens, 1),
                    "weight": g.weight,
                    "executed": g.num_executed,
                    "rejected": g.num_rejected,
                    "cpu_ms_total": round(g.cpu_ms_total, 1),
                    "wall_ms_total": round(g.wall_ms_total, 1),
                }
            return out


def make_scheduler(name: str, max_concurrent: int, max_queued: int,
                   **kwargs):
    """Config-selected scheduler (pinot.server.query.scheduler.name)."""
    if name in ("fcfs", "", None):
        return QueryScheduler(max_concurrent=max_concurrent,
                              max_queued=max_queued)
    if name == "tokenbucket":
        return TokenBucketScheduler(max_concurrent=max_concurrent,
                                    max_queued=max_queued, **kwargs)
    raise ValueError(f"unknown scheduler {name!r} (fcfs|tokenbucket)")
