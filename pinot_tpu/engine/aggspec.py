"""Aggregation-function specs: canonical mergeable partial states.

The TPU analog of the reference's AggregationFunction SPI
(pinot-core/.../query/aggregation/function/AggregationFunction.java:
``aggregate`` / ``aggregateGroupBySV`` / ``merge`` / ``extractFinalResult``).
Each spec defines:

- ``host_groups(values, group_idx, n)``  — numpy partial arrays per group
- ``empty(n)`` / ``scatter_merge(acc, idx, part)`` — value-space merge used
  by the reduce step (IndexedTable / DataTableReducer analog); device
  executors convert their dense global-id partials into this same canonical
  form, so reduce is backend-agnostic
- ``finalize(part)``                      — final result column

Partial layout: dict[str, np.ndarray] with per-group arrays; object arrays
hold set/list-valued states (distinct sets, percentile value lists).
"""

from __future__ import annotations

import re

import numpy as np

from pinot_tpu.ops import hll as hll_ops
from pinot_tpu.ops import quantile_digest as qd
from pinot_tpu.query.context import Expression


class AggSpec:
    """Base: subclasses define the state algebra."""

    name: str = ""
    # MV specs take ONE arg evaluated as an (entry_values, per_doc_lens)
    # pair (the executor's eval_mv form) instead of per-doc value arrays
    mv: bool = False

    # which select-time arg expressions need evaluating over filtered rows
    def __init__(self, expr: Expression):
        self.expr = expr
        self.args = expr.args

    # ---- host computation over filtered row values -----------------------
    def host_groups(self, arg_values: list, group_idx: np.ndarray, n: int) -> dict:
        raise NotImplementedError

    def host_scalar(self, arg_values: list) -> dict:
        """Non-group-by: one-group case."""
        idx = np.zeros(len(arg_values[0]) if arg_values else 0, dtype=np.int64)
        return self.host_groups(arg_values, idx, 1)

    # ---- merge algebra ---------------------------------------------------
    def empty(self, n: int) -> dict:
        raise NotImplementedError

    def scatter_merge(self, acc: dict, idx: np.ndarray, part: dict) -> None:
        raise NotImplementedError

    def finalize(self, part: dict) -> np.ndarray:
        raise NotImplementedError

    def take(self, part: dict, idx: np.ndarray) -> dict:
        """Row-select a partial (server-side trim): every state field is a
        per-group array, so fancy indexing covers all specs."""
        return {k: np.asarray(v)[idx] for k, v in part.items()}

    def result_type(self) -> str:
        return "DOUBLE"


def _obj_array(n, factory):
    a = np.empty(n, dtype=object)
    for i in range(n):
        a[i] = factory()
    return a


class CountSpec(AggSpec):
    name = "count"

    def __init__(self, expr: Expression):
        super().__init__(expr)
        self.args = ()  # COUNT(*) / COUNT(col) both count docs

    def host_groups(self, arg_values, group_idx, n):
        c = np.zeros(n, dtype=np.int64)
        np.add.at(c, group_idx, 1)
        return {"count": c}

    def empty(self, n):
        return {"count": np.zeros(n, dtype=np.int64)}

    def scatter_merge(self, acc, idx, part):
        np.add.at(acc["count"], idx, part["count"])

    def finalize(self, part):
        return part["count"]

    def result_type(self):
        return "LONG"


class SumSpec(AggSpec):
    name = "sum"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        s = np.zeros(n, dtype=np.float64)
        np.add.at(s, group_idx, v)
        return {"sum": s}

    def empty(self, n):
        return {"sum": np.zeros(n, dtype=np.float64)}

    def scatter_merge(self, acc, idx, part):
        np.add.at(acc["sum"], idx, part["sum"])

    def finalize(self, part):
        return part["sum"]


class MinSpec(AggSpec):
    name = "min"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        m = np.full(n, np.inf)
        np.minimum.at(m, group_idx, v)
        return {"min": m}

    def empty(self, n):
        return {"min": np.full(n, np.inf)}

    def scatter_merge(self, acc, idx, part):
        np.minimum.at(acc["min"], idx, part["min"])

    def finalize(self, part):
        return part["min"]


class MaxSpec(AggSpec):
    name = "max"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        m = np.full(n, -np.inf)
        np.maximum.at(m, group_idx, v)
        return {"max": m}

    def empty(self, n):
        return {"max": np.full(n, -np.inf)}

    def scatter_merge(self, acc, idx, part):
        np.maximum.at(acc["max"], idx, part["max"])

    def finalize(self, part):
        return part["max"]


class AvgSpec(AggSpec):
    name = "avg"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        s = np.zeros(n, dtype=np.float64)
        c = np.zeros(n, dtype=np.int64)
        np.add.at(s, group_idx, v)
        np.add.at(c, group_idx, 1)
        return {"sum": s, "count": c}

    def empty(self, n):
        return {"sum": np.zeros(n, dtype=np.float64), "count": np.zeros(n, dtype=np.int64)}

    def scatter_merge(self, acc, idx, part):
        np.add.at(acc["sum"], idx, part["sum"])
        np.add.at(acc["count"], idx, part["count"])

    def finalize(self, part):
        with np.errstate(divide="ignore", invalid="ignore"):
            return part["sum"] / part["count"]


class MinMaxRangeSpec(AggSpec):
    name = "minmaxrange"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        mn = np.full(n, np.inf)
        mx = np.full(n, -np.inf)
        np.minimum.at(mn, group_idx, v)
        np.maximum.at(mx, group_idx, v)
        return {"min": mn, "max": mx}

    def empty(self, n):
        return {"min": np.full(n, np.inf), "max": np.full(n, -np.inf)}

    def scatter_merge(self, acc, idx, part):
        np.minimum.at(acc["min"], idx, part["min"])
        np.maximum.at(acc["max"], idx, part["max"])

    def finalize(self, part):
        return part["max"] - part["min"]


class DistinctCountSpec(AggSpec):
    """Exact distinct count: object array of python sets (host canonical
    form; the device path decodes presence vectors into the same sets)."""

    name = "distinctcount"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        sets = _obj_array(n, set)
        for g, val in zip(group_idx, v.tolist()):
            sets[g].add(val)
        return {"sets": sets}

    def empty(self, n):
        return {"sets": _obj_array(n, set)}

    def scatter_merge(self, acc, idx, part):
        if "cnt" in part:
            raise AssertionError(
                "finalized distinct counts are not mergeable — 'cnt' "
                "partials only occur on the terminal single-partial path")
        for i, g in enumerate(idx):
            acc["sets"][g] |= part["sets"][i]

    def finalize(self, part):
        if "cnt" in part:
            # terminal device path: the popcount already happened on device
            return np.asarray(part["cnt"], dtype=np.int64)
        return np.array([len(s) for s in part["sets"]], dtype=np.int64)

    def result_type(self):
        return "INT"


class DistinctCountHLLSpec(AggSpec):
    name = "distinctcounthll"

    def __init__(self, expr: Expression, log2m: int = hll_ops.DEFAULT_LOG2M):
        super().__init__(expr)
        # optional second literal arg = log2m (reference signature)
        if len(expr.args) > 1 and expr.args[1].is_literal:
            log2m = int(expr.args[1].value)
            self.args = expr.args[:1]
        self.log2m = log2m
        self.m = 1 << log2m

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        return {"regs": hll_ops.registers_np(v, group_idx, n, self.log2m)}

    def empty(self, n):
        return {"regs": np.zeros((n, self.m), dtype=np.int32)}

    def scatter_merge(self, acc, idx, part):
        if "est" in part:
            raise AssertionError(
                "finalized HLL estimates are not mergeable — 'est' "
                "partials only occur on the terminal single-partial path")
        np.maximum.at(acc["regs"], idx, part["regs"])

    def finalize(self, part):
        if "est" in part:
            # terminal device path: estimated on device, registers never
            # crossed the host link
            return np.asarray(part["est"], dtype=np.int64)
        if len(part["regs"]) == 0:
            return np.zeros(0, dtype=np.int64)
        return hll_ops.estimate_batch_np(part["regs"])

    def result_type(self):
        return "LONG"


def bytes_planes(values, m: int) -> np.ndarray:
    """(n_rows, m) int32 register planes from a fixed-width BYTES column
    (np 'S<m>' array or object array of bytes). The numpy view recovers
    trailing zero registers that element access would strip."""
    arr = np.asarray(values)
    if arr.dtype.kind == "S":
        if arr.dtype.itemsize != m:
            raise ValueError(
                f"HLLMERGE state column width {arr.dtype.itemsize} != "
                f"register count {m} — was the cube built with a different "
                f"log2m?")
        return arr.view(np.uint8).reshape(len(arr), m).astype(np.int32)
    out = np.zeros((len(arr), m), dtype=np.int32)
    for i, b in enumerate(arr):
        if not isinstance(b, (bytes, bytearray)):
            raise ValueError(
                "HLLMERGE requires a BYTES column of HLL register planes "
                f"(got {type(b).__name__} values)")
        if len(b) > m:
            raise ValueError(
                f"HLLMERGE plane of {len(b)} bytes exceeds register "
                f"count {m}")
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


class HllMergeSpec(DistinctCountHLLSpec):
    """HLLMERGE(state_col[, log2m]): max-merge pre-aggregated HLL register
    planes (one fixed-width BYTES row = one int8 register plane) into the
    same canonical {"regs"} partial DISTINCTCOUNTHLL produces.

    This is the star-tree execution rewrite of DISTINCTCOUNTHLL over the
    cube's sketch column — the reference's DistinctCountHLLAggregationFunction
    byte[]-input merge path paired with DistinctCountHLLValueAggregator
    (pinot-segment-local/.../aggregator/DistinctCountHLLValueAggregator.java:1).
    """

    name = "hllmerge"

    def host_groups(self, arg_values, group_idx, n):
        planes = bytes_planes(arg_values[0], self.m)
        acc = np.zeros((n, self.m), dtype=np.int32)
        np.maximum.at(acc, np.asarray(group_idx), planes)
        return {"regs": acc}


def set_to_bytes(values) -> bytes:
    """Serialize a distinct-value set for a star-tree cube row
    (DistinctCountBitmapValueAggregator's serialized-RoaringBitmap role):
    json of the sorted values. JSON round-trips ints, floats, and strings
    exactly; trailing-NUL padding of the fixed-width BYTES column is safe
    because json text never ends in NUL."""
    import json

    return json.dumps(sorted(values, key=lambda x: (str(type(x)), x))).encode()


def set_from_bytes(blob) -> set:
    import json

    if not blob:
        return set()
    return set(json.loads(bytes(blob).rstrip(b"\x00").decode("utf-8")))


class BitmapMergeSpec(DistinctCountSpec):
    """BITMAPMERGE(state_col): union pre-aggregated distinct-value sets
    (one serialized set per cube row) into DistinctCountSpec's canonical
    {"sets"} partial — the star-tree execution rewrite of DISTINCTCOUNT /
    DISTINCTCOUNTBITMAP over the cube's state column (reference
    DistinctCountBitmapValueAggregator,
    pinot-segment-local/.../aggregator/DistinctCountBitmapValueAggregator.java:1).

    The state holds VALUES (not dict ids): cube segments from different
    parent segments have different dictionaries, so id-space planes could
    not merge across segments."""

    name = "bitmapmerge"

    def host_groups(self, arg_values, group_idx, n):
        sets = _obj_array(n, set)
        for g, blob in zip(np.asarray(group_idx).tolist(),
                           np.asarray(arg_values[0]).tolist()):
            sets[g] |= set_from_bytes(blob)
        return {"sets": sets}


class RawHLLSpec(DistinctCountHLLSpec):
    """DISTINCTCOUNTRAWHLL: serialized registers (base64) instead of the
    estimate, like the reference's serialized HyperLogLog blob."""

    name = "distinctcountrawhll"

    def finalize(self, part):
        import base64

        return np.asarray(
            [base64.b64encode(np.asarray(r, dtype=np.int8).tobytes())
             .decode("ascii") for r in part["regs"]], dtype=object)

    def result_type(self):
        return "STRING"


class PercentileSpec(AggSpec):
    """Percentile over a mergeable t-digest (merging variant,
    ops/quantile_digest.py) instead of the reference PERCENTILE's raw
    DoubleArrayList — bounded per-group state (≲2·compression centroids)
    shipped over the wire as (means, weights) lists, matching
    PercentileTDigestAggregationFunction's state algebra. Deliberate
    divergence: plain PERCENTILE is approximate here (rank error
    ~1/compression); O(matched rows) wire state was a scaling hazard the
    round-2 review flagged."""

    name = "percentile"
    compression = float(qd.DEFAULT_COMPRESSION)  # δ: <1% mid-range rank error

    def __init__(self, expr: Expression):
        super().__init__(expr)
        if len(expr.args) < 2 or not expr.args[1].is_literal:
            raise ValueError(f"{expr.name}(column, p) requires a literal p")
        self.p = float(expr.args[1].value)
        if len(expr.args) >= 3 and expr.args[2].is_literal:
            try:
                self.compression = float(expr.args[2].value)
            except (TypeError, ValueError):
                # a parameters STRING third arg (PERCENTILESMARTTDIGEST's
                # 'threshold=...') is accepted and ignored, not a crash
                pass
        self.args = expr.args[:1]

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0], dtype=np.float64)
        means = _obj_array(n, list)
        weights = _obj_array(n, list)
        if len(v):
            order = np.argsort(group_idx, kind="stable")
            gs = np.asarray(group_idx)[order]
            vs = v[order]
            bounds = np.flatnonzero(np.diff(gs)) + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(gs)]])
            for s, e in zip(starts, ends):
                g = int(gs[s])
                m, w = qd.add_values([], [], vs[s:e], self.compression)
                means[g] = m.tolist()
                weights[g] = w.tolist()
        return {"means": means, "weights": weights}

    def empty(self, n):
        return {"means": _obj_array(n, list), "weights": _obj_array(n, list)}

    def scatter_merge(self, acc, idx, part):
        for i, g in enumerate(idx):
            if not len(part["means"][i]):
                continue
            if not len(acc["means"][g]):
                acc["means"][g] = list(part["means"][i])
                acc["weights"][g] = list(part["weights"][i])
                continue
            m, w = qd.merge(acc["means"][g], acc["weights"][g],
                            part["means"][i], part["weights"][i],
                            self.compression)
            acc["means"][g] = m.tolist()
            acc["weights"][g] = w.tolist()

    def finalize(self, part):
        out = np.full(len(part["means"]), np.nan)
        for i, (m, w) in enumerate(zip(part["means"], part["weights"])):
            if len(m):
                out[i] = qd.quantile(m, w, self.p / 100.0)
        return out


class DistinctCountThetaSketchSpec(AggSpec):
    """DISTINCTCOUNTTHETASKETCH — mergeable KMV theta sketch
    (ops/theta.py), the role DataSketches' QuickSelect sketch plays in
    DistinctCountThetaSketchAggregationFunction.java. Two forms:

    - ``(col[, nominalEntries])``: one sketch per group; state is theta +
      <=k retained hashes.
    - ``(col, 'nominalEntries=K', filterExpr..., 'SET_INTERSECT($1,$2)')``
      — the reference's set-operation form: each quoted filter expression
      builds its OWN sketch over the matching rows ($1 is the first), the
      quoted LAST argument is a post-merge set expression
      (SET_INTERSECT / SET_UNION / SET_DIFF, nestable) evaluated at
      finalize. Filters evaluate per row through the engine's own
      expression registry, so any boolean-valued expression works. State
      per group: one (theta, hashes) pair per filter, keyed theta{i} /
      hashes{i} — each key is a wire-supported flat state, so partials
      ship over the DataTable like the single-sketch form."""

    name = "distinctcountthetasketch"

    def __init__(self, expr: Expression):
        from pinot_tpu.ops import theta as theta_ops

        super().__init__(expr)
        self.k = theta_ops.DEFAULT_NOMINAL
        args = expr.args
        if len(args) >= 2 and args[1].is_literal:
            v = args[1].value
            if isinstance(v, str):
                params_ok = self._parse_params(v)
                if not params_ok and len(args) >= 4:
                    # set form: a malformed params string is almost always
                    # a MISSING params string — treating a filter like
                    # 'dim = ''a''' as ignorable params would silently
                    # shift every $N reference one filter over
                    raise ValueError(
                        f"DISTINCTCOUNTTHETASKETCH set form: second "
                        f"argument must be a parameters string like "
                        f"'nominalEntries=4096' (or ''), got {v!r}")
            elif v is not None:
                self.k = int(v)
        self.filters = []
        self.set_expr = None
        if len(args) >= 4:
            from pinot_tpu.sql.parser import Parser

            for a in args[2:-1]:
                if not (a.is_literal and isinstance(a.value, str)):
                    raise ValueError(
                        "theta set form takes quoted filter expressions")
                self.filters.append(Parser(a.value).parse_expr())
            last = args[-1]
            if not (last.is_literal and isinstance(last.value, str)):
                raise ValueError(
                    "theta set form needs a quoted set expression last")
            self.set_expr = theta_ops.parse_set_expression(last.value)
            if theta_ops.max_ref(self.set_expr) >= len(self.filters):
                raise ValueError(
                    f"set expression references ${theta_ops.max_ref(self.set_expr) + 1} "
                    f"but only {len(self.filters)} filters are given")
            self.args = [args[0]] + self.filters
        elif len(args) == 3:
            # ambiguous: (col, params, X) — X can't be both the required
            # filter AND the required set expression. Silently ignoring it
            # would return an UNFILTERED count, so fail loudly.
            raise ValueError(
                "DISTINCTCOUNTTHETASKETCH set form needs at least one "
                "filter expression AND a set expression: "
                "(col, params, filterExpr..., 'SET_...($1,...)')")
        else:
            self.args = args[:1]

    _KNOWN_PARAMS = {"nominalentries", "samplingprobability",
                     "accumulatorthreshold"}

    def _parse_params(self, s: str) -> bool:
        """'nominalEntries=4096' style parameter string (';'/',' separated;
        empty allowed; a bare quoted integer is legacy nominalEntries).
        Returns False when the content doesn't look like parameters
        (unknown key, no '=') — the caller decides whether that's
        tolerable (legacy 2-arg form) or an error (set form)."""
        if s.strip().isdigit():  # legacy quoted form: ('4096')
            self.k = int(s)
            return True
        ok = True
        for kv in re.split(r"[;,]", s):
            if not kv.strip():
                continue
            key, eq, val = kv.partition("=")
            if not eq or key.strip().lower() not in self._KNOWN_PARAMS:
                ok = False
                continue
            if key.strip().lower() == "nominalentries" and val.strip():
                try:
                    self.k = int(val)
                except ValueError:
                    ok = False
        return ok

    def _sketch_keys(self):
        if not self.filters:
            return [("theta", "hashes")]
        return [(f"theta{i}", f"hashes{i}") for i in range(len(self.filters))]

    @staticmethod
    def _build_per_group(v, group_idx, n, k):
        from pinot_tpu.ops import theta as theta_ops

        thetas = np.full(n, float(theta_ops.MAX_HASH))
        hashes = _obj_array(n, list)
        if len(v):
            order = np.argsort(group_idx, kind="stable")
            gs = np.asarray(group_idx)[order]
            vs = np.asarray(v)[order]
            bounds = np.flatnonzero(np.diff(gs)) + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(gs)]])
            for s, e in zip(starts, ends):
                g = int(gs[s])
                th, h = theta_ops.build(vs[s:e], k)
                thetas[g] = float(th)
                hashes[g] = h.tolist()
        return thetas, hashes

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        gi = np.asarray(group_idx)
        if not self.filters:
            thetas, hashes = self._build_per_group(v, gi, n, self.k)
            return {"theta": thetas, "hashes": hashes}
        out = {}
        for i, (tk, hk) in enumerate(self._sketch_keys()):
            fmask = np.asarray(arg_values[1 + i], dtype=bool)
            thetas, hashes = self._build_per_group(
                v[fmask], gi[fmask], n, self.k)
            out[tk] = thetas
            out[hk] = hashes
        return out

    def empty(self, n):
        from pinot_tpu.ops import theta as theta_ops

        out = {}
        for tk, hk in self._sketch_keys():
            out[tk] = np.full(n, float(theta_ops.MAX_HASH))
            out[hk] = _obj_array(n, list)
        return out

    def scatter_merge(self, acc, idx, part):
        from pinot_tpu.ops import theta as theta_ops

        for tk, hk in self._sketch_keys():
            for i, g in enumerate(idx):
                if not len(part[hk][i]) \
                        and part[tk][i] >= float(theta_ops.MAX_HASH):
                    continue
                th, h = theta_ops.merge(
                    int(acc[tk][g]), np.asarray(acc[hk][g], np.int64),
                    int(part[tk][i]), np.asarray(part[hk][i], np.int64),
                    self.k,
                )
                acc[tk][g] = float(th)
                acc[hk][g] = h.tolist()

    def finalize(self, part):
        from pinot_tpu.ops import theta as theta_ops

        keys = self._sketch_keys()
        n = len(part[keys[0][0]])
        if self.set_expr is None:
            return np.array([
                round(theta_ops.estimate(int(t), h))
                for t, h in zip(part["theta"], part["hashes"])
            ], dtype=np.int64)
        out = np.empty(n, dtype=np.int64)
        for g in range(n):
            sketches = [
                (int(part[tk][g]), np.asarray(part[hk][g], np.int64))
                for tk, hk in keys
            ]
            th, h = theta_ops.evaluate_set(self.set_expr, sketches, self.k)
            out[g] = round(theta_ops.estimate(th, h))
        return out

    def result_type(self):
        return "LONG"


class PercentileTDigestSpec(PercentileSpec):
    """PERCENTILETDIGEST(col, p[, compression]) — same digest algebra with
    the reference's default compression (100)."""

    name = "percentiletdigest"
    compression = 100.0


class TDigestMergeSpec(PercentileSpec):
    """TDIGESTMERGE(state_col, p, compression): re-merge pre-aggregated
    t-digest blobs (one serialized digest per cube row) into the same
    canonical {"means","weights"} partial the percentile family produces —
    the star-tree execution rewrite of PERCENTILE/PERCENTILETDIGEST over
    the cube's digest column (reference PercentileTDigestValueAggregator,
    pinot-segment-local/.../aggregator/)."""

    name = "tdigestmerge"

    def host_groups(self, arg_values, group_idx, n):
        means = _obj_array(n, list)
        weights = _obj_array(n, list)
        digests: dict = {}
        for g, blob in zip(np.asarray(group_idx).tolist(),
                           np.asarray(arg_values[0]).tolist()):
            m2, w2 = qd.digest_from_bytes(blob)
            if not len(m2):
                continue
            if g in digests:
                m1, w1 = digests[g]
                digests[g] = qd.merge(m1, w1, m2, w2, self.compression)
            else:
                digests[g] = (m2, w2)
        for g, (m, w) in digests.items():
            means[g] = np.asarray(m).tolist()
            weights[g] = np.asarray(w).tolist()
        return {"means": means, "weights": weights}


class ModeSpec(AggSpec):
    name = "mode"

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        if v.dtype.kind not in "iuf":
            raise ValueError(
                "MODE requires a numeric column (reference ModeAggregationFunction "
                "supports INT/LONG/FLOAT/DOUBLE only)"
            )
        counters = _obj_array(n, dict)
        for g, val in zip(group_idx, v.tolist()):
            d = counters[g]
            d[val] = d.get(val, 0) + 1
        return {"counts": counters}

    def empty(self, n):
        return {"counts": _obj_array(n, dict)}

    def scatter_merge(self, acc, idx, part):
        for i, g in enumerate(idx):
            d = acc["counts"][g]
            for k, c in part["counts"][i].items():
                d[k] = d.get(k, 0) + c

    def finalize(self, part):
        out = np.full(len(part["counts"]), np.nan)
        for i, d in enumerate(part["counts"]):
            if d:
                # max count; ties broken by smallest value (reference default),
                # without float-coercing keys in the sort key
                best_count = max(d.values())
                out[i] = min(k for k, c in d.items() if c == best_count)
        return out


class FirstLastWithTimeSpec(AggSpec):
    """FIRSTWITHTIME/LASTWITHTIME(valueCol, timeCol[, 'dataType']): the
    value carried by the earliest/latest time per group — the argmin/
    argmax-by-time combine family
    (pinot-core/.../function/FirstWithTimeAggregationFunction.java:1,
    LastWithTimeAggregationFunction.java:1).

    Deliberate divergence: ties on the winning time break toward the
    LARGEST value (the reference keeps whichever replica/segment merged
    last — stream-order-dependent). A deterministic, associative rule is
    required here so host scatter, device scatter, and the mesh's
    pmin/pmax-pair combine (parallel/mesh.py) all agree bit-for-bit.

    State: per-group (val, time); float values ride float64 arrays,
    INTEGER value columns ride an object array of exact Python ints on
    the host path (ADVICE r5: the old astype(float64) rounded LONG values
    with |v| > 2^53 — the winning TIME was always exact, the VALUE was
    not), STRING dataType rides an object array. The device path's value
    plane remains float64 (PARITY.md documents that divergence); a device
    partial merging into a host accumulator keeps whatever exactness each
    side produced."""

    _T_MAX = np.iinfo(np.int64).max
    _T_MIN = np.iinfo(np.int64).min

    def __init__(self, expr: Expression, is_first: bool):
        super().__init__(expr)
        self.is_first = is_first
        self.name = "firstwithtime" if is_first else "lastwithtime"
        if len(expr.args) < 2:
            raise ValueError(
                f"{self.name.upper()}(valueCol, timeCol[, 'dataType']) "
                "requires value and time expressions")
        self.data_type = "DOUBLE"
        if len(expr.args) >= 3 and expr.args[2].is_literal:
            self.data_type = str(expr.args[2].value).upper()
        # args: (valueCol, timeCol[, 'dataType'])
        self.args = expr.args[:2]

    @property
    def _sentinel(self):
        return self._T_MAX if self.is_first else self._T_MIN

    @staticmethod
    def _val_gt(a, b):
        """Tie-break comparison with None = -inf (empty slot loses)."""
        if b is None:
            return a is not None
        if a is None:
            return False
        try:
            if np.isnan(b):
                return True
            if np.isnan(a):
                return False
        except TypeError:
            pass  # strings
        return a > b

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        if v.dtype.kind == "f":
            v = v.astype(np.float64)
            val = np.full(n, np.nan)
        else:
            # exact value plane: integer columns become Python ints
            # (arbitrary precision — LONG |v| > 2^53 survives exactly),
            # strings stay objects; empty slots are None either way
            val = np.empty(n, dtype=object)
            val[:] = None
        t = np.asarray(arg_values[1], dtype=np.int64)
        tim = np.full(n, self._sentinel, dtype=np.int64)
        for g, vv, tt in zip(group_idx, v.tolist(), t):
            better = tt < tim[g] if self.is_first else tt > tim[g]
            if better or (tt == tim[g] and self._val_gt(vv, val[g])):
                tim[g] = tt
                val[g] = vv
        return {"val": val, "time": tim}

    def empty(self, n):
        return {
            "val": np.full(n, np.nan),
            "time": np.full(n, self._sentinel, dtype=np.int64),
        }

    def scatter_merge(self, acc, idx, part):
        pv = np.asarray(part["val"])
        if pv.dtype == object and acc["val"].dtype != object:
            # string-valued partials arriving into a fresh numeric-empty
            # accumulator: promote (one value type per query — segments of
            # one column can't mix string and numeric)
            promoted = np.empty(len(acc["val"]), dtype=object)
            for j, x in enumerate(acc["val"]):
                promoted[j] = None if (isinstance(x, float) and np.isnan(x)) else x
            acc["val"] = promoted
        for i, g in enumerate(idx):
            tt = part["time"][i]
            better = tt < acc["time"][g] if self.is_first else tt > acc["time"][g]
            vv = pv[i]
            if isinstance(vv, list):
                # wire artifact: an ALL-None object val array round-trips
                # as empty lists (datatable list fallback) — restore None
                vv = vv[0] if vv else None
            if isinstance(vv, float) and np.isnan(vv) and tt == self._sentinel:
                continue  # empty slot in the partial
            if better or (tt == acc["time"][g] and self._val_gt(vv, acc["val"][g])):
                acc["time"][g] = tt
                acc["val"][g] = vv

    def finalize(self, part):
        out = np.asarray(part["val"])
        # the declared dataType shapes the output (result typing is
        # runtime-dtype-based, reduce._np_type_name): an integral
        # declaration renders LONG/INT unless empty groups force NaN
        # (NULL) into the column
        integral = self.data_type in ("INT", "LONG", "BOOLEAN", "TIMESTAMP")
        if out.dtype == object and len(out):
            # exact int plane (host_groups) — possibly mixed with float64
            # values merged in from a device partial
            vals = out.tolist()
            if all(v is None or isinstance(v, (int, float, np.integer,
                                               np.floating)) for v in vals):
                has_null = any(
                    v is None or (isinstance(v, float) and np.isnan(v))
                    for v in vals)
                if integral and not has_null:
                    # the exact path: LONG |v| > 2^53 renders bit-exact
                    return np.array([int(v) for v in vals], dtype=np.int64)
                return np.array(
                    [np.nan if v is None else float(v) for v in vals],
                    dtype=np.float64)
        if integral and out.dtype.kind == "f" and len(out) \
                and not np.isnan(out).any():
            return out.astype(np.int64)
        return out

    def result_type(self):
        if self.data_type in ("INT", "LONG", "FLOAT", "DOUBLE", "STRING",
                              "BOOLEAN", "TIMESTAMP"):
            return self.data_type
        return "DOUBLE"


class FirstWithTimeSpec(FirstLastWithTimeSpec):
    name = "firstwithtime"

    def __init__(self, expr: Expression):
        super().__init__(expr, is_first=True)


class LastWithTimeSpec(FirstLastWithTimeSpec):
    name = "lastwithtime"

    def __init__(self, expr: Expression):
        super().__init__(expr, is_first=False)


class _MVEntrySpec(AggSpec):
    """Shared shape for MV aggregations that fold per-entry values: expand
    the group index per entry and delegate to the SV spec's state algebra
    (reference: SumMVAggregationFunction et al. iterate getDictIdMV)."""

    mv = True
    sv_base: type = None  # parent SV spec class

    def host_groups(self, arg_values, group_idx, n):
        vals, lens = arg_values[0]
        g = np.repeat(group_idx, lens)
        return self.sv_base.host_groups(self, [vals], g, n)


class SumMVSpec(_MVEntrySpec, SumSpec):
    name = "summv"
    sv_base = SumSpec


class MinMVSpec(_MVEntrySpec, MinSpec):
    name = "minmv"
    sv_base = MinSpec


class MaxMVSpec(_MVEntrySpec, MaxSpec):
    name = "maxmv"
    sv_base = MaxSpec


class AvgMVSpec(_MVEntrySpec, AvgSpec):
    name = "avgmv"
    sv_base = AvgSpec


class DistinctCountMVSpec(_MVEntrySpec, DistinctCountSpec):
    name = "distinctcountmv"
    sv_base = DistinctCountSpec


class SumPrecisionSpec(AggSpec):
    """SUMPRECISION: exact arbitrary-precision sum
    (SumPrecisionAggregationFunction / BigDecimal analog) — Python ints and
    Decimals in object arrays, result as a string like the reference's
    BigDecimal rendering."""

    name = "sumprecision"

    def __init__(self, expr: Expression):
        super().__init__(expr)
        self.args = expr.args[:1]

    @staticmethod
    def _exact(v):
        import decimal

        if isinstance(v, int):
            return v  # already exact: never round-trip through float
        f = float(v)
        if f.is_integer():
            return int(f)
        return decimal.Decimal(repr(f))

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        sums = _obj_array(n, int)
        for g, x in zip(group_idx, v.tolist()):
            sums[g] = sums[g] + self._exact(x)
        return {"psum": sums}

    def empty(self, n):
        return {"psum": _obj_array(n, int)}

    def scatter_merge(self, acc, idx, part):
        for i, g in enumerate(idx):
            acc["psum"][g] = acc["psum"][g] + part["psum"][i]

    def finalize(self, part):
        return np.asarray([str(x) for x in part["psum"]], dtype=object)

    def result_type(self):
        return "STRING"


class SumPrecisionMergeSpec(SumPrecisionSpec):
    """SUMPRECISIONMERGE(state_col): exact re-sum of pre-aggregated
    decimal-string partial sums (one per cube row) — the star-tree rewrite
    of SUMPRECISION (reference SumPrecisionValueAggregator,
    pinot-segment-local/.../aggregator/SumPrecisionValueAggregator.java:1)."""

    name = "sumprecisionmerge"

    @staticmethod
    def _parse(blob):
        import decimal

        s = (bytes(blob).rstrip(b"\x00").decode("ascii")
             if isinstance(blob, (bytes, bytearray)) else str(blob))
        if not s:
            return 0
        return int(s) if ("." not in s and "E" not in s.upper()) \
            else decimal.Decimal(s)

    def host_groups(self, arg_values, group_idx, n):
        sums = _obj_array(n, int)
        for g, blob in zip(np.asarray(group_idx).tolist(),
                           np.asarray(arg_values[0]).tolist()):
            sums[g] = sums[g] + self._parse(blob)
        return {"psum": sums}


class IdSetSpec(DistinctCountSpec):
    """IDSET: serialized set of ids (IdSetAggregationFunction analog) —
    base64(gzip(json(sorted values))) instead of a RoaringBitmap blob.
    Shares DistinctCountSpec's set-union state algebra; only the final
    rendering differs."""

    name = "idset"

    def finalize(self, part):
        import base64
        import gzip
        import json

        out = np.empty(len(part["sets"]), dtype=object)
        for i, s in enumerate(part["sets"]):
            blob = gzip.compress(
                json.dumps(sorted(s, key=str)).encode("utf-8"))
            out[i] = base64.b64encode(blob).decode("ascii")
        return out

    def result_type(self):
        return "STRING"


class SmartHLLSpec(AggSpec):
    """DISTINCTCOUNTSMARTHLL: exact set up to a threshold, HLL beyond
    (DistinctCountSmartHLLAggregationFunction) — the memory-bounding
    auto-switch, per group. State: ('set', set) or ('hll', registers)."""

    name = "distinctcountsmarthll"
    DEFAULT_THRESHOLD = 100_000

    def __init__(self, expr: Expression, log2m: int = hll_ops.DEFAULT_LOG2M):
        super().__init__(expr)
        self.threshold = self.DEFAULT_THRESHOLD
        if len(expr.args) > 1 and expr.args[1].is_literal:
            # reference takes a parameters string; accept a numeric
            # threshold literal
            try:
                self.threshold = int(expr.args[1].value)
            except (TypeError, ValueError):
                pass
        self.log2m = log2m
        self.args = expr.args[:1]

    def _to_hll(self, s: set) -> np.ndarray:
        v = np.asarray(list(s))
        return hll_ops.registers_np(v, np.zeros(len(v), dtype=np.int64),
                                    1, self.log2m)[0]

    def _shrink(self, state):
        kind, payload = state
        if kind == "set" and len(payload) > self.threshold:
            return ("hll", self._to_hll(payload))
        return state

    def host_groups(self, arg_values, group_idx, n):
        v = np.asarray(arg_values[0])
        states = _obj_array(n, lambda: ("set", set()))
        for g, x in zip(group_idx, v.tolist()):
            kind, payload = states[g]
            if kind == "set":
                payload.add(x)
        for i in range(n):
            states[i] = self._shrink(states[i])
        return {"smart": states}

    def empty(self, n):
        return {"smart": _obj_array(n, lambda: ("set", set()))}

    def scatter_merge(self, acc, idx, part):
        for i, g in enumerate(idx):
            ak, ap = acc["smart"][g]
            pk, pp = part["smart"][i]
            if ak == "set" and pk == "set":
                acc["smart"][g] = self._shrink(("set", ap | pp))
            elif ak == "hll" and pk == "hll":
                acc["smart"][g] = ("hll", np.maximum(ap, pp))
            else:
                regs = ap if ak == "hll" else pp
                s = pp if ak == "hll" else ap
                if s:
                    regs = np.maximum(regs, self._to_hll(s))
                acc["smart"][g] = ("hll", regs)

    def finalize(self, part):
        out = np.zeros(len(part["smart"]), dtype=np.int64)
        for i, (kind, payload) in enumerate(part["smart"]):
            out[i] = len(payload) if kind == "set" \
                else hll_ops.estimate(payload)
        return out

    def result_type(self):
        return "LONG"


class STUnionSpec(DistinctCountSpec):
    """ST_UNION over POINT geographies: MULTIPOINT of the distinct points
    (STUnionAggregationFunction's role; JTS union collapses to the same
    for point inputs). Set-union state algebra inherited from
    DistinctCountSpec; only the final rendering differs."""

    name = "stunion"

    def finalize(self, part):
        from pinot_tpu.ops.geo import parse_points

        out = np.empty(len(part["sets"]), dtype=object)
        for i, s in enumerate(part["sets"]):
            lon, lat = parse_points(sorted(str(w) for w in s))
            pts = ", ".join(f"{x:.10g} {y:.10g}"
                            for x, y in zip(lon, lat) if not np.isnan(x))
            out[i] = f"MULTIPOINT ({pts})" if pts else "MULTIPOINT EMPTY"
        return out

    def result_type(self):
        return "STRING"


class RawDigestPercentileSpec(PercentileTDigestSpec):
    """PERCENTILERAWTDIGEST/PERCENTILERAWEST: return the serialized digest
    instead of the quantile (base64 json of (means, weights) — the role of
    the reference's serialized TDigest/QuantileDigest blobs). Inherits the
    tdigest family's compression (100)."""

    def finalize(self, part):
        import base64
        import json

        out = np.empty(len(part["means"]), dtype=object)
        for i, (m, w) in enumerate(zip(part["means"], part["weights"])):
            blob = json.dumps({"means": list(m), "weights": list(w),
                               "compression": self.compression})
            out[i] = base64.b64encode(blob.encode("utf-8")).decode("ascii")
        return out

    def result_type(self):
        return "STRING"


class MinMaxRangeMVSpec(_MVEntrySpec, MinMaxRangeSpec):
    name = "minmaxrangemv"
    sv_base = MinMaxRangeSpec


class DistinctCountHLLMVSpec(_MVEntrySpec, DistinctCountHLLSpec):
    name = "distinctcounthllmv"
    sv_base = DistinctCountHLLSpec


class PercentileMVSpec(_MVEntrySpec, PercentileSpec):
    name = "percentilemv"
    sv_base = PercentileSpec


class PercentileTDigestMVSpec(_MVEntrySpec, PercentileTDigestSpec):
    name = "percentiletdigestmv"
    sv_base = PercentileTDigestSpec


class RawDigestPercentileMVSpec(_MVEntrySpec, RawDigestPercentileSpec):
    """PERCENTILERAWEST_MV / PERCENTILERAWTDIGEST_MV: serialized digest
    over MV entry values (the last two names of the reference's
    AggregationFunctionType enum missing here)."""

    name = "percentilerawtdigestmv"
    sv_base = RawDigestPercentileSpec


class RawHLLMVSpec(_MVEntrySpec, RawHLLSpec):
    name = "distinctcountrawhllmv"
    sv_base = RawHLLSpec


class CountMVSpec(AggSpec):
    """COUNTMV: total MV entries per group (not docs)."""

    name = "countmv"
    mv = True

    def __init__(self, expr: Expression):
        super().__init__(expr)
        self.args = expr.args[:1]

    def host_groups(self, arg_values, group_idx, n):
        _, lens = arg_values[0]
        c = np.zeros(n, dtype=np.int64)
        np.add.at(c, group_idx, lens)
        return {"count": c}

    def empty(self, n):
        return {"count": np.zeros(n, dtype=np.int64)}

    def scatter_merge(self, acc, idx, part):
        np.add.at(acc["count"], idx, part["count"])

    def finalize(self, part):
        return part["count"]

    def result_type(self):
        return "LONG"


_SPECS = {
    "count": CountSpec,
    "sum": SumSpec,
    "min": MinSpec,
    "max": MaxSpec,
    "avg": AvgSpec,
    "minmaxrange": MinMaxRangeSpec,
    "distinctcount": DistinctCountSpec,
    "distinctcountbitmap": DistinctCountSpec,  # same exact semantics
    "segmentpartitioneddistinctcount": DistinctCountSpec,
    "distinctcounthll": DistinctCountHLLSpec,
    "hllmerge": HllMergeSpec,
    "tdigestmerge": TDigestMergeSpec,
    "bitmapmerge": BitmapMergeSpec,
    "sumprecisionmerge": SumPrecisionMergeSpec,
    "distinctcountthetasketch": DistinctCountThetaSketchSpec,
    "distinctcountrawthetasketch": DistinctCountThetaSketchSpec,
    "percentile": PercentileSpec,
    "percentileest": PercentileSpec,
    "percentiletdigest": PercentileTDigestSpec,
    "percentilesmarttdigest": PercentileTDigestSpec,
    "percentilerawest": RawDigestPercentileSpec,
    "percentilerawtdigest": RawDigestPercentileSpec,
    "mode": ModeSpec,
    "firstwithtime": FirstWithTimeSpec,
    "lastwithtime": LastWithTimeSpec,
    "sumprecision": SumPrecisionSpec,
    "idset": IdSetSpec,
    "distinctcountsmarthll": SmartHLLSpec,
    "fasthll": DistinctCountHLLSpec,  # deprecated legacy alias upstream
    "distinctcountrawhll": RawHLLSpec,
    "stunion": STUnionSpec,
    "st_union": STUnionSpec,
    "summv": SumMVSpec,
    "minmv": MinMVSpec,
    "maxmv": MaxMVSpec,
    "avgmv": AvgMVSpec,
    "countmv": CountMVSpec,
    "distinctcountmv": DistinctCountMVSpec,
    "distinctcountbitmapmv": DistinctCountMVSpec,  # same exact semantics
    "minmaxrangemv": MinMaxRangeMVSpec,
    "distinctcounthllmv": DistinctCountHLLMVSpec,
    "distinctcountrawhllmv": RawHLLMVSpec,
    "percentilemv": PercentileMVSpec,
    "percentileestmv": PercentileMVSpec,
    "percentiletdigestmv": PercentileTDigestMVSpec,
    "percentilerawestmv": RawDigestPercentileMVSpec,
    "percentilerawtdigestmv": RawDigestPercentileMVSpec,
}


def make_spec(expr: Expression) -> AggSpec:
    name = expr.name
    cls = _SPECS.get(name)
    if cls is None:
        raise KeyError(f"unsupported aggregation function: {name}")
    return cls(expr)
