"""Query engine entry point: SQL → response, over locally-held segments.

Mirrors the reference's in-process server execution path
(ServerQueryExecutorV1Impl.java:120-133 — acquire segments, prune, plan,
execute, build response) plus the broker reduce, the way the reference's
query-correctness fixture runs both in one process (BaseQueriesTest.java).

Backend selection: the device (JAX) executor handles the accelerated shapes;
anything it reports as unsupported falls back to the host numpy path — the
moral equivalent of the reference falling back from index-based to
scan-based operators (FilterOperatorUtils.java:165-194).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from pinot_tpu.common.deadline import QueryTimeout
from pinot_tpu.engine.host import HostExecutor
from pinot_tpu.engine.reduce import finalize, merge_intermediates
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    PredicateType,
    QueryContext,
)
from pinot_tpu.query.optimizer import optimize_query
from pinot_tpu.sql.compiler import compile_select
from pinot_tpu.storage.segment import ImmutableSegment

log = logging.getLogger("pinot_tpu.engine")


class SegmentPruner:
    """Server-side pruning on column metadata min/max + bloom filters
    (query/pruner/ColumnValueSegmentPruner.java analog)."""

    def prune(self, q: QueryContext, seg: ImmutableSegment) -> bool:
        """True → segment cannot match; skip it."""
        f = q.filter
        if f is None:
            return False
        return self._cannot_match(f, seg)

    def _cannot_match(self, f: FilterNode, seg: ImmutableSegment) -> bool:
        if f.type is FilterNodeType.CONSTANT_FALSE:
            return True
        if f.type is FilterNodeType.AND:
            return any(self._cannot_match(c, seg) for c in f.children)
        if f.type is FilterNodeType.OR:
            return all(self._cannot_match(c, seg) for c in f.children)
        if f.type is not FilterNodeType.PREDICATE:
            return False
        p = f.predicate
        if not p.lhs.is_identifier or p.lhs.name not in seg.metadata.columns:
            return False
        meta = seg.column_metadata(p.lhs.name)
        # min/max interval exclusion: the SAME algebra the broker prunes
        # routing with (common/pruning.py) — strict about incomparable
        # literals, so a mis-typed literal surfaces from the scan instead
        # of silently pruning to empty
        from pinot_tpu.common.pruning import interval_may_match

        if p.type in (PredicateType.EQ, PredicateType.IN,
                      PredicateType.RANGE):
            if not interval_may_match(p, meta.min_value, meta.max_value):
                return True
        if p.type is PredicateType.EQ and \
                self._provably_absent(seg, p.lhs.name, [p.value]):
            return True
        if p.type is PredicateType.IN and p.values and \
                self._provably_absent(seg, p.lhs.name, list(p.values)):
            return True
        return False

    @staticmethod
    def _provably_absent(seg, col: str, values: list) -> bool:
        from pinot_tpu.common.pruning import provably_absent

        return provably_absent(seg, col, values)



class TableDataManager:
    """Segments of one table (data/manager/offline/OfflineTableDataManager
    analog): acquire/release refcounting so an unload (retention, minion
    swap, rebalance) during an in-flight query defers teardown — the
    reference's ``acquireSegment``/``releaseSegment`` on TableDataManager.
    ``on_unload`` fires once the last reference drains (the server deletes
    its local working copy there)."""

    def __init__(self, name: str, host_name: Optional[str] = None):
        self.name = name
        self.segments: dict[str, ImmutableSegment] = {}
        self._refs: dict[str, int] = {}
        self._doomed: dict[str, ImmutableSegment] = {}
        self._lock = threading.Lock()
        self.on_unload = None  # callback(segment) after last ref drops
        self.host_name = host_name  # stamps $hostName on hosted segments
        self.generation = 0  # bumped on add/remove; dim-lookup cache key
        # None = unknown (embedded engines allow LOOKUP on any local table);
        # the server layer sets True/False from the registry's TableConfig
        self.is_dim_table = None

    def add_segment(self, seg: ImmutableSegment) -> None:
        if self.host_name is not None and getattr(seg, "host_name", None) is None:
            seg.host_name = self.host_name
        with self._lock:
            self.segments[seg.name] = seg
            self.generation += 1
            self._doomed.pop(seg.name, None)  # re-add wins over unload

    def replace_if_idle(self, name: str, seg) -> bool:
        """Atomically swap the hosted object for ``name`` when NO query
        holds a reference (tier transitions, server/tiering.py): an
        in-flight scan must never lose its mmaps mid-query, so a held
        reference refuses the swap (False — the caller retries next
        tick). The doomed map is untouched: a swap is not an unload."""
        with self._lock:
            if name not in self.segments or self._refs.get(name, 0) > 0:
                return False
            if self.host_name is not None \
                    and getattr(seg, "host_name", None) is None:
                seg.host_name = self.host_name
            self.segments[name] = seg
            self.generation += 1
            return True

    def remove_segment(self, name: str) -> None:
        with self._lock:
            seg = self.segments.pop(name, None)
            if seg is None:
                return
            self.generation += 1
            if self._refs.get(name, 0) > 0:
                self._doomed[name] = seg  # teardown deferred to release()
                return
            self._refs.pop(name, None)
        self._fire_unload(seg)

    def acquire(self) -> list:
        with self._lock:
            segs = list(self.segments.values())
            for s in segs:
                self._refs[s.name] = self._refs.get(s.name, 0) + 1
            return segs

    def release(self, segments) -> None:
        to_unload = []
        with self._lock:
            for s in segments:
                left = self._refs.get(s.name, 1) - 1
                if left > 0:
                    self._refs[s.name] = left
                    continue
                self._refs.pop(s.name, None)
                doomed = self._doomed.pop(s.name, None)
                if doomed is not None:
                    to_unload.append(doomed)
        for seg in to_unload:
            self._fire_unload(seg)

    def _fire_unload(self, seg) -> None:
        if self.on_unload is not None:
            try:
                self.on_unload(seg)
            except Exception:  # noqa: BLE001 — unload cleanup is best-effort
                log.exception("segment unload callback failed for %s", seg.name)


class QueryEngine:
    """SQL in, response out, over in-process tables."""

    def __init__(self, device_executor="auto", num_groups_limit: int = 100_000,
                 host_name: Optional[str] = None):
        self.tables: dict[str, TableDataManager] = {}
        self.host_name = host_name  # server instance id for $hostName
        self.host = HostExecutor(num_groups_limit=num_groups_limit)
        self.pruner = SegmentPruner()
        if device_executor == "auto":
            from pinot_tpu.engine.device import DeviceExecutor

            device_executor = DeviceExecutor(num_groups_limit=num_groups_limit)
        self.device = device_executor  # None → host-only
        self._dim_cache: dict = {}  # (table, pk, val) -> (generation, map)
        self.host.lookup_resolver = self.dim_table_lookup

    # ---- table management -----------------------------------------------
    def table(self, name: str) -> TableDataManager:
        if name not in self.tables:
            self.tables[name] = TableDataManager(name, host_name=self.host_name)
        return self.tables[name]

    def add_segment(self, table: str, seg: ImmutableSegment) -> None:
        self.table(table).add_segment(seg)

    # ---- query -----------------------------------------------------------
    def execute(self, sql: str) -> dict:
        """Full path: SQL string → broker-response dict. Join / window
        queries route to the multi-stage engine (query2/); plain
        single-table queries take the single-stage path untouched."""
        t0 = time.time()
        try:
            from pinot_tpu.sql.compiler import is_multistage
            from pinot_tpu.sql.parser import parse_sql

            stmt = parse_sql(sql)
            if is_multistage(stmt):
                from pinot_tpu.query2.runner import execute_multistage

                return execute_multistage(self, stmt, t0)
            q = optimize_query(compile_select(stmt))
            if q.explain:
                if q.analyze:
                    return self._explain_analyze(q, t0)
                return self._explain(q)
            result, merged = self._execute_merged(q)
        except Exception as e:  # noqa: BLE001 — reference returns exceptions in-band
            return {"exceptions": [{"errorCode": 200, "message": f"{type(e).__name__}: {e}"}]}
        return self._stats_response(result, merged, t0)

    @staticmethod
    def _stats_response(result, merged, t0: float) -> dict:
        """Broker-response-shaped dict from a finalized result + merged
        intermediate (the one shared by execute and EXPLAIN ANALYZE)."""
        stats = merged.stats
        resp = result.to_json()
        resp.update(
            {
                "exceptions": [],
                "numDocsScanned": stats.num_docs_scanned,
                "numEntriesScannedInFilter": stats.num_entries_scanned_in_filter,
                "numEntriesScannedPostFilter": stats.num_entries_scanned_post_filter,
                "numSegmentsQueried": stats.num_segments_queried,
                "numSegmentsProcessed": stats.num_segments_processed,
                "numSegmentsMatched": stats.num_segments_matched,
                "numSegmentsPrunedByServer": stats.num_segments_pruned,
                "numBlocksPruned": stats.num_blocks_pruned,
                # cold-tier segments that answered as in-flight partials
                # while their deep-store download proceeds (ISSUE 12)
                "numSegmentsCold": stats.num_segments_cold,
                "numGroupsLimitReached": stats.num_groups_limit_reached,
                "partialsCacheHit": stats.partials_cache_hit,
                "totalDocs": stats.total_docs,
                # kernel roofline accounting (ISSUE 11)
                "deviceBytesMoved": stats.device_bytes_moved,
                "deviceKernelMs": round(stats.device_kernel_ms, 3),
                "deviceLinkMs": round(stats.device_link_ms, 3),
                "timeUsedMs": round((time.time() - t0) * 1000, 3),
            }
        )
        if getattr(merged, "roofline", None):
            resp["roofline"] = merged.roofline
        if stats.advisor_decisions:
            # plan-advisor stamps (ISSUE 17): every measurement-driven
            # override this execution ran with, for responses / querylog
            # / EXPLAIN ANALYZE
            resp["advisorDecisions"] = list(stats.advisor_decisions)
        return resp

    def execute_query(self, q: QueryContext, tracer=None):
        result, merged = self._execute_merged(q, tracer=tracer)
        return result, merged.stats

    def _execute_merged(self, q: QueryContext, tracer=None):
        """(finalized ResultTable, merged IntermediateResult) — the inner
        execute path; keeps the merged result (trace/roofline/stat
        leaves) available to callers that render more than rows."""
        tdm = self.tables.get(q.table_name)
        if tdm is None:
            raise KeyError(f"table {q.table_name!r} not found")
        segments = tdm.acquire()
        try:
            if not segments:
                raise ValueError(f"table {q.table_name!r} has no segments")
            merged = self.execute_segments_async(
                q, segments, terminal=True, tracer=tracer)()
            q = self._expand_star(q, segments[0])
            return finalize(q, merged), merged
        finally:
            tdm.release(segments)

    def execute_segments(self, q: QueryContext, segments, terminal: bool = False,
                         trim_ok: bool = True):
        """Server-side partial execution over an explicit segment list →
        merged (unfinalized) IntermediateResult — what a server ships to the
        broker as a DataTable (ServerQueryExecutorV1Impl.processQuery).

        ``terminal=True`` (the local execute_query path): nothing upstream
        will merge this result, so when the device batch is the SOLE
        partial, sketch aggregations may finalize on device and skip
        shipping G×m mergeable state over the host link. Server-shipped
        partials stay mergeable (the broker combines them).

        ``trim_ok=False`` disables the on-device final reduce for callers
        whose finalize runs under a DIFFERENT QueryContext than the one
        executed here (star-tree substitution plans)."""
        return self.execute_segments_async(q, segments, terminal,
                                           trim_ok=trim_ok)()

    def execute_segments_async(self, q: QueryContext, segments,
                               terminal: bool = False, fallback_gate=None,
                               deadline=None, tracer=None,
                               trim_ok: bool = True):
        """LAUNCH phase of execute_segments → zero-arg fetch() closure.

        ``tracer`` (common/trace.py Tracer, optional): the query's
        explicit trace object, carried BY REFERENCE through the device
        launch handles and into the returned fetch closure — spans
        recorded during the deferred fetch (possibly another thread) or
        inside a coalesced cohort land on this query's trace, never on
        whatever tracer the executing thread happens to hold.

        ``deadline`` (common/deadline.py Deadline, optional): the query's
        propagated end-to-end budget. Checked before each host segment
        scan, before each blocking device fetch, and before each
        host-fallback re-scan — an expired budget aborts with a typed
        QueryTimeout (releasing every still-pinned in-flight launch)
        instead of finishing work the client already abandoned.

        TIER SPLIT (ISSUE 12, server/tiering.py): cold segments
        (``is_cold`` placeholders whose planes live only in the deep
        store) are split out FIRST — each counts as ``numSegmentsCold``
        in the merged stats and its ``touch()`` enqueues an asynchronous
        hydration, so the query returns an honest in-flight partial
        instead of blocking its scheduler slot on a download. Warm
        segments fail ``segment_device_eligible`` and take the host
        scan path over their lazily-mmap'd planes; hot segments ride
        the device batch exactly as before.

        Everything CPU-bound runs here — pruning, star-tree/metadata fast
        paths, the device template build + NON-BLOCKING dispatch
        (DeviceExecutor.launch), and the host scan partials (which overlap
        the device launch's link round trip). The returned closure does
        only the blocking device fetch + merge, so a server can release
        its scheduler slot before the host↔device round trip and N
        concurrent queries overlap their link waits (server/server.py
        _handle_submit). Fetch-time device fallbacks (sorted group-table
        overflow) re-run the device batch on the host inside the closure;
        ``fallback_gate`` (callable(fn) → fn()) wraps THAT re-run so a
        server can put the heavy host scan back under scheduler admission
        — the fetch phase itself runs slot-free by design, and without
        the gate a fallback storm would escape the concurrency cap."""
        all_segments = segments
        cold_refs = [s for s in segments if getattr(s, "is_cold", False)]
        if cold_refs:
            segments = [s for s in segments
                        if not getattr(s, "is_cold", False)]
            for s in cold_refs:
                touch = getattr(s, "touch", None)
                if touch is not None:
                    touch()  # async hydration; never blocks this query
        q = self._expand_star(q, (segments or cold_refs)[0])

        from pinot_tpu.common.trace import span
        from pinot_tpu.engine.device import DeviceUnsupported, \
            segment_device_eligible

        results = []
        executed = []
        scan = []
        scan_pruned: set = set()  # id(s) of scan segments the pruner excluded
        pruned = 0                # segments dropped HERE (non-device paths)
        if segments:
            # per-segment fast paths first: metadata-only aggregation, then
            # star-tree substitution (AggregationPlanNode.java:186-210).
            # Star-tree-eligible segments are GROUPED by tree signature and
            # executed as one batch — a single device launch over all
            # pre-aggregated child segments.
            from pinot_tpu.engine.startree_exec import (
                execute_star_tree_group,
                fitting_tree,
                try_metadata_only,
            )

            remaining = []
            st_groups: dict = {}
            for s in segments:
                is_pruned = self.pruner.prune(q, s)
                if not is_pruned:
                    r = try_metadata_only(q, s)
                    if r is not None:
                        results.append(r)
                        executed.append(s)
                        continue
                hit = fitting_tree(q, s)
                if hit is not None:
                    if is_pruned:
                        pruned += 1
                        continue
                    sig, meta, st_seg = hit
                    grp = st_groups.setdefault(sig, {"meta": meta, "sts": [], "docs": 0})
                    grp["sts"].append(st_seg)
                    grp["docs"] += s.n_docs
                    executed.append(s)
                    continue
                if is_pruned:
                    # device-eligible sealed segments STAY in the scan batch,
                    # alive-masked at launch (DeviceExecutor Level-1) — the
                    # (S, L) batch key, its compiled templates, and the
                    # cohort coalescer key must not depend on which filter
                    # literals pruned what. Other backends drop them here.
                    if not (self.device is not None
                            and segment_device_eligible(s)):
                        pruned += 1
                        continue
                    scan_pruned.add(id(s))
                remaining.append(s)
                executed.append(s)
            # a lone star-tree group with nothing to merge against stays
            # terminal: its cube execution may finalize sketches on device
            st_terminal = (terminal and not results and not remaining
                           and len(st_groups) == 1)
            for grp in st_groups.values():
                results.append(
                    execute_star_tree_group(self, q, grp["meta"], grp["sts"],
                                            grp["docs"], terminal=st_terminal)
                )
            scan = remaining
        device_handles, host_results = [], []
        if scan:
            # consuming (mutable) and upsert-masked segments run on the host
            # scan path; sealed immutables go to the device in one batch.
            # A consuming segment with PROMOTED CHUNKLETS splits: the clean
            # frozen-prefix blocks go to the device, the unfrozen row tail
            # (+ any upsert-dirtied blocks, mask applied) stays on the
            # host, and the partials merge below like any backend mix
            # (realtime/chunklet.py). Chunklets launch as their OWN device
            # batch: promotion changes the chunklet set every 64k rows, and
            # a combined batch key would evict + re-upload the (stable)
            # sealed columns on every promotion.
            from pinot_tpu.realtime.chunklet import split_for_query

            device_sealed, device_chunklets, host_segs = [], [], []
            for s in scan:
                if segment_device_eligible(s):
                    device_sealed.append(s)
                    continue
                split = split_for_query(s) if self.device is not None else None
                if split is None:
                    host_segs.append(s)
                else:
                    device_chunklets.extend(split[0])
                    host_segs.extend(split[1])
            groups = [g for g in (device_sealed, device_chunklets) if g]
            if self.device is not None and groups:
                # device finalize is safe only when ONE device batch is the
                # whole answer: no host segments, no star-tree/metadata
                # partials, no second batch to merge with. The same
                # sole-partial condition gates the on-device final reduce
                # (ops/device_reduce.py): "terminal" when nothing merges
                # after (exact trim to offset+limit), "partial" when a
                # broker still combines server partials (the
                # trim_group_by keep bound, ORDER BY only).
                sole = (not results and not host_segs and len(groups) == 1)
                final = terminal and sole
                reduce_mode = None
                if trim_ok and sole:
                    reduce_mode = "terminal" if terminal else "partial"
                try:
                    for g in groups:
                        # the sealed group's Level-1 verdicts were already
                        # computed by self.pruner above — hand them to the
                        # launch so it doesn't re-derive them. Chunklet
                        # groups compute their OWN per-chunklet verdicts
                        # (the engine pruned the consuming segment as a
                        # whole, not per block).
                        hint = [id(s) not in scan_pruned for s in g] \
                            if g is device_sealed else None
                        handle = self.device.launch(q, g, final=final,
                                                    alive=hint,
                                                    tracer=tracer,
                                                    reduce_mode=reduce_mode)
                        handle.deadline = deadline
                        device_handles.append((handle, g))
                except DeviceUnsupported:
                    for h, _ in device_handles:
                        h.release()
                    device_handles = []
            if not device_handles:
                # launch refused: whole scan on the host — segments the
                # metadata pruner excluded (kept only for device batch-key
                # stability) drop back out rather than host-scan for nothing
                host_segs = [s for s in scan if id(s) not in scan_pruned]
                pruned += len(scan) - len(host_segs)
                if scan_pruned:
                    executed = [s for s in executed
                                if id(s) not in scan_pruned]
            # host partials execute in the launch phase, overlapping the
            # dispatched device batches' link round trip; a host failure
            # must release the in-flight handles or their batch pins leak
            try:
                host_results = []
                with span("host_scan", tracer):
                    for s in host_segs:
                        if deadline is not None:
                            deadline.check("host scan")
                        host_results.append(self.host.execute_segment(q, s))
            except BaseException:
                for h, _ in device_handles:
                    h.release()
                raise

        def fetch():
            res = list(results)
            ran = executed
            fallback_pruned = []  # stats-pruned members of fallen-back handles
            if device_handles:
                # ANY failure below must drop every remaining in-flight
                # launch's batch pin (handle.release is idempotent after
                # fetch), or the batches stay unevictable and the
                # coalescer's pressure signal never drains — the guard
                # covers QueryTimeout, fallback-gate rejections, AND
                # unexpected errors alike
                pending = list(device_handles)
                try:
                    while pending:
                        handle, segs_of_handle = pending.pop(0)
                        try:
                            res.append(handle.fetch())
                        except DeviceUnsupported:
                            # fetch-time fallback (sorted group-table
                            # overflow, or a device-runtime failure the
                            # executor converted after counting it toward
                            # its quarantine breaker): the device must
                            # never shape truncation policy. The host
                            # re-scan is heavy CPU work — route it through
                            # the caller's admission gate when one is
                            # provided. Members the metadata pruner
                            # already proved empty (kept in the batch only
                            # for batch-key stability) don't re-scan; they
                            # count as pruned like the launch-refused
                            # path.
                            live = [s for s in segs_of_handle
                                    if id(s) not in scan_pruned]
                            fallback_pruned.extend(
                                s for s in segs_of_handle
                                if id(s) in scan_pruned)

                            def _host_rerun(_segs=live):
                                out = []
                                with span("host_fallback", tracer):
                                    for s in _segs:
                                        if deadline is not None:
                                            deadline.check(
                                                "host fallback scan")
                                        out.append(
                                            self.host.execute_segment(q, s))
                                return out

                            res.extend(
                                _host_rerun() if fallback_gate is None
                                else fallback_gate(_host_rerun))
                except BaseException:
                    for h, _ in pending:
                        h.release()
                    raise
            if fallback_pruned:
                dropped = {id(s) for s in fallback_pruned}
                ran = [s for s in ran if id(s) not in dropped]
            res.extend(host_results)
            if not res:
                if segments:
                    # everything pruned: empty result over first segment's
                    # schema
                    ran = [segments[0]]
                    res.append(self.host.execute_segment(
                        _impossible(q), segments[0]))
                else:
                    # EVERY routed segment is cold: honest empty partial
                    # shaped by the cold metadata's zero-doc view (its
                    # stats zero out — the cold docs count below)
                    ran = []
                    empty = self.host.execute_segment(
                        _impossible(q), cold_refs[0].empty_view())
                    empty.stats.num_segments_processed = 0
                    empty.stats.num_segments_queried = 0
                    res.append(empty)

            with span("merge", tracer):
                merged = merge_intermediates(q, res)
            # per-flight roofline records (ISSUE 11) concatenate across
            # partials (merge_intermediates builds a fresh result; the
            # single-partial shortcut passes its own list through)
            roofs = [rec for r in res if getattr(r, "roofline", None)
                     for rec in r.roofline]
            if roofs:
                merged.roofline = roofs
            # device partials carry their own launch-level pruned counts
            # (alive-masked batch members); add the segments dropped here
            merged.stats.num_segments_pruned += pruned + len(fallback_pruned)
            merged.stats.num_segments_queried = len(all_segments)
            # cold segments answered nothing this execution: the partial
            # is honest about it (numSegmentsCold) and their docs still
            # count toward totalDocs below like any unexecuted segment
            merged.stats.num_segments_cold += len(cold_refs)
            # pruned segments still count toward totalDocs (reference
            # semantics)
            executed_ids = {id(s) for s in ran}
            for s in all_segments:
                if id(s) not in executed_ids:
                    merged.stats.total_docs += s.n_docs
            return merged

        return fetch

    # ---- dimension-table lookup (DimensionTableDataManager analog) -------
    def dim_table_lookup(self, dim_table: str, value_col: str, pk_col: str):
        """(pk value → value_col value, miss default) over all hosted
        segments of the dimension table; cached until the table's segment
        set changes (LookupTransformFunction resolves against this map).
        The miss default comes from the value column's TYPE, not a sample
        row, so empty dim tables keep numeric semantics."""
        tdm = self.tables.get(dim_table) or self.tables.get(f"{dim_table}_OFFLINE")
        if tdm is None:
            raise KeyError(f"dimension table {dim_table!r} not hosted here")
        if getattr(tdm, "is_dim_table", None) is False:
            # cluster mode: a regular table's segments are spread across
            # servers, so a local pk map would be silently incomplete — the
            # reference's LookupTransformFunction rejects these the same way
            raise ValueError(f"LOOKUP target {dim_table!r} is not a "
                             f"dimension table (is_dim_table=false)")
        key = (tdm.name, pk_col, value_col)
        cached = self._dim_cache.get(key)
        if cached is not None and cached[0] == tdm.generation:
            return cached[1], cached[2]
        import numpy as np

        gen = tdm.generation
        mapping: dict = {}
        default = ""
        segs = tdm.acquire()
        try:
            if not segs:
                raise KeyError(f"dimension table {dim_table!r} has no "
                               f"segments loaded here")
            dt = segs[0].column_metadata(value_col).data_type
            default = "" if dt.is_string_like else dt.np_dtype.type(0).item()
            for seg in segs:
                pks = np.asarray(seg.values(pk_col))
                vals = np.asarray(seg.values(value_col))
                for k, v in zip(pks.tolist(), vals.tolist()):
                    mapping[k] = v
        finally:
            tdm.release(segs)
        self._dim_cache[key] = (gen, mapping, default)
        return mapping, default

    # ---- helpers ---------------------------------------------------------
    @staticmethod
    def _expand_star(q: QueryContext, seg: ImmutableSegment) -> QueryContext:
        from pinot_tpu.query.rewrite import expand_star

        return expand_star(q, seg.column_names())

    def _explain(self, q: QueryContext) -> dict:
        from pinot_tpu.engine.explain import explain_plan

        return explain_plan(self, q)

    def _explain_analyze(self, q: QueryContext, t0: float) -> dict:
        """EXPLAIN ANALYZE (ISSUE 11): execute the underlying query for
        real (traced, so the phase ladder fills), then render the plan
        tree annotated with per-node actuals. The executed response rides
        along as ``analyzedResponse`` so callers can verify the results
        are bit-identical to the non-ANALYZE form."""
        import dataclasses

        from pinot_tpu.common.trace import Tracer
        from pinot_tpu.engine.explain import annotate_analyze, explain_plan

        # the partials cache is bypassed for the analyzed run: a cache
        # hit skips the kernel entirely, and the point of ANALYZE is to
        # MEASURE it (results are bit-identical either way — pinned by
        # the subrtt differential suite)
        q_run = dataclasses.replace(
            q, explain=False, analyze=False,
            options=q.options + (("usePartialsCache", False),))
        tracer = Tracer("analyze")
        result, merged = self._execute_merged(q_run, tracer=tracer)
        resp = self._stats_response(result, merged, t0)
        resp["traceInfo"] = {"server": tracer.to_json()}
        out = annotate_analyze(explain_plan(self, q), resp)
        out["analyzedResponse"] = resp
        return out


def _impossible(q: QueryContext):
    import dataclasses

    return dataclasses.replace(q, filter=FilterNode.FALSE)
