"""EXPLAIN PLAN FOR: render the logical plan as rows.

Reference: ServerQueryExecutorV1Impl.processExplainPlanQueries (:338-352)
renders the operator tree via Operator.toExplainString; here the plan is the
engine's shape dispatch + filter tree + backend choice.
"""

from __future__ import annotations

import os

from pinot_tpu.common.options import bool_option
from pinot_tpu.query.context import FilterNode, FilterNodeType, QueryContext


def _width_lines(engine, q: QueryContext, segs, out: list) -> None:
    """PINOT_TPU_WIDTH_AUDIT=1: render the device width plan per referenced
    column (engine/params.py ColPlan) — the EXPLAIN face of the debug
    width-audit mode. Best-effort: anything the device path would reject
    simply renders no WIDTH lines (the host path has no width plan)."""
    import numpy as np

    from pinot_tpu.engine.params import BatchContext
    from pinot_tpu.storage.segment import Encoding

    try:
        # a THROWAWAY context: planning reads only metadata/dictionaries,
        # and going through the executor's batch_for here would insert a
        # display-only batch into the production LRU (evicting a hot one)
        # and skew the hit/miss gauges
        ctx = BatchContext(segs)
        for name in sorted(q.columns()):
            plan = ctx.width_plan(name)
            desc = np.dtype(plan.dtype).name
            if plan.bits:
                desc += f" packed={plan.bits}b"
            if plan.offset is not None:
                desc += f" for-offset={plan.offset}"
            if plan.wide:
                desc += f" wide={np.dtype(plan.wide).name}"
            if ctx.encoding(name) == Encoding.DICT:
                desc += f" card={ctx.cardinality(name)}"
            out.append(f"    WIDTH({name}: {desc})")
    except Exception:  # noqa: BLE001 — display only
        pass


def _filter_lines(f: FilterNode, depth: int, out: list, seg=None) -> None:
    pad = "  " * depth
    if f.type is FilterNodeType.PREDICATE:
        op = "PREDICATE"
        if seg is not None:
            from pinot_tpu.engine.host import filter_operator_for

            op = filter_operator_for(seg, f.predicate)
        out.append(f"{pad}FILTER_{op}({f.predicate})")
        return
    if f.type in (FilterNodeType.CONSTANT_TRUE, FilterNodeType.CONSTANT_FALSE):
        out.append(f"{pad}FILTER_{f.type.value}")
        return
    out.append(f"{pad}FILTER_{f.type.value}")
    for c in f.children:
        _filter_lines(c, depth + 1, out, seg)


def _rows_response(lines: list) -> dict:
    rows = [[ln, i, i - 1] for i, ln in enumerate(lines)]
    return {
        "resultTable": {
            "dataSchema": {
                "columnNames": ["Operator", "Operator_Id", "Parent_Id"],
                "columnDataTypes": ["STRING", "INT", "INT"],
            },
            "rows": rows,
        },
        "exceptions": [],
    }


def explain_multistage(engine, plan) -> dict:
    """EXPLAIN for a two-stage (join / window) plan: the stage boundary,
    the join strategy with build/probe sides, window spec lines, and the
    per-table stage-1 scans with their pushed-down filters."""
    from pinot_tpu.query2.logical import to_sql
    from pinot_tpu.sql.compiler import _to_filter

    q = plan.stage2
    aggs = q.aggregations()
    if q.distinct:
        shape = "DISTINCT"
    elif aggs and q.group_by:
        shape = "AGGREGATE_GROUPBY_ORDERBY"
    elif aggs:
        shape = "AGGREGATE"
    elif plan.windows:
        shape = "SELECT_WINDOW"
    else:
        shape = "SELECT_ORDERBY" if q.order_by else "SELECT"

    device = getattr(engine, "device", None) if engine is not None else None
    backend = "DEVICE(jax/xla)" if device is not None else "HOST(numpy)"
    mesh = getattr(device, "mesh", None) if device is not None else None

    lines: list[str] = []
    lines.append(f"BROKER_REDUCE(limit:{q.limit})")
    lines.append(f"  STAGE_2_{shape}"
                 f"({', '.join(str(e) for e in q.select_expressions)})"
                 f" [{backend}]")
    if q.group_by:
        lines.append(
            f"    GROUP_BY({', '.join(str(g) for g in q.group_by)})")
    if q.having is not None:
        lines.append(f"    HAVING({q.having})")
    for w in plan.windows:
        lines.append(f"    WINDOW({w.describe()})")
    if plan.post_filter is not None:
        lines.append(f"    POST_JOIN_FILTER({to_sql(plan.post_filter)})")
    # DISTRIBUTED runs stage 2 on the server fleet (ISSUE 16): the
    # boundary is a wire exchange between servers, whatever mesh the
    # broker-side renderer happens to see
    if plan.strategy == "DISTRIBUTED" and plan.joins:
        exchange = "server-fleet"
    else:
        exchange = "mesh-collective" if mesh is not None else "local"
    if plan.joins:
        lines.append(f"  STAGE_BOUNDARY(exchange:{plan.strategy} "
                     f"[{exchange}])")
    else:
        lines.append("  STAGE_BOUNDARY(exchange:SORT [window])")
    probe_desc = f"{plan.probe.alias}={plan.probe.table}"
    for j in plan.joins:
        dim = " dim" if j.build.is_dim else ""
        lines.append(
            f"  JOIN_{j.kind}(strategy={plan.strategy}, "
            f"build={j.build.alias}={j.build.table}{dim}, "
            f"probe={probe_desc})")
        keys = ", ".join(f"{lk} = {rk}"
                         for lk, rk in zip(j.left_keys, j.right_keys))
        lines.append(f"      KEYS({keys})")
        if j.residual is not None:
            lines.append(f"      RESIDUAL({to_sql(j.residual)})")
    for src in plan.sources:
        role = "probe" if src is plan.probe else \
            ("build/broadcast" if plan.strategy == "BROADCAST"
             else "build/shuffle")
        lines.append(f"  SCAN({src.alias}={src.table} [{role}])")
        push = plan.pushdown.get(src.alias)
        if push is not None:
            _filter_lines(_to_filter(push), 2, lines)
        else:
            lines.append("    FILTER_MATCH_ENTIRE_SEGMENT")
    return _rows_response(lines)


def _fmt_ms(v) -> str:
    try:
        return f"{float(v):.2f}ms"
    except (TypeError, ValueError):
        return "?"


def _kernel_line(rec: dict) -> str:
    """One roofline flight → the per-kernel ``GB/s (x% of HBM peak)``
    line EXPLAIN ANALYZE renders (ISSUE 11 / ROADMAP 1: the SNIPPETS.md
    "GB/s vs HBM peak reported per query" target)."""
    label = rec.get("kernel", "kernel")
    inst = rec.get("instance")
    where = f"@{inst}" if inst else ""
    if rec.get("cacheHit"):
        return (f"    KERNEL({label}{where}: CACHED_PARTIALS, "
                f"linkMs={rec.get('linkMs')})")
    gbps = rec.get("gbps")
    pct = rec.get("pctOfPeak")
    peak = rec.get("peakGbps")
    if gbps is None:
        perf = "n/a"
    elif pct is not None:
        perf = f"{gbps} GB/s ({pct}% of HBM peak {peak} GB/s)"
    else:
        perf = f"{gbps} GB/s"
    return (f"    KERNEL({label}{where}: {perf}, "
            f"bytes={rec.get('bytesMoved')}, "
            f"kernelMs={rec.get('kernelMs')}, linkMs={rec.get('linkMs')})")


def annotate_analyze(plan: dict, resp: dict) -> dict:
    """EXPLAIN ANALYZE rendering (ISSUE 11): the static plan tree from
    explain_plan / explain_multistage, annotated in place with per-node
    actuals from the EXECUTED response — rows in/out on the reduce /
    combine / join / scan nodes, matched rows + blocks pruned on the
    filter root — followed by an ANALYZE subtree carrying the segment
    counters, the per-phase ms waterfall (merged traceInfo), one KERNEL
    line per roofline flight (achieved GB/s vs the HBM peak), and the
    cache-hit provenance (device partials / broker result cache)."""
    from pinot_tpu.tools.querylog import phase_breakdown

    lines = [r[0] for r in plan["resultTable"]["rows"]]
    nrows = len(((resp.get("resultTable") or {}).get("rows")) or [])
    docs = resp.get("numDocsScanned")
    leaf_rows = resp.get("leafRows") or {}
    # multistage plans carry PER-TABLE pushdown filters; the cluster-wide
    # docsScanned total belongs to none of them, so the filter-root
    # annotation is single-stage-only (leafRows is the multistage marker)
    multistage = bool(leaf_rows) or resp.get("numJoinedRows") is not None
    filter_done = multistage
    out = []
    for ln in lines:
        s = ln.strip()
        if s.startswith("BROKER_REDUCE"):
            ln += (f" (actual: rows={nrows}, "
                   f"timeMs={resp.get('timeUsedMs')})")
        elif s.startswith("STAGE_2_"):
            # stage 2 consumes the JOINED row set, not the stage-1 scan
            # docs (a 1M-doc scan joining down to 500 rows must say 500)
            n_in = resp.get("numJoinedRows")
            ln += (f" (actual: in={docs if n_in is None else n_in} rows, "
                   f"out={nrows} rows)")
        elif s.startswith("COMBINE_"):
            ln += f" (actual: in={docs} rows, out={nrows} rows)"
        elif s.startswith("STAGE_BOUNDARY(") and resp.get("exchange"):
            # distributed stage-2 ran (possibly a RUNTIME demotion the
            # static plan did not know about): render the strategy that
            # actually executed, plus the exchange actuals — partition
            # count, shipped bytes, spill count, per-server stage-2 rows
            import re as _re

            ex = resp["exchange"]
            if "exchange:DISTRIBUTED" not in ln:
                ln = _re.sub(r"exchange:\w+ \[[^\]]*\]",
                             "exchange:DISTRIBUTED [server-fleet]", ln)
            per = ", ".join(
                f"{w}={v.get('stage2Rows')}"
                for w, v in sorted((ex.get("servers") or {}).items()))
            ln += (f" (actual: partitions={ex.get('partitions')}, "
                   f"shippedBytes={resp.get('exchangeBytes')}, "
                   f"spills={resp.get('exchangeSpillCount')}, "
                   f"stage2Rows[{per}])")
        elif s.startswith("JOIN_") and resp.get("numJoinedRows") is not None:
            ln += f" (actual: out={resp['numJoinedRows']} rows)"
        elif s.startswith("SCAN("):
            alias = s[len("SCAN("):].split("=", 1)[0]
            if alias in leaf_rows:
                ln += f" (actual: out={leaf_rows[alias]} rows)"
        elif (s.startswith("FILTER_") and not filter_done
              and not s.startswith("FILTER_MATCH_ENTIRE")
              and docs is not None):
            filter_done = True  # annotate the ROOT filter node only
            ln += (f" (actual: matched={docs} rows, "
                   f"blocksPruned={resp.get('numBlocksPruned', 0)})")
        out.append(ln)

    out.append("  ANALYZE")
    out.append(f"    ROWS(scanned={docs}, returned={nrows}, "
               f"totalDocs={resp.get('totalDocs')})")
    out.append(
        "    SEGMENTS("
        f"queried={resp.get('numSegmentsQueried')}, "
        f"processed={resp.get('numSegmentsProcessed')}, "
        f"matched={resp.get('numSegmentsMatched')}, "
        f"prunedByServer={resp.get('numSegmentsPrunedByServer')}, "
        f"prunedByBroker={resp.get('numSegmentsPrunedByBroker', 0)}, "
        f"blocksPruned={resp.get('numBlocksPruned')})")
    phases = phase_breakdown({"traceInfo": resp.get("traceInfo") or {}})
    if phases:
        out.append("    PHASE(" + ", ".join(
            f"{k}={_fmt_ms(v)}" for k, v in sorted(phases.items())) + ")")
    for rec in resp.get("roofline") or ():
        out.append(_kernel_line(rec))
    out.append(
        f"    CACHE(partialsCacheHit={bool(resp.get('partialsCacheHit'))}, "
        f"resultCacheHit={bool(resp.get('resultCacheHit'))})")
    # plan advisor (ISSUE 17): one line per measurement-driven override
    # this execution ran with — already formatted as
    # ADVISOR(<decision>: measured=X default=Y) at the decision site, so
    # a mis-advised plan is debuggable straight from EXPLAIN ANALYZE
    for line in resp.get("advisorDecisions") or ():
        out.append(f"    {line}")
    return _rows_response(out)


def explain_plan(engine, q: QueryContext) -> dict:
    lines: list[str] = []
    aggs = q.aggregations()
    if q.distinct:
        shape = "DISTINCT"
    elif aggs and q.group_by:
        shape = "AGGREGATE_GROUPBY_ORDERBY"
    elif aggs:
        shape = "AGGREGATE"
    else:
        shape = "SELECT_ORDERBY" if q.order_by else "SELECT"

    backend = "HOST(numpy)"
    if engine.device is not None and engine.device.supports(q):
        backend = "DEVICE(jax/xla)"

    lines.append(f"BROKER_REDUCE(limit:{q.limit})")
    lines.append(f"  COMBINE_{shape} [{backend}]")
    lines.append(f"    PLAN_START(table:{q.table_name})")
    lines.append(f"    {shape}({', '.join(str(e) for e in q.select_expressions)})")
    if q.group_by:
        lines.append(f"    GROUP_BY({', '.join(str(g) for g in q.group_by)})")
    if q.filter is not None:
        # index choice is per-segment; EXPLAIN (like the reference's
        # non-verbose mode) describes it against one representative segment
        seg = None
        segs = []
        tdm = engine.tables.get(q.table_name)
        if tdm is not None and tdm.segments:
            segs = list(tdm.segments.values())
            seg = segs[0]
        # server-side stats pruning (min/max + dictionary membership +
        # bloom, engine.SegmentPruner — the same tri-state the device
        # launch masks segments with): provably-false-everywhere renders
        # as FILTER_EMPTY, partial prunes as a PRUNE line under the tree
        n_pruned = 0
        pruner = getattr(engine, "pruner", None)
        if pruner is not None and segs:
            n_pruned = sum(1 for s in segs if pruner.prune(q, s))
        if segs and n_pruned == len(segs):
            lines.append("    FILTER_EMPTY")
        else:
            _filter_lines(q.filter, 2, lines, seg)
            if n_pruned:
                lines.append(
                    f"      PRUNE(zone-map: {n_pruned}/{len(segs)} segments)")
    else:
        lines.append("    FILTER_MATCH_ENTIRE_SEGMENT")
    lines.append("    PROJECT(" + ", ".join(sorted(q.columns())) + ")")
    if backend.startswith("DEVICE"):
        # sub-RTT serving surfaces (ISSUE 9): the on-device final reduce
        # (when the query's ORDER/LIMIT shape supports an in-kernel trim)
        # and the device partials cache state
        dev = engine.device
        if q.group_by and not q.distinct:
            from pinot_tpu.ops.device_reduce import plan_trim, trim_keep_count

            # render the trim only when it would actually engage: the
            # static bound must sit BELOW the real group-table length
            # (product of cardinalities from a THROWAWAY context, like
            # _width_lines — never batch_for; best-effort, host-only
            # shapes simply render no line). The embedded explain path
            # is terminal semantics (nothing merges after finalize).
            spec = None
            try:
                from pinot_tpu.engine.device import (
                    MAX_DENSE_GROUPS,
                    MAX_SORTED_GROUPS,
                )
                from pinot_tpu.engine.params import BatchContext

                tdm = engine.tables.get(q.table_name)
                segs = list(tdm.segments.values()) if tdm is not None else []
                if segs:
                    ctx = BatchContext(segs)
                    total = 1
                    for g in q.group_by:
                        total *= ctx.cardinality(g.name)
                    if total > MAX_DENSE_GROUPS:
                        total = min(dev.num_groups_limit, MAX_SORTED_GROUPS)
                    spec = plan_trim(
                        q, tuple(q.group_by), tuple(q.aggregations()),
                        "groupby", total, "terminal",
                        getattr(dev, "group_trim_size", 5000))
            except Exception:  # noqa: BLE001 — display only
                spec = None
            if spec is not None:
                lines.append(
                    f"    DEVICE_REDUCE(trim={trim_keep_count(q, 'terminal')})")
        if getattr(dev, "partials_cache_enabled", False) \
                and bool_option(q.options_ci(), "usepartialscache",
                                None) is not False:
            lines.append(
                f"    CACHED_PARTIALS(entries={len(dev._partials)})")
    if (backend.startswith("DEVICE")
            and os.environ.get("PINOT_TPU_WIDTH_AUDIT", "") not in ("", "0")):
        tdm = engine.tables.get(q.table_name)
        segs = list(tdm.segments.values()) if tdm is not None else []
        if segs:
            _width_lines(engine, q, segs, lines)

    rows = [[ln, i, i - 1] for i, ln in enumerate(lines)]
    return {
        "resultTable": {
            "dataSchema": {
                "columnNames": ["Operator", "Operator_Id", "Parent_Id"],
                "columnDataTypes": ["STRING", "INT", "INT"],
            },
            "rows": rows,
        },
        "exceptions": [],
    }
