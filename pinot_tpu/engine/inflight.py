"""In-flight launch handles + cross-query launch coalescing.

The device executor's hot path splits into an async **launch** phase
(template build + column gather + non-blocking XLA dispatch — JAX dispatch
is already asynchronous, only ``jax.device_get`` blocks) and a **fetch**
phase that resolves the packed output buffer. ``InflightLaunch`` is the
handle between the two: N concurrent queries overlap their host↔device
round trips instead of serializing them on the transport threads, and the
server releases its scheduler slot before the link wait (the per-server
many-requests-in-flight posture of the reference's scatter-gather model —
a Pinot server keeps many segment queries in flight to hide exactly this
latency).

``LaunchCoalescer`` rides on top: concurrent queries sharing one
(batch, template, param-shape) cohort key — the dashboard fan-out case,
same SQL shape with different literals — stack their params along a
leading axis and execute as ONE vmapped launch whose result crosses the
link as ONE packed buffer, amortizing a single RTT over the whole cohort.
The micro-batch window only opens under pressure (another query already in
flight on the executor, or the server scheduler reporting contention): an
idle server dispatches immediately and pays no window latency.
"""

from __future__ import annotations

import threading
import time


class InflightLaunch:
    """A dispatched-but-not-fetched device launch.

    ``fetch()`` blocks on the host link (the ONLY blocking step), unpacks
    the packed buffer, and builds the canonical IntermediateResult. The
    batch the launch reads from is refcounted against LRU eviction until
    the fetch completes (``DeviceExecutor._retain_launch`` /
    ``_release_launch``) — without the pin, a concurrent query's
    ``_evict`` could drop the HBM blocks this launch is still reading.
    """

    def __init__(self, executor, q, ctx, template, aggs, batch_key, resolve):
        self._executor = executor
        self._q = q
        self._ctx = ctx
        self._template = template
        self._aggs = aggs
        self._batch_key = batch_key
        self._resolve = resolve
        self._done = False
        # optional per-query Deadline (common/deadline.py), set by the
        # engine when the request carried a budget: an expired deadline
        # aborts BEFORE the blocking device_get (which itself cannot be
        # interrupted) with a typed QueryTimeout
        self.deadline = None
        # optional explicit Tracer (common/trace.py), set by the executor
        # when the query is traced: the fetch phase may run on a different
        # thread than the launch (PR-2 split) or ride a cohort whose
        # shared buffer another member resolves — spans recorded against
        # the handle's tracer land on THIS query's trace regardless
        self.tracer = None
        # True when the launch was served from the device partials cache
        # (no gather/dispatch/kernel — the fetch re-reads a cached packed
        # buffer); surfaces as the result's partialsCacheHit stat
        self.cache_hit = False
        # roofline flight dict (ISSUE 11), set by the executor when
        # accounting is on: the resolve fills flight["record"] with the
        # modeled-bytes/kernel-ms/GB/s record, and fetch() folds it into
        # the result's stats + roofline list. Cohort members other than
        # the leader carry an unfilled flight (the shared kernel is
        # attributed once, to the leader's trace and record).
        self.flight = None

    def fetch(self):
        """Blocking phase: resolve the packed buffer → IntermediateResult.
        Raises DeviceUnsupported on fetch-time fallbacks (sorted group
        table overflow) — the caller re-runs the batch on the host path —
        and QueryTimeout when the query's deadline expired before the
        link wait began. One-shot: the batch pin is dropped whether or
        not it succeeds."""
        if self._done:
            raise RuntimeError("InflightLaunch.fetch() called twice")
        self._done = True
        try:
            if self.deadline is not None:
                try:
                    self.deadline.check("device fetch")
                except BaseException:
                    # this member will never run the shared resolve: it
                    # counts as abandoned, or an all-timed-out cohort
                    # leaves fetch_done unset and the next stream window
                    # polls out its whole cap
                    self._note_abandoned()
                    raise
            try:
                if self.tracer is not None:
                    # the member-side fetch wait: covers the cohort-shared
                    # resolve (whose own kernel/link sub-spans land on the
                    # LEADER's trace) as well as the solo path
                    from pinot_tpu.common.trace import span

                    with span("device_fetch", self.tracer):
                        outs = self._resolve()
                else:
                    outs = self._resolve()
            except Exception as e:  # noqa: BLE001 — may convert to fallback
                # device-runtime failures (XlaRuntimeError /
                # RESOURCE_EXHAUSTED, real or injected) convert to the
                # host-fallback signal after the executor records them
                # toward the quarantine breaker; anything else re-raises
                self._executor.on_fetch_device_error(
                    e, self._template, self._batch_key,
                    getattr(self, "used_pallas", False))
                raise
            # success clears the quarantine breaker's strike count — the
            # breaker is for failures close together, not two transient
            # faults a week apart
            self._executor._note_device_success(
                self._template, self._batch_key)
            adv_key = getattr(self, "adv_key", None)
            result = self._executor._to_intermediate(
                self._q, self._ctx, self._template, outs, self._aggs,
                cache_hit=self.cache_hit, adv_key=adv_key,
                adv_trim_keep=getattr(self, "adv_trim_keep", None))
            result.stats.partials_cache_hit = self.cache_hit
            # plan-advisor stamps + cache-hit feedback (ISSUE 17): the
            # decisions this launch ran with ride the result's stats to
            # the response / querylog / EXPLAIN ANALYZE, and the
            # partials-cache outcome feeds the template's memo
            notes = getattr(self, "advisor_notes", None)
            if notes:
                result.stats.advisor_decisions.extend(notes)
            advisor = getattr(self._executor, "advisor", None)
            if adv_key is not None and advisor is not None:
                advisor.observe(adv_key, partials_hit=self.cache_hit)
            rec = None if self.flight is None else self.flight.get("record")
            if rec is not None:
                # per-query roofline accounting (ISSUE 11): the flight's
                # record rides the result so servers ship it in DataTable
                # metadata and the broker/EXPLAIN ANALYZE render it
                result.roofline = [rec]
                st = result.stats
                st.device_bytes_moved += int(rec.get("bytesMoved") or 0)
                st.device_kernel_ms += float(rec.get("kernelMs") or 0.0)
                st.device_link_ms += float(rec.get("linkMs") or 0.0)
            return result
        finally:
            self._executor._release_launch(self._batch_key)

    def _note_abandoned(self):
        """Tell a cohort this member will never fetch (resolve closures
        carry the ``abandon`` hook; solo resolves don't — no-op)."""
        abandon = getattr(self._resolve, "abandon", None)
        if abandon is not None:
            try:
                abandon()
            except Exception:  # noqa: BLE001 — bookkeeping must not mask
                pass

    def release(self):
        """Abandon without fetching: drop the batch pin. Callers that fail
        BETWEEN launch and fetch (e.g. a host-segment partial raising
        while the device batch is in flight) must call this, or the pin
        leaks — the batch would stay unevictable and the executor's
        inflight count (the coalescer's pressure signal) never drains.
        Idempotent with fetch(); safe to call on an already-fetched handle."""
        if not self._done:
            self._done = True
            # cohort members tell their cohort: an all-abandoned cohort
            # must still set fetch_done or the next stream window stalls
            # to its cap
            self._note_abandoned()
            self._executor._release_launch(self._batch_key)


class _Cohort:
    """One coalesced launch: the leader stacks every member's params and
    dispatches once; the shared packed buffer is fetched once (first
    ``resolve_member`` wins) and each member slices its row."""

    # liveness poll: a member waits as long as the leader THREAD is alive
    # (a first dispatch jit-compiles the whole vmapped pipeline, which can
    # far exceed any fixed timeout) but must not wait forever on a leader
    # that died mid-window
    READY_POLL_S = 5.0

    def __init__(self, launch_fn):
        self._launch_fn = launch_fn
        self.leader_thread = threading.current_thread()  # creator leads
        self.members = []          # per-member params dicts, join order
        self.open = True           # False once the window closed
        self.full = threading.Event()  # hit max_cohort: leader stops waiting
        self.ready = threading.Event()
        # set once the shared buffer crossed the link (or the cohort
        # failed): the SUCCESSOR cohort's launch window keys off it — the
        # double-buffer handoff that keeps the link continuously busy
        # (LaunchCoalescer stream windows)
        self.fetch_done = threading.Event()
        self.error = None          # leader's dispatch failure, if any
        self._shared_resolve = None
        self._fetch_lock = threading.Lock()
        self._outs = None
        self._exc = None
        self._fetched = False
        self._abandoned = 0        # members released without fetching

    def dispatch(self):
        """Leader only: one stacked launch for the whole cohort."""
        try:
            self._shared_resolve = self._launch_fn(self.members)
        except BaseException as e:  # noqa: BLE001 — members must observe it
            self.error = e
            self.fetch_done.set()  # nothing will ever fetch; unblock successor
        finally:
            self.ready.set()
            # members that abandoned BEFORE dispatch finished couldn't
            # conclude the all-abandoned check; settle it now
            with self._fetch_lock:
                self._check_all_abandoned()

    def note_abandoned(self):
        """A member released its handle without fetching
        (InflightLaunch.release — deadline expiry, upstream failure).
        When EVERY member abandons, nothing will ever run the shared
        fetch: fetch_done must still fire or the next same-key stream
        window polls out its whole cap for a link that is already
        free."""
        with self._fetch_lock:
            self._abandoned += 1
            self._check_all_abandoned()

    def _check_all_abandoned(self):
        """Caller holds _fetch_lock. Membership is final once ready is
        set (the window closed before dispatch ran)."""
        if (self.ready.is_set() and not self._fetched
                and self._abandoned >= len(self.members)):
            self.fetch_done.set()

    def resolve_member(self, idx: int) -> dict:
        """Member ``idx``'s unpacked outputs. The shared buffer crosses
        the link ONCE; every member's slice comes from that one fetch."""
        while not self.ready.wait(self.READY_POLL_S):
            # slow-but-alive leader (e.g. first jit compile of the cohort
            # pipeline) keeps members waiting; a dead one fails them fast
            if not self.leader_thread.is_alive():
                raise RuntimeError(
                    "coalesced launch leader died before dispatch")
        if self.error is not None:
            raise self.error
        with self._fetch_lock:
            if not self._fetched:
                try:
                    self._outs = self._shared_resolve()
                except BaseException as e:  # noqa: BLE001 — shared failure
                    self._exc = e
                self._fetched = True
                self.fetch_done.set()  # link free: successor may dispatch
        if self._exc is not None:
            raise self._exc
        return {k: v[idx] for k, v in self._outs.items()}


class LaunchCoalescer:
    """Micro-batches concurrent same-template launches into one vmapped
    dispatch. Pure synchronization — the executor supplies the actual
    stacked-launch closure (``DeviceExecutor._cohort_launch``)."""

    def __init__(self, window_s: float = 0.003, max_cohort: int = 8,
                 stream_cap_s: float = 0.25):
        self.enabled = True
        self.window_s = window_s      # leader's micro-batch window
        self.max_cohort = max_cohort  # vmap width cap (bounds recompiles)
        # double-buffered launch/fetch streams: while cohort N's shared
        # buffer is in its link flight, cohort N+1's leader holds its
        # window open until N's fetch completes (capped at stream_cap_s
        # for the abandoned-handle case where nobody ever fetches) — so
        # arrivals during the RTT accumulate into ONE launch that
        # dispatches the moment the link frees. Steady-state QPS becomes
        # cohort_size / RTT, bounded by kernel time rather than by one
        # round trip per query. A leader with no in-flight predecessor
        # keeps the fixed micro-batch window (an idle link should not
        # wait).
        self.stream_cap_s = stream_cap_s
        self.force = False            # tests/bench: window regardless of load
        self.pressure_fn = None       # server wires scheduler.pressure here
        self._lock = threading.Lock()
        self._pending: dict = {}      # cohort key -> open _Cohort
        # cohort key -> the last dispatched cohort's fetch_done EVENT —
        # only the event, never the _Cohort: the cohort object closes
        # over the batch's gathered device columns and the packed output
        # buffer, and retaining it here would pin those past the batch
        # LRU's eviction decisions
        self._last_dispatched: dict = {}
        # observability (bench concurrency sweep reads deltas)
        self.cohorts_launched = 0
        self.queries_coalesced = 0    # members that joined past the leader
        self.stream_windows = 0       # windows that keyed off a predecessor

    def should_window(self, executor_inflight: int) -> bool:
        """Gate: open a window only when concurrency makes a partner
        likely — an idle server must run its one query immediately.
        ``executor_inflight`` counts launches between dispatch and fetch
        (INCLUDING the asking query, hence > 1); the scheduler's pressure
        covers queries still queued for admission."""
        if not self.enabled:
            return False
        if self.force:
            return True
        if executor_inflight > 1:
            return True
        fn = self.pressure_fn
        if fn is not None:
            try:
                return fn() > 1
            except Exception:  # noqa: BLE001 — gating must never fail a query
                return False
        return False

    def join(self, key, params: dict, launch_fn, window_s=None):
        """Join (or open) the cohort for ``key`` → (cohort, member index).

        The FIRST arrival becomes leader: it holds the window open for
        ``window_s``, then closes the cohort and dispatches one stacked
        launch built by ``launch_fn(members)``. Later arrivals append
        their params and return immediately — they block only inside
        ``resolve_member`` (their fetch phase), so a member's scheduler
        slot is released while the leader's launch is still in flight.

        ``window_s``: per-join override of the leader's micro-batch
        window (the plan advisor sizes it from the template's observed
        arrival cohesion); None keeps the configured default.
        """
        with self._lock:
            c = self._pending.get(key)
            if c is not None and c.open:
                idx = len(c.members)
                c.members.append(params)
                if len(c.members) >= self.max_cohort:
                    c.open = False          # full: stop accepting members
                    self._pending.pop(key, None)
                    c.full.set()            # leader dispatches immediately
                self.queries_coalesced += 1
                return c, idx
            c = _Cohort(launch_fn)
            c.members.append(params)
            self._pending[key] = c
            pred_done = self._last_dispatched.get(key)
            if pred_done is not None and pred_done.is_set():
                self._last_dispatched.pop(key, None)  # link already free
                pred_done = None
        # leader: hold the micro-batch window open — but a cohort that
        # fills to max_cohort early dispatches immediately (the remaining
        # window would be pure added latency for everyone in it). A window
        # that finds NO partner costs window_s against a ~100ms link RTT;
        # the pressure gate keeps that bounded to genuinely-concurrent load.
        #
        # STREAM window (double-buffered launch/fetch): when the previous
        # cohort of this key is still in its link flight, the window
        # extends until that fetch completes — every arrival during the
        # predecessor's RTT buffers into THIS cohort, and it dispatches
        # the instant the link frees (capped so an abandoned predecessor
        # can't stall the stream).
        if pred_done is not None:
            self.stream_windows += 1
            deadline = time.monotonic() + self.stream_cap_s
            while not c.full.is_set() and not pred_done.is_set():
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                c.full.wait(min(0.002, left))
        else:
            c.full.wait(self.window_s if window_s is None else window_s)
        with self._lock:
            c.open = False
            if self._pending.get(key) is c:
                self._pending.pop(key, None)
            self.cohorts_launched += 1
            # LRU order: re-insert so the 64-key bound purges genuinely
            # stale keys, never the hot template that just dispatched
            self._last_dispatched.pop(key, None)
            self._last_dispatched[key] = c.fetch_done
            while len(self._last_dispatched) > 64:  # bound stale keys
                self._last_dispatched.pop(next(iter(self._last_dispatched)))
        c.dispatch()
        return c, 0
