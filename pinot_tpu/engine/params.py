"""Device batch context: segment batch + parameter resolution.

This is the host-side half of the device query pipeline — the analog of the
reference's per-segment plan construction (predicate → dict-id resolution in
operator/filter/predicate/ PredicateEvaluator factories) re-shaped for
batched TPU launches:

- **Global dictionaries**: per-segment dictionaries are unioned per column;
  per-segment remap LUTs (S, Cmax) send local dict ids → global ids. Group-by
  and distinct aggregation then run in *global id space*, so the cross-
  segment combine is a dense scatter into one accumulator instead of a
  value-space merge (the IndexedTable / BlockingQueue replacement).
- **Predicate params**: literals resolve per segment into small arrays
  (target ids, id ranges via sorted-dictionary binary search, per-dictid
  boolean LUTs for regex/LIKE). The jitted pipeline is a pure function of
  these params, so one compiled template serves all literal values.

Raises ``DeviceUnsupported`` for anything the device path doesn't accelerate;
the engine falls back to the host executor.
"""

from __future__ import annotations

import re

import numpy as np

from pinot_tpu.engine.host import like_to_regex
from pinot_tpu.ops.hll import hash32_np
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
)
from pinot_tpu.storage.device import host_column_block, padded_len
from pinot_tpu.storage.segment import Encoding, ImmutableSegment

import jax.numpy as jnp


class DeviceUnsupported(Exception):
    """Query shape not handled by the device pipeline → host fallback."""


_NUMERIC_KINDS = ("i", "u", "f")


class BatchContext:
    """Host+device state for one batch of segments (cached per segment set)."""

    def __init__(self, segments: list, pad_multiple: int = 1024):
        self.segments = list(segments)
        self.pad_to = max(padded_len(s.n_docs, pad_multiple) for s in self.segments)
        self.S = len(self.segments)
        self.n_docs = np.array([s.n_docs for s in self.segments], dtype=np.int32)
        self.n_docs_dev = jnp.asarray(self.n_docs)
        self._columns: dict[str, object] = {}       # name -> (S, L) device array
        self._encodings: dict[str, str] = {}
        self._dicts: dict[str, list] = {}           # name -> [Dictionary per seg]
        self._global_dicts: dict[str, np.ndarray] = {}
        self._remap_luts: dict[str, object] = {}    # name -> (S, Cmax) device int32
        self._value_luts: dict[str, object] = {}
        self._hash_luts: dict[str, object] = {}

    # ---- column access ---------------------------------------------------
    def column_meta(self, name: str):
        for s in self.segments:
            if name in s.metadata.columns:
                return s.column_metadata(name)
        raise DeviceUnsupported(f"unknown column {name}")

    def encoding(self, name: str) -> str:
        if name not in self._encodings:
            metas = [s.column_metadata(name) for s in self.segments]
            enc = metas[0].encoding
            if any(m.encoding != enc for m in metas):
                raise DeviceUnsupported(f"mixed encodings for {name}")
            if any(not m.single_value for m in metas):
                raise DeviceUnsupported(f"multi-value column {name}")
            self._encodings[name] = enc
        return self._encodings[name]

    def column(self, name: str):
        """(S, L) device array of dict ids (DICT) or raw values (RAW)."""
        if name not in self._columns:
            self.encoding(name)  # validates SV/consistency
            blocks = np.stack(
                [host_column_block(s, name, self.pad_to) for s in self.segments]
            )
            self._columns[name] = jnp.asarray(blocks)
        return self._columns[name]

    def dictionaries(self, name: str) -> list:
        if name not in self._dicts:
            self._dicts[name] = [s.dictionary(name) for s in self.segments]
            if any(d is None for d in self._dicts[name]):
                raise DeviceUnsupported(f"column {name} lacks a dictionary")
        return self._dicts[name]

    def max_card(self, name: str) -> int:
        return max(len(d) for d in self.dictionaries(name))

    def global_dict(self, name: str) -> np.ndarray:
        """Union of per-segment dictionary values, sorted (global id space)."""
        if name not in self._global_dicts:
            dicts = self.dictionaries(name)
            self._global_dicts[name] = np.unique(
                np.concatenate([np.asarray(d.values) for d in dicts])
            )
        return self._global_dicts[name]

    def remap_lut(self, name: str):
        """(S, Cmax) int32 device LUT: local dict id -> global id."""
        if name not in self._remap_luts:
            g = self.global_dict(name)
            cmax = self.max_card(name)
            lut = np.zeros((self.S, cmax), dtype=np.int32)
            for i, d in enumerate(self.dictionaries(name)):
                lut[i, : len(d)] = np.searchsorted(g, np.asarray(d.values)).astype(
                    np.int32
                )
            self._remap_luts[name] = jnp.asarray(lut)
        return self._remap_luts[name]

    def value_lut(self, name: str):
        """(S, Cmax) device LUT: local dict id -> numeric value."""
        if name not in self._value_luts:
            dicts = self.dictionaries(name)
            kind = np.asarray(dicts[0].values).dtype.kind
            if kind not in _NUMERIC_KINDS:
                raise DeviceUnsupported(f"non-numeric dict column {name} in expression")
            cmax = self.max_card(name)
            dt = np.asarray(dicts[0].values).dtype
            if dt == np.float64:
                dt = np.dtype(np.float32)  # device value space is f32
            lut = np.zeros((self.S, cmax), dtype=dt)
            for i, d in enumerate(dicts):
                lut[i, : len(d)] = np.asarray(d.values)
            self._value_luts[name] = jnp.asarray(lut)
        return self._value_luts[name]

    def hash_lut(self, name: str):
        """(S, Cmax) device LUT: local dict id -> canonical value hash
        (for DISTINCTCOUNTHLL; host/device-consistent, ops/hll.py)."""
        if name not in self._hash_luts:
            cmax = self.max_card(name)
            lut = np.zeros((self.S, cmax), dtype=np.uint32)
            for i, d in enumerate(self.dictionaries(name)):
                lut[i, : len(d)] = hash32_np(np.asarray(d.values))
            self._hash_luts[name] = jnp.asarray(lut)
        return self._hash_luts[name]


# ---------------------------------------------------------------------------
# filter template + params
# ---------------------------------------------------------------------------

_DEVICE_PRED_TYPES = {
    PredicateType.EQ,
    PredicateType.NOT_EQ,
    PredicateType.IN,
    PredicateType.NOT_IN,
    PredicateType.RANGE,
    PredicateType.LIKE,
    PredicateType.REGEXP_LIKE,
}


def build_filter(f: FilterNode, ctx: BatchContext, params: dict, counter: list):
    """FilterNode → (template, params filled). Template is a nested hashable
    tuple; params dict maps slot names → device arrays."""
    t = f.type
    if t is FilterNodeType.CONSTANT_TRUE:
        return ("true",)
    if t is FilterNodeType.CONSTANT_FALSE:
        return ("false",)
    if t is FilterNodeType.AND:
        return ("and",) + tuple(build_filter(c, ctx, params, counter) for c in f.children)
    if t is FilterNodeType.OR:
        return ("or",) + tuple(build_filter(c, ctx, params, counter) for c in f.children)
    if t is FilterNodeType.NOT:
        return ("not", build_filter(f.children[0], ctx, params, counter))
    return build_predicate(f.predicate, ctx, params, counter)


def _slot(params: dict, counter: list, arr) -> str:
    key = f"p{counter[0]}"
    counter[0] += 1
    a = np.asarray(arr)
    if a.dtype == np.float64:
        a = a.astype(np.float32)  # device columns are f32; avoid f64 upcast
    params[key] = jnp.asarray(a)
    return key


def build_predicate(p: Predicate, ctx: BatchContext, params: dict, counter: list):
    if p.type not in _DEVICE_PRED_TYPES:
        raise DeviceUnsupported(f"predicate {p.type} not device-supported")
    lhs = p.lhs
    if lhs.is_identifier:
        enc = ctx.encoding(lhs.name)
        if enc == Encoding.DICT:
            return _dict_predicate(p, ctx, params, counter)
        return _raw_predicate(p, lhs, ctx, params, counter)
    # expression lhs: evaluate on device, compare in raw space
    return _raw_predicate(p, lhs, ctx, params, counter)


def _dict_predicate(p: Predicate, ctx: BatchContext, params: dict, counter: list):
    col = p.lhs.name
    dicts = ctx.dictionaries(col)
    t = p.type
    if t in (PredicateType.EQ, PredicateType.NOT_EQ):
        ids = np.array([d.index_of(p.value) for d in dicts], dtype=np.int32)
        ids[ids < 0] = -2  # never matches (pad is -1)
        key = _slot(params, counter, ids)
        tpl = ("eq_dict", col, key)
        return ("not", tpl) if t is PredicateType.NOT_EQ else tpl
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        k = max(1, len(p.values))
        mat = np.full((ctx.S, k), -2, dtype=np.int32)
        for i, d in enumerate(dicts):
            ids = d.ids_of(list(p.values))
            mat[i, : len(ids)] = ids
        key = _slot(params, counter, mat)
        tpl = ("in_dict", col, key, k)
        return ("not", tpl) if t is PredicateType.NOT_IN else tpl
    if t is PredicateType.RANGE:
        lo = np.zeros(ctx.S, dtype=np.int32)
        hi = np.zeros(ctx.S, dtype=np.int32)
        for i, d in enumerate(dicts):
            lo[i], hi[i] = d.range_ids(
                p.lower, p.upper, p.lower_inclusive, p.upper_inclusive
            )
        klo = _slot(params, counter, lo)
        khi = _slot(params, counter, hi)
        return ("range_dict", col, klo, khi)
    # LIKE / REGEXP_LIKE: evaluate once per dictionary entry → bool LUT
    pat = like_to_regex(p.value) if t is PredicateType.LIKE else p.value
    rx = re.compile(pat)
    match = rx.match if t is PredicateType.LIKE else rx.search
    cmax = ctx.max_card(col)
    lut = np.zeros((ctx.S, cmax), dtype=bool)
    for i, d in enumerate(dicts):
        vals = np.asarray(d.values).astype(str)
        lut[i, : len(vals)] = np.fromiter(
            (bool(match(s)) for s in vals), dtype=bool, count=len(vals)
        )
    key = _slot(params, counter, lut)
    return ("lut_dict", col, key)


def _raw_predicate(p: Predicate, lhs: Expression, ctx: BatchContext, params: dict,
                   counter: list):
    expr_tpl = build_expr(lhs, ctx, params, counter)
    t = p.type
    if t in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
        raise DeviceUnsupported("regex over raw (non-dict) column")
    if t in (PredicateType.EQ, PredicateType.NOT_EQ):
        key = _slot(params, counter, np.asarray(p.value))
        tpl = ("eq_raw", expr_tpl, key)
        return ("not", tpl) if t is PredicateType.NOT_EQ else tpl
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        key = _slot(params, counter, np.asarray(list(p.values)))
        tpl = ("in_raw", expr_tpl, key, len(p.values))
        return ("not", tpl) if t is PredicateType.NOT_IN else tpl
    # RANGE
    klo = _slot(params, counter, np.asarray(0 if p.lower is None else p.lower))
    khi = _slot(params, counter, np.asarray(0 if p.upper is None else p.upper))
    return (
        "range_raw",
        expr_tpl,
        klo,
        khi,
        p.lower is not None,
        p.upper is not None,
        p.lower_inclusive,
        p.upper_inclusive,
    )


# ---------------------------------------------------------------------------
# expression templates (device value-space evaluation)
# ---------------------------------------------------------------------------


def build_expr(e: Expression, ctx: BatchContext, params: dict, counter: list):
    if e.is_literal:
        if isinstance(e.value, str) or e.value is None:
            raise DeviceUnsupported("string/null literal in device expression")
        key = _slot(params, counter, np.asarray(e.value))
        return ("lit", key)
    if e.is_identifier:
        enc = ctx.encoding(e.name)
        if enc == Encoding.RAW:
            return ("raw", e.name)
        ctx.value_lut(e.name)  # validates numeric; uploaded lazily
        return ("dictval", e.name)
    fn = get_function(e.name)
    if not fn.device_capable:
        raise DeviceUnsupported(f"function {e.name} is host-only")
    if e.name == "cast":
        arg = build_expr(e.args[0], ctx, params, counter)
        return ("cast", arg, str(e.args[1].value).upper())
    return (e.name,) + tuple(build_expr(a, ctx, params, counter) for a in e.args)
