"""Device batch context: segment batch + parameter resolution.

This is the host-side half of the device query pipeline — the analog of the
reference's per-segment plan construction (predicate → dict-id resolution in
operator/filter/predicate/ PredicateEvaluator factories) re-shaped for
batched TPU launches:

- **Global-id columns**: per-segment dictionaries are unioned per column and
  the forward index is remapped into global id space *on the host at upload
  time* (a one-off numpy gather, cached with the batch). Device kernels then
  never touch per-segment dictionaries: group-by keys are the column itself,
  cross-segment combine is a dense scatter, and predicate literals resolve to
  *batch-wide scalars* via one binary search on the global dictionary.
  (Measured on v5e: this removes a per-doc remap gather that cost ~100x the
  actual aggregation scatter.)
- **Predicate params**: literals become replicated scalar/vector params; the
  jitted pipeline is a pure function of these params, so one compiled
  template serves all literal values. Regex/LIKE evaluate once per global
  dictionary entry into a (C,) boolean LUT.

Raises ``DeviceUnsupported`` for anything the device path doesn't accelerate;
the engine falls back to the host executor.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading

import numpy as np

from pinot_tpu.engine.host import like_to_regex
from pinot_tpu.ops.hll import hash32_np
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
)
from pinot_tpu.storage.device import padded_len
from pinot_tpu.storage.dictionary import Dictionary
from pinot_tpu.storage.segment import (
    ZONE_BLOCK_ROWS,
    Encoding,
    build_zone_map,
)

import jax.numpy as jnp


class DeviceUnsupported(Exception):
    """Query shape not handled by the device pipeline → host fallback."""


_NUMERIC_KINDS = ("i", "u", "f")

# ---------------------------------------------------------------------------
# cardinality-aware column width planning
# ---------------------------------------------------------------------------
# The reference never stores a forward index at full width
# (FixedBitSVForwardIndexReader reads ceil(log2(cardinality)) bits per dict
# id); the device path used to widen everything to int32/int64 before upload,
# making scans HBM-bandwidth-bound and the batch LRU evict batches that
# would fit 4-8x over at their true width. A ColPlan is the per-column
# device storage decision:
#
# - DICT id planes: uint8 (C <= 255), uint16 (C <= 65535), else int32 —
#   the pad sentinel is C itself on unsigned planes (ids are < C, so the
#   pad matches no literal) and -1 on signed ones (legacy). An OPT-IN
#   sub-byte tier (PINOT_TPU_SUBBYTE=1) packs 2-bit (C <= 3) / 4-bit
#   (C <= 15) ids into uint8 bytes, unpacked in-kernel with shifts/masks
#   (ops/masks.py unpack_subbyte).
# - RAW / decoded (dv::) int planes: frame-of-reference (min-offset)
#   downcast — values store as (v - min) in the narrowest unsigned dtype
#   whose span covers (max - min), decoding to the legacy wide dtype at
#   REGISTER level only (``wide`` + the per-batch "fo::<key>" offset
#   param). When values already fit the narrow dtype unsigned, the offset
#   is skipped entirely; int64 planes whose values fit int32 drop to a
#   plain int32.
# - Floats stay f32 (the pre-existing device value space).
#
# Zone-map (zlo::/zhi::) planes narrow WITH their column (stored in the
# same space the plane stores — id space or FOR space); ops/blockskip.py
# decodes them the same way the kernels decode the column.
#
# PINOT_TPU_FORCE_WIDE=1 restores the legacy widths end to end (the
# differential-parity reference form). Env knobs are read ONCE per
# BatchContext so a cached batch's plans never shift mid-life.


@dataclasses.dataclass(frozen=True)
class ColPlan:
    """Device storage plan for one column plane."""

    dtype: str          # numpy dtype .str of the STORED plane
    bits: int = 0       # sub-byte pack width (2 | 4); 0 = byte-aligned
    offset: int | None = None  # frame-of-reference offset (raw value space)
    wide: str = ""      # register decode target dtype ("" = none needed)

    @property
    def packed(self) -> bool:
        return self.bits > 0

    def sig(self) -> tuple:
        """Hashable template-key form (offset VALUE excluded — it is a
        runtime param, one compiled pipeline serves any offset)."""
        return (self.dtype, self.bits, self.offset is not None, self.wide)


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def _int_for_plan(lo: int, hi: int, base: np.dtype) -> ColPlan:
    """Frame-of-reference plan for an integer plane with exact (python
    int) bounds: narrowest unsigned dtype covering the RANGE, offset only
    when the values don't already fit unsigned, int32 fallback for int64
    planes whose values fit natively. Bounds arithmetic runs in python
    ints, so dtype-extreme columns (min near -2^63) can't overflow here."""
    rng = hi - lo
    for dt, span in ((np.uint8, 1 << 8), (np.uint16, 1 << 16)):
        ndt = np.dtype(dt)
        if ndt.itemsize >= base.itemsize:
            break  # no byte-width win at/past the base dtype
        if 0 <= lo and hi < span:
            return ColPlan(ndt.str, wide=base.str)
        if rng < span:
            return ColPlan(ndt.str, offset=int(lo), wide=base.str)
    if base.itemsize > 4:
        # same 4 bytes either way: prefer the offset-free native int32
        if -(1 << 31) <= lo and hi < (1 << 31):
            return ColPlan(np.dtype(np.int32).str, wide=base.str)
        if 0 <= lo and hi < (1 << 32):
            return ColPlan(np.dtype(np.uint32).str, wide=base.str)
        if rng < (1 << 32):
            return ColPlan(np.dtype(np.uint32).str, offset=int(lo),
                           wide=base.str)
    return ColPlan(base.str)


class BatchContext:
    """Host+device state for one batch of segments (cached per segment set)."""

    MAX_MV_K = 16  # (S, L, K) id blocks cost K x an SV column of HBM

    def __init__(self, segments: list, pad_multiple: int = 1024):
        self.segments = list(segments)
        # pad to a whole number of zone-map blocks so the block-skip path
        # (ops/blockskip.py) can reshape (S, L) -> (S * n_blocks, R) without
        # a second padding pass; worst case +3072 pad rows per segment
        pad_multiple = max(pad_multiple, ZONE_BLOCK_ROWS)
        self.pad_to = max(padded_len(s.n_docs, pad_multiple) for s in self.segments)
        self.S = len(self.segments)
        self.n_docs = np.array([s.n_docs for s in self.segments], dtype=np.int32)
        self.n_docs_dev = jnp.asarray(self.n_docs)
        self._columns: dict[str, object] = {}       # name -> (S, L) device array
        self._encodings: dict[str, str] = {}
        self._global_dicts: dict[str, Dictionary] = {}
        self._decoded: dict[str, object] = {}       # name -> (S, L) decoded values
        self._prehashed: dict[str, object] = {}     # name -> (S, L) value hashes
        self._mv_columns: dict[str, object] = {}    # name -> (S, L, K) id blocks
        self._sorted_hll: dict = {}   # (group_cols, hash_col, log2m) -> sorted keys
        # col key -> ((S, NB) lo, (S, NB) hi) device zone maps in the
        # column's device value space (global ids / decoded / raw); built
        # eagerly alongside the column block (the host data is in hand
        # there — rebuilding later would repeat the remap gather)
        self._zone_maps: dict = {}
        # concurrent queries share one cached BatchContext (the executor's
        # batch LRU): lazy materialization is locked so two threads never
        # build the same block twice. RLock: sorted_hll_keys re-enters
        # column. Resident bytes ride a LOCK-FREE counter updated at
        # block-insert time — the executor's _evict reads it from OTHER
        # queries' batches, and taking this lock there would stall
        # unrelated launches behind a cold multi-GB column build.
        self._lock = threading.RLock()
        self._resident_bytes = 0
        # width planning (ColPlan) — env knobs sampled ONCE so a cached
        # batch's plans (and the executor's width-keyed templates) never
        # shift mid-life; bytes the narrowing saved vs the legacy wide
        # layout accumulate lock-free like _resident_bytes
        self._force_wide = _env_flag("PINOT_TPU_FORCE_WIDE")
        self._subbyte = _env_flag("PINOT_TPU_SUBBYTE")
        self._plans: dict[str, ColPlan] = {}
        self._narrow_saved_bytes = 0

    # ---- column access ---------------------------------------------------
    def column_meta(self, name: str):
        for s in self.segments:
            if name in s.metadata.columns:
                return s.column_metadata(name)
        raise DeviceUnsupported(f"unknown column {name}")

    def encoding(self, name: str) -> str:
        with self._lock:
            return self._encoding_locked(name)

    def _encoding_locked(self, name: str) -> str:
        if name not in self._encodings:
            metas = []
            for s in self.segments:
                if name not in s.metadata.columns:
                    raise DeviceUnsupported(f"column {name} missing from {s.name}")
                metas.append(s.column_metadata(name))
            enc = metas[0].encoding
            if any(m.encoding != enc for m in metas):
                raise DeviceUnsupported(f"mixed encodings for {name}")
            if any(not m.single_value for m in metas):
                raise DeviceUnsupported(f"multi-value column {name}")
            self._encodings[name] = enc
        return self._encodings[name]

    def is_mv(self, name: str) -> bool:
        for s in self.segments:
            if name not in s.metadata.columns:
                raise DeviceUnsupported(f"column {name} missing from {s.name}")
            if s.column_metadata(name).single_value:
                return False
        return True

    def mv_column(self, name: str):
        """(S, L, K) device array of GLOBAL dict ids for an MV column,
        entries padded with -1 (K = batch max entries per doc). The device
        form of getDictIdMV (ForwardIndexReader.java:99) — predicates
        evaluate per entry and reduce match-any over K."""
        with self._lock:
            return self._mv_column_locked(name)

    def _mv_column_locked(self, name: str):
        if name not in self._mv_columns:
            metas = [s.column_metadata(name) for s in self.segments]
            if any(m.encoding != Encoding.DICT for m in metas):
                raise DeviceUnsupported(f"raw MV column {name} on device")
            K = max(m.max_mv_entries for m in metas)
            if K == 0 or K > self.MAX_MV_K:
                raise DeviceUnsupported(
                    f"MV column {name} has up to {K} entries/doc (cap {self.MAX_MV_K})"
                )
            gdict = self.global_dict(name)
            blocks = np.full((self.S, self.pad_to, K), -1, dtype=np.int32)
            for i, s in enumerate(self.segments):
                d = s.dictionary(name)
                remap = np.searchsorted(
                    gdict.values, np.asarray(d.values)
                ).astype(np.int32)
                fwd = np.asarray(s.forward(name))
                off = np.asarray(s.mv_offsets(name))
                lens = np.diff(off)
                doc_of_entry = np.repeat(
                    np.arange(len(lens), dtype=np.int64), lens
                )
                rank = np.arange(len(fwd), dtype=np.int64) - np.repeat(off[:-1], lens)
                blocks[i, doc_of_entry, rank] = remap[fwd]
            self._mv_columns[name] = jnp.asarray(blocks)
            self._note_resident(self._mv_columns[name])
        return self._mv_columns[name]

    # ---- width planning (ColPlan) ---------------------------------------
    def width_plan(self, key: str) -> ColPlan:
        """Device storage plan for a cols-dict key (bare column name or
        "dv::name"); the executor folds these into its template cache key
        so cohort coalescing keeps stacking same-shape queries."""
        with self._lock:
            return self._width_plan_locked(key)

    def _width_plan_locked(self, key: str) -> ColPlan:
        plan = self._plans.get(key)
        if plan is None:
            if key.startswith("dv::"):
                plan = self._plan_decoded(key[4:])
            elif self._encoding_locked(key) == Encoding.DICT:
                plan = self._plan_dict(key)
            else:
                plan = self._plan_raw(key)
            self._plans[key] = plan
        return plan

    def _plan_dict(self, name: str) -> ColPlan:
        if self._force_wide:
            return ColPlan(np.dtype(np.int32).str)
        C = len(self._global_dict_locked(name))
        # sub-byte tiers reserve the pad sentinel C inside the bit width
        if self._subbyte and C <= 3:
            return ColPlan(np.dtype(np.uint8).str, bits=2)
        if self._subbyte and C <= 15:
            return ColPlan(np.dtype(np.uint8).str, bits=4)
        if C <= 255:  # ids 0..C-1, pad C: C == 255 still fits uint8
            return ColPlan(np.dtype(np.uint8).str)
        if C <= 65535:
            return ColPlan(np.dtype(np.uint16).str)
        return ColPlan(np.dtype(np.int32).str)

    def _plan_raw(self, name: str) -> ColPlan:
        from pinot_tpu.storage.device import _RAW_DEVICE_DTYPES

        base = np.dtype(_RAW_DEVICE_DTYPES[self.column_meta(name).data_type])
        if self._force_wide or base.kind == "f":
            return ColPlan(base.str)
        b = self._exact_int_bounds(name)
        if b is None:
            return ColPlan(base.str)
        return _int_for_plan(b[0], b[1], base)

    def _plan_decoded(self, name: str) -> ColPlan:
        if self._encoding_locked(name) != Encoding.DICT:
            return self._width_plan_locked(name)  # dv:: of RAW aliases raw
        per_seg = [np.asarray(s.dictionary(name).values)
                   for s in self.segments]
        if any(v.dtype.kind == "f" for v in per_seg):
            return ColPlan(np.dtype(np.float32).str)
        base = np.dtype(np.int64) if any(v.dtype.itemsize == 8
                                         for v in per_seg) \
            else np.dtype(np.int32)
        if self._force_wide or not any(len(v) for v in per_seg):
            return ColPlan(base.str)
        # dictionaries are sorted: batch bounds are the edge values
        lo = min(int(v[0]) for v in per_seg if len(v))
        hi = max(int(v[-1]) for v in per_seg if len(v))
        return _int_for_plan(lo, hi, base)

    def _exact_int_bounds(self, name: str):
        """(min, max) as exact python ints from segment metadata, or None
        (missing stats / non-integer values) — int_bounds() stays float
        for the two-stage-sum interval arithmetic; FOR offsets need
        exactness at dtype extremes."""
        mns, mxs = [], []
        for s in self.segments:
            m = s.column_metadata(name)
            if not isinstance(m.min_value, (int, np.integer)) \
                    or not isinstance(m.max_value, (int, np.integer)):
                return None
            mns.append(int(m.min_value))
            mxs.append(int(m.max_value))
        return (min(mns), max(mxs)) if mns else None

    def _dict_pad(self, name: str, plan: ColPlan) -> int:
        """Pad sentinel for an id plane: C on unsigned planes (< any real
        id's successor, matches no literal, fits by the tier rule), -1 on
        signed (legacy)."""
        if np.dtype(plan.dtype).kind == "u":
            return len(self._global_dict_locked(name))
        return -1

    @staticmethod
    def _pack_subbyte_np(blocks: np.ndarray, bits: int) -> np.ndarray:
        """(S, L) small ids → (S, L * bits // 8) uint8, little-endian
        within each byte (the host-side inverse of ops/masks.py
        unpack_subbyte)."""
        f = 8 // bits
        v = blocks.reshape(blocks.shape[0], -1, f).astype(np.uint16)
        shifts = np.arange(f, dtype=np.uint16) * bits
        return (v << shifts).sum(axis=-1, dtype=np.uint16).astype(np.uint8)

    def _note_saved(self, wide_nbytes: int, *arrays) -> None:
        """Caller holds self._lock: record bytes the width plan saved vs
        the legacy wide layout of the same logical plane(s)."""
        actual = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
        if wide_nbytes > actual:
            self._narrow_saved_bytes += wide_nbytes - actual

    def narrow_saved_bytes(self) -> int:
        """HBM bytes saved by width planning vs the r05 wide layout
        (lock-free read, like device_bytes)."""
        return self._narrow_saved_bytes

    def column(self, name: str):
        """(S, L) device array at the column's PLANNED width: **global**
        dict ids (DICT — pad -1 signed / C unsigned; sub-byte plans pack
        8//bits ids per byte into an (S, L * bits // 8) plane) or raw
        values (RAW — frame-of-reference storage when the plan carries an
        offset, pad 0)."""
        with self._lock:
            return self._column_locked(name)

    def _column_locked(self, name: str):
        if name not in self._columns:
            enc = self.encoding(name)
            plan = self._width_plan_locked(name)
            sdt = np.dtype(plan.dtype)
            if enc == Encoding.DICT:
                gdict = self.global_dict(name)
                pad = self._dict_pad(name, plan)
                blocks = np.full((self.S, self.pad_to), pad, dtype=sdt)
                zlo, zhi = self._zone_fills(sdt)
                for i, s in enumerate(self.segments):
                    d = s.dictionary(name)
                    remap = np.searchsorted(
                        gdict.values, np.asarray(d.values)
                    ).astype(np.int32)
                    fwd = np.asarray(s.forward(name))
                    gids = remap[fwd]
                    blocks[i, : len(fwd)] = gids  # ids < C: fits the plan
                    zm = self._reader_zone_map(s, name, len(fwd))
                    # local->global id remap is monotone (both dictionaries
                    # are sorted), so per-block min/max ids survive it
                    z = remap[np.asarray(zm)] if zm is not None \
                        else build_zone_map(gids)
                    zlo[i, : z.shape[1]] = z[0]
                    zhi[i, : z.shape[1]] = z[1]
                if plan.packed:
                    blocks = self._pack_subbyte_np(blocks, plan.bits)
            else:
                off = plan.offset or 0
                blocks = np.zeros((self.S, self.pad_to), dtype=sdt)
                zlo, zhi = self._zone_fills(sdt)
                for i, s in enumerate(self.segments):
                    fwd = np.asarray(s.forward(name))
                    if off:
                        # FOR storage: python-int-exact metadata bounds
                        # guarantee (v - off) fits the plan dtype; the
                        # int64 intermediate never overflows (|off| and v
                        # both fit int64 and their difference fits uint32)
                        vals = (fwd.astype(np.int64) - off).astype(sdt)
                    else:
                        # astype matches the device narrowing (float
                        # round-to-nearest is monotone, so narrowed
                        # bounds still bound the narrowed values)
                        vals = fwd.astype(sdt)
                    blocks[i, : len(fwd)] = vals
                    zm = self._reader_zone_map(s, name, s.n_docs)
                    if zm is not None:
                        zm = np.asarray(zm)
                        z = ((zm.astype(np.int64) - off).astype(sdt)
                             if off else zm.astype(sdt))
                    else:
                        z = build_zone_map(blocks[i, : s.n_docs])
                    zlo[i, : z.shape[1]] = z[0]
                    zhi[i, : z.shape[1]] = z[1]
            self._columns[name] = jnp.asarray(blocks)
            self._note_resident(self._columns[name])
            self._store_zone_map(name, zlo, zhi)
            # legacy wide layout: int32 id plane / base-dtype raw plane,
            # plus two int32/base zone planes
            wide_item = 4 if enc == Encoding.DICT else \
                np.dtype(self._legacy_raw_dtype(name)).itemsize
            nb = self.pad_to // ZONE_BLOCK_ROWS
            self._note_saved(
                wide_item * self.S * (self.pad_to + 2 * nb),
                self._columns[name], *self._zone_maps[name])
        return self._columns[name]

    def _legacy_raw_dtype(self, name: str):
        from pinot_tpu.storage.device import _RAW_DEVICE_DTYPES

        return _RAW_DEVICE_DTYPES[self.column_meta(name).data_type]

    # ---- zone maps (device block-skip basis, ops/blockskip.py) ----------
    def _zone_fills(self, dtype):
        """(S, NB) lo/hi arrays pre-filled with never-match sentinels (lo =
        dtype max, hi = dtype min) so padding blocks past a segment's data
        satisfy no interval predicate."""
        nb = self.pad_to // ZONE_BLOCK_ROWS
        dtype = np.dtype(dtype)
        if dtype.kind in ("i", "u"):
            lof, hif = np.iinfo(dtype).max, np.iinfo(dtype).min
        else:
            lof, hif = np.finfo(dtype).max, np.finfo(dtype).min
        return (np.full((self.S, nb), lof, dtype=dtype),
                np.full((self.S, nb), hif, dtype=dtype))

    @staticmethod
    def _reader_zone_map(seg, name: str, n: int):
        """Segment-provided (2, n_blocks) zone map (sealed: <col>.zmap.npy;
        chunklets: computed at promotion), or None -> recompute from the
        column block (pre-zone-map segments)."""
        fn = getattr(seg, "zone_map", None)
        if fn is None:
            return None
        try:
            zm = fn(name)
        except Exception:  # noqa: BLE001 — corrupt file: recompute instead
            return None
        if zm is None:
            return None
        zm = np.asarray(zm)
        if zm.shape != (2, -(-n // ZONE_BLOCK_ROWS)):
            return None  # stale granularity: recompute
        return zm

    def _store_zone_map(self, key: str, zlo, zhi) -> None:
        self._zone_maps[key] = (jnp.asarray(zlo), jnp.asarray(zhi))
        for a in self._zone_maps[key]:
            self._note_resident(a)

    def zone_map(self, key: str):
        """((S, NB) lo, (S, NB) hi) device zone arrays for a cols-dict key
        (bare name -> global dict ids or raw values; "dv::name" -> decoded
        values), materializing the backing column on first use."""
        with self._lock:
            if key not in self._zone_maps:
                if key.startswith("dv::"):
                    self._decoded_column_locked(key[4:])
                else:
                    self._column_locked(key)
            return self._zone_maps[key]

    def global_dict(self, name: str) -> Dictionary:
        """Sorted union of per-segment dictionary values (global id space)."""
        with self._lock:
            return self._global_dict_locked(name)

    def _global_dict_locked(self, name: str) -> Dictionary:
        if name not in self._global_dicts:
            vals = []
            for s in self.segments:
                d = s.dictionary(name)
                if d is None:
                    raise DeviceUnsupported(f"column {name} lacks a dictionary")
                vals.append(np.asarray(d.values))
            self._global_dicts[name] = Dictionary(np.unique(np.concatenate(vals)))
        return self._global_dicts[name]

    def cardinality(self, name: str) -> int:
        return len(self.global_dict(name))

    def decoded_column(self, name: str):
        """(S, L) device array of DECODED numeric values for a dict column —
        the per-doc LUT gather runs on the host at upload (numpy fancy
        index, one-off, cached); device kernels never gather. Measured on
        v5e a (C,)-LUT gather over 12M docs costs ~80ms per query — this
        removes it entirely. Floats decode to f32 (the device value space,
        as the old value-LUT path did); ints keep the WIDEST dtype across
        segments."""
        with self._lock:
            return self._decoded_column_locked(name)

    def _decoded_column_locked(self, name: str):
        if name not in self._decoded:
            if self.encoding(name) != Encoding.DICT:
                return self.column(name)
            per_seg = []
            for s in self.segments:
                vals = np.asarray(s.dictionary(name).values)
                if vals.dtype.kind not in _NUMERIC_KINDS:
                    raise DeviceUnsupported(f"non-numeric dict column {name} in expression")
                per_seg.append(vals)
            plan = self._width_plan_locked("dv::" + name)
            sdt = np.dtype(plan.dtype)
            off = plan.offset or 0
            # legacy wide layout = the plan's decode target (un-narrowed
            # plans store the legacy dtype already)
            wide_item = np.dtype(plan.wide).itemsize if plan.wide \
                else sdt.itemsize
            blocks = np.zeros((self.S, self.pad_to), dtype=sdt)
            zlo, zhi = self._zone_fills(sdt)
            for i, (s, vals) in enumerate(zip(self.segments, per_seg)):
                fwd = np.asarray(s.forward(name))
                # FOR narrowing happens on the (C,)-sized LUT, not the
                # rows: one subtract per distinct value, then the same
                # one-off host gather as before
                lut = (vals.astype(np.int64) - off).astype(sdt) if off \
                    else vals.astype(sdt)
                blocks[i, : len(fwd)] = lut[fwd]
                zm = self._reader_zone_map(s, name, len(fwd))
                # id zone -> value zone through the sorted dictionary (id
                # order == value order, so min/max ids decode to min/max
                # values)
                z = lut[np.asarray(zm)] if zm is not None \
                    else build_zone_map(blocks[i, : len(fwd)])
                zlo[i, : z.shape[1]] = z[0]
                zhi[i, : z.shape[1]] = z[1]
            self._decoded[name] = jnp.asarray(blocks)
            self._note_resident(self._decoded[name])
            self._store_zone_map("dv::" + name, zlo, zhi)
            nb = self.pad_to // ZONE_BLOCK_ROWS
            self._note_saved(
                wide_item * self.S * (self.pad_to + 2 * nb),
                self._decoded[name], *self._zone_maps["dv::" + name])
        return self._decoded[name]

    def prehashed_column(self, name: str):
        """(S, L) device array of per-doc canonical value hashes for
        DISTINCTCOUNTHLL — host-side LUT gather at upload replaces the
        device hash-LUT gather (~80ms/query on v5e at 12M docs)."""
        with self._lock:
            return self._prehashed_column_locked(name)

    def _prehashed_column_locked(self, name: str):
        if name not in self._prehashed:
            blocks = np.zeros((self.S, self.pad_to), dtype=np.uint32)
            for i, s in enumerate(self.segments):
                h = hash32_np(np.asarray(s.dictionary(name).values))
                fwd = np.asarray(s.forward(name))
                blocks[i, : len(fwd)] = h[fwd]
            self._prehashed[name] = jnp.asarray(blocks)
            self._note_resident(self._prehashed[name])
        return self._prehashed[name]

    def bytes_width(self, name: str) -> int:
        """Fixed byte width of a BYTES dict column's values (0 = not a
        fixed-width bytes column)."""
        widths = set()
        for s in self.segments:
            d = s.dictionary(name)
            if d is None:
                return 0
            dt = np.asarray(d.values).dtype
            if dt.kind != "S":
                return 0
            widths.add(dt.itemsize)
        return widths.pop() if len(widths) == 1 else 0

    def bytes_plane_column(self, name: str):
        """(S, L, W) device array of raw byte planes for a fixed-width
        BYTES dict column (HLLMERGE's pre-aggregated register planes) —
        per-doc LUT gather on the host at upload, like decoded_column."""
        with self._lock:
            return self._bytes_plane_locked(name)

    def _bytes_plane_locked(self, name: str):
        key = "bp::" + name
        if key not in self._decoded:
            W = self.bytes_width(name)
            if W == 0:
                raise DeviceUnsupported(
                    f"column {name} is not a fixed-width BYTES dict column")
            blocks = np.zeros((self.S, self.pad_to, W), dtype=np.uint8)
            for i, s in enumerate(self.segments):
                vals = np.asarray(s.dictionary(name).values)
                planes = vals.view(np.uint8).reshape(len(vals), W)
                fwd = np.asarray(s.forward(name))
                blocks[i, : len(fwd)] = planes[fwd]
            self._decoded[key] = jnp.asarray(blocks)
            self._note_resident(self._decoded[key])
        return self._decoded[key]

    def _note_resident(self, arr) -> None:
        """Caller holds self._lock; device_bytes reads the counter
        lock-free (int update under the GIL)."""
        self._resident_bytes += int(getattr(arr, "nbytes", 0))

    def device_bytes(self) -> int:
        """HBM resident bytes of materialized column blocks (columns +
        decoded + prehashed + sorted projections) — the executor's
        byte-aware LRU eviction key. LOCK-FREE read of the insert-time
        counter: _evict must never block behind another query's cold
        column build."""
        return self._resident_bytes

    def sorted_hll_keys(self, group_cols, group_cards, hash_col: str,
                        log2m: int):
        """(n_total,) device int32: SORTED packed ``slot << 5 | rho`` keys
        for the FILTERLESS HLL scan over these group columns — a lazily
        built sorted projection, cached per batch exactly like the
        prehashed/decoded columns (the role a sorted index plays in the
        reference: built once, reused by every later query of the shape).
        The first query pays the lax.sort (~320ms at 100M rows on v5e);
        repeats reduce boundaries + one matmul (~60ms)."""
        with self._lock:
            return self._sorted_hll_keys_locked(
                group_cols, group_cards, hash_col, log2m)

    def _sorted_hll_keys_locked(self, group_cols, group_cards, hash_col: str,
                                log2m: int):
        key = (tuple(group_cols), tuple(group_cards), hash_col, int(log2m))
        if key not in self._sorted_hll:
            import jax

            from pinot_tpu.ops import agg as agg_ops
            from pinot_tpu.ops import hll as hll_ops
            from pinot_tpu.ops import masks as mask_ops

            num_groups = 1
            for c in group_cards:
                num_groups *= int(c)
            m = 1 << log2m
            # sub-byte id planes unpack before the sort build (the sorted
            # projection is row-scale anyway; group_ids_combine widens ids
            # to int32 in-register regardless of plane width)
            per_col = []
            for c in group_cols:
                col = self._column_locked(c)
                plan = self._width_plan_locked(c)
                if plan.packed:
                    col = mask_ops.unpack_subbyte(col, plan.bits)
                per_col.append(col)
            hh = self.prehashed_column(hash_col)

            def build(cols_list, h, n_docs):
                valid = mask_ops.valid_mask(n_docs, h.shape[1], batched=True)
                gid = agg_ops.group_ids_combine(
                    cols_list, group_cards, valid, num_groups)
                idx, rho = hll_ops.hll_idx_rho(h, log2m)
                slot = jnp.where(valid, gid * m + idx, num_groups * m)
                k32 = (slot.reshape(-1).astype(jnp.int32) << 5) \
                    | rho.reshape(-1).astype(jnp.int32)
                return jax.lax.sort(k32)

            self._sorted_hll[key] = jax.jit(build)(
                per_col, hh, self.n_docs_dev)
            self._note_resident(self._sorted_hll[key])
        return self._sorted_hll[key]

    def int_bounds(self, name: str):
        """(min, max) over the batch from column metadata, or None."""
        mns, mxs = [], []
        for s in self.segments:
            m = s.column_metadata(name)
            if m.min_value is None or m.max_value is None:
                return None
            mns.append(m.min_value)
            mxs.append(m.max_value)
        try:
            return float(min(mns)), float(max(mxs))
        except (TypeError, ValueError):
            return None


# ---------------------------------------------------------------------------
# filter template + params
# ---------------------------------------------------------------------------

_DEVICE_PRED_TYPES = {
    PredicateType.EQ,
    PredicateType.NOT_EQ,
    PredicateType.IN,
    PredicateType.NOT_IN,
    PredicateType.RANGE,
    PredicateType.LIKE,
    PredicateType.REGEXP_LIKE,
}


def build_filter(f: FilterNode, ctx: BatchContext, params: dict, counter: list):
    """FilterNode → (template, params filled). Template is a nested hashable
    tuple; params dict maps slot names → device arrays (all replicated —
    global id space has no per-segment params)."""
    t = f.type
    if t is FilterNodeType.CONSTANT_TRUE:
        return ("true",)
    if t is FilterNodeType.CONSTANT_FALSE:
        return ("false",)
    if t is FilterNodeType.AND:
        return ("and",) + tuple(build_filter(c, ctx, params, counter) for c in f.children)
    if t is FilterNodeType.OR:
        return ("or",) + tuple(build_filter(c, ctx, params, counter) for c in f.children)
    if t is FilterNodeType.NOT:
        return ("not", build_filter(f.children[0], ctx, params, counter))
    return build_predicate(f.predicate, ctx, params, counter)


# device-resident literal/LUT cache: repeated query shapes re-upload the
# same predicate literals on every execute (one device_put each ≈ 1ms of
# host dispatch; measured ~5ms/query on a 6-literal filter). Keyed on the
# HOST bytes BEFORE upload — keying on the device array would need a
# blocking device→host read, costing a round trip instead of saving one.
# Locked: server query threads run _slot concurrently. Bounded at
# 256 × 64KB = 16MB of HBM worst case (big IN-list LUTs skip the cache —
# DeviceExecutor's batch budget doesn't know about this one).
_LITERAL_CACHE: dict = {}
_LITERAL_CACHE_LOCK = threading.Lock()
_LITERAL_CACHE_MAX = 256
_LITERAL_MAX_BYTES = 64 << 10


def _slot(params: dict, counter: list, arr) -> str:
    key = f"pr{counter[0]}"
    counter[0] += 1
    a = np.asarray(arr)
    if a.dtype == np.float64:
        a = a.astype(np.float32)  # device columns are f32; avoid f64 upcast
    sig = params.get("__hostsig__")
    if sig is not None:
        # host-bytes record for the executor's partials-cache digest
        # (engine/device.py): the VALUE identity of this literal, taken
        # BEFORE upload — reading it back off the device would cost the
        # very round trip the cache exists to save
        sig.append((key, a.dtype.str, a.shape, a.tobytes()))
    if a.nbytes <= _LITERAL_MAX_BYTES:
        ck = (a.dtype.str, a.shape, a.tobytes())
        with _LITERAL_CACHE_LOCK:
            hit = _LITERAL_CACHE.pop(ck, None)
        if hit is None:
            hit = jnp.asarray(a)
        with _LITERAL_CACHE_LOCK:
            _LITERAL_CACHE[ck] = hit  # LRU re-insert
            while len(_LITERAL_CACHE) > _LITERAL_CACHE_MAX:
                _LITERAL_CACHE.pop(next(iter(_LITERAL_CACHE)), None)
        params[key] = hit
    else:
        params[key] = jnp.asarray(a)
    return key


def build_predicate(p: Predicate, ctx: BatchContext, params: dict, counter: list):
    if p.type not in _DEVICE_PRED_TYPES:
        raise DeviceUnsupported(f"predicate {p.type} not device-supported")
    lhs = p.lhs
    if lhs.is_identifier:
        if ctx.is_mv(lhs.name):
            # match-any over the (S, L, K) id block: the inner template is
            # the ordinary dict predicate evaluated per entry; mv_any reduces
            # over K with -1 padding masked out (NOT_EQ's inner "not" stays
            # per-entry — reference MV semantics: ANY entry != value)
            ctx.mv_column(lhs.name)  # validates dict encoding + K cap
            tpl = _dict_predicate(p, ctx, params, counter, col_key="mv::" + lhs.name)
            return ("mv_any", "mv::" + lhs.name, tpl)
        enc = ctx.encoding(lhs.name)
        if enc == Encoding.DICT:
            return _dict_predicate(p, ctx, params, counter)
        return _raw_predicate(p, lhs, ctx, params, counter)
    # expression lhs: evaluate on device, compare in raw space
    return _raw_predicate(p, lhs, ctx, params, counter)


def _dict_predicate(p: Predicate, ctx: BatchContext, params: dict, counter: list,
                    col_key: str = None):
    col = col_key or p.lhs.name
    gdict = ctx.global_dict(p.lhs.name)
    t = p.type
    if t in (PredicateType.EQ, PredicateType.NOT_EQ):
        gid = gdict.index_of(p.value)
        key = _slot(params, counter, np.int32(gid if gid >= 0 else -2))
        tpl = ("eq_dict", col, key)
        return ("not", tpl) if t is PredicateType.NOT_EQ else tpl
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        k = max(1, len(p.values))
        vec = np.full(k, -2, dtype=np.int32)
        ids = gdict.ids_of(list(p.values))
        vec[: len(ids)] = ids
        key = _slot(params, counter, vec)
        tpl = ("in_dict", col, key, k)
        return ("not", tpl) if t is PredicateType.NOT_IN else tpl
    if t is PredicateType.RANGE:
        lo, hi = gdict.range_ids(
            p.lower, p.upper, p.lower_inclusive, p.upper_inclusive
        )
        klo = _slot(params, counter, np.int32(lo))
        khi = _slot(params, counter, np.int32(hi))
        return ("range_dict", col, klo, khi)
    # LIKE / REGEXP_LIKE: evaluate once per global dictionary entry → bool LUT
    pat = like_to_regex(p.value) if t is PredicateType.LIKE else p.value
    rx = re.compile(pat)
    match = rx.match if t is PredicateType.LIKE else rx.search
    vals = np.asarray(gdict.values).astype(str)
    lut = np.fromiter((bool(match(s)) for s in vals), dtype=bool, count=len(vals))
    key = _slot(params, counter, lut)
    return ("lut_dict", col, key)


def _raw_predicate(p: Predicate, lhs: Expression, ctx: BatchContext, params: dict,
                   counter: list):
    expr_tpl = build_expr(lhs, ctx, params, counter)
    t = p.type
    if t in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
        raise DeviceUnsupported("regex over raw (non-dict) column")
    if t in (PredicateType.EQ, PredicateType.NOT_EQ):
        key = _slot(params, counter, np.asarray(p.value))
        tpl = ("eq_raw", expr_tpl, key)
        return ("not", tpl) if t is PredicateType.NOT_EQ else tpl
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        key = _slot(params, counter, np.asarray(list(p.values)))
        tpl = ("in_raw", expr_tpl, key, len(p.values))
        return ("not", tpl) if t is PredicateType.NOT_IN else tpl
    # RANGE
    klo = _slot(params, counter, np.asarray(0 if p.lower is None else p.lower))
    khi = _slot(params, counter, np.asarray(0 if p.upper is None else p.upper))
    return (
        "range_raw",
        expr_tpl,
        klo,
        khi,
        p.lower is not None,
        p.upper is not None,
        p.lower_inclusive,
        p.upper_inclusive,
    )


# ---------------------------------------------------------------------------
# expression templates (device value-space evaluation)
# ---------------------------------------------------------------------------


def build_expr(e: Expression, ctx: BatchContext, params: dict, counter: list):
    if e.is_literal:
        if isinstance(e.value, str) or e.value is None:
            raise DeviceUnsupported("string/null literal in device expression")
        key = _slot(params, counter, np.asarray(e.value))
        return ("lit", key)
    if e.is_identifier:
        enc = ctx.encoding(e.name)
        if enc == Encoding.RAW:
            return ("raw", e.name)
        if np.asarray(ctx.global_dict(e.name).values).dtype.kind not in _NUMERIC_KINDS:
            raise DeviceUnsupported(f"non-numeric dict column {e.name} in expression")
        return ("dictval", e.name)
    fn = get_function(e.name)
    if not fn.device_capable:
        raise DeviceUnsupported(f"function {e.name} is host-only")
    if e.name == "cast":
        arg = build_expr(e.args[0], ctx, params, counter)
        return ("cast", arg, str(e.args[1].value).upper())
    return (e.name,) + tuple(build_expr(a, ctx, params, counter) for a in e.args)


def expr_bounds(e: Expression, ctx: BatchContext):
    """Interval arithmetic over column metadata: |bound| for two-stage sum
    block sizing (ops/agg.py rows_per_block_for). None = unknown."""
    if e.is_literal:
        try:
            v = float(e.value)
            return v, v
        except (TypeError, ValueError):
            return None
    if e.is_identifier:
        return ctx.int_bounds(e.name)
    if not e.is_function:
        return None
    if e.name in ("plus", "minus", "times"):
        a = expr_bounds(e.args[0], ctx)
        b = expr_bounds(e.args[1], ctx)
        if a is None or b is None:
            return None
        if e.name == "plus":
            return a[0] + b[0], a[1] + b[1]
        if e.name == "minus":
            return a[0] - b[1], a[1] - b[0]
        prods = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
        return min(prods), max(prods)
    if e.name == "cast":
        return expr_bounds(e.args[0], ctx)
    if e.name == "abs":
        b = expr_bounds(e.args[0], ctx)
        if b is None:
            return None
        lo = 0.0 if b[0] <= 0 <= b[1] else min(abs(b[0]), abs(b[1]))
        return lo, max(abs(b[0]), abs(b[1]))
    return None
