"""Feedback-driven plan advisor: per-template memos that turn the PR-11
telemetry into execution decisions.

The engine *measures* everything — per-kernel achieved GB/s, block-skip
pruning ratios, build-side row counts, cache-hit rates, observed group
counts — but used to *decide* almost everything by static constant:
join strategy by ``BROADCAST_MAX_BUILD_ROWS``, block-skip by a fixed
``ceil(total/16)`` candidate bound, trim by a fixed ``group_trim_size``,
cohort windows by scheduler pressure alone. The reference makes these
calls with ``InstancePlanMakerImplV2``'s hand-tuned heuristics; the
advisor replaces the hand-tuning with the measurements the system
already collects (PAPER.md layer 5, ROADMAP item 2).

Design:

- **PlanMemo**: one memo per literal-free ``template_key`` (PR 7),
  holding EWMA'd measurements — build-side rows per alias, effective
  join strategy, block-skip selectivity (``blocks_scanned /
  blocks_total``), per-rung kernel GB/s (Pallas vs XLA roofline
  labels), observed group counts, cohort sizes, cache-hit counts.
- **Bounded LRU + decay**: memos live per server/broker process (no
  persistence across restarts in v1); the map is LRU-bounded, and a
  measurement that *drifts* (a table's shape changed) halves the
  signal's confidence so advice stands down until it re-converges —
  decisions decay toward the static defaults rather than chasing stale
  measurements.
- **Safety**: every advised decision is either bit-exact by
  construction (join strategies compute identical rows; the Pallas and
  XLA rungs are differential-pinned; a candidate-bound overflow falls
  back to the dense branch *in kernel*) or guarded by a no-drop rule
  (trim tightens only when the observed group count plus headroom still
  fits, so no group the default would keep is ever dropped).
- **Debuggability**: every overridden decision returns an
  ``ADVISOR(<decision>: measured=X default=Y)`` line that rides the
  response (``advisorDecisions``), the query log, and EXPLAIN ANALYZE.
- ``SET useAdvisor=false`` bypasses both reads and writes for a query
  (zero memo effect, bit-exact against advisor-on by the rules above).

Config (common/config.py Configuration keys):

- ``pinot.advisor.enabled``        (default True)
- ``pinot.advisor.max.memos``      (default 256; LRU bound)
- ``pinot.advisor.min.samples``    (default 3; advice warmup)
- ``pinot.advisor.ewma.alpha``     (default 0.3)
- ``pinot.advisor.reprobe.every``  (default 16; periodic default-probe
  so a sticky decision (e.g. advised-dense block skip, whose ratio is
  only measurable on the skip path) re-measures and can un-stick)
"""

from __future__ import annotations

import threading
from collections import OrderedDict

# relative deviation past which an observation counts as DRIFT: the
# memo's confidence halves so advice stands down toward the default
DRIFT_FACTOR = 3.0
# headroom multipliers: advice must beat the default by a real margin,
# not measurement noise
TRIM_HEADROOM = 1.5       # tightened trim keeps >= groups_hi * this
CAND_HEADROOM = 2.5       # 1/frac must be >= observed ratio * this
PALLAS_MARGIN = 1.15      # rung switch needs >= 15% measured GB/s edge
DENSE_RATIO = 0.75        # skip ratio past this: block-skip buys nothing


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class _Ewma:
    """Mean tracker with drift detection: ``add`` returns True when the
    sample deviated far enough from the converged mean to halve the
    confidence count (decay toward the default)."""

    __slots__ = ("mean", "n", "alpha")

    def __init__(self, alpha: float = 0.3):
        self.mean = 0.0
        self.n = 0
        self.alpha = alpha

    def add(self, x: float) -> bool:
        x = float(x)
        if self.n == 0:
            self.mean = x
            self.n = 1
            return False
        drift = abs(x - self.mean) > DRIFT_FACTOR * max(abs(self.mean), 1e-9)
        self.mean += self.alpha * (x - self.mean)
        if drift:
            # stats drifted: halve confidence so advice stands down and
            # the mean re-converges before decisions resume
            self.n = self.n // 2
        else:
            self.n += 1
        return drift

    def ready(self, min_samples: int) -> bool:
        return self.n >= min_samples


class PlanMemo:
    """Measurements for one query template (one LRU slot)."""

    __slots__ = ("key", "build_rows", "strategies", "demotions",
                 "skip_ratio", "gbps", "groups", "groups_hi",
                 "trim_overflows", "cohort", "partials_hits",
                 "result_hits", "executions", "decisions", "overrides",
                 "drift_cooldown", "_probe_tick")

    def __init__(self, key: str, alpha: float):
        self.key = key
        self.build_rows: dict = {}      # alias -> _Ewma of measured rows
        self.strategies: dict = {}      # effective strategy -> count
        self.demotions = 0              # PR-15 distributed demotions seen
        self.skip_ratio = _Ewma(alpha)  # blocks_scanned / blocks_total
        self.gbps: dict = {}            # (base label, rung) -> _Ewma GB/s
        self.groups = _Ewma(alpha)      # observed group count
        self.groups_hi = 0              # decaying max (trim safety bound)
        self.trim_overflows = 0         # advised keep < observed groups
        self.cohort = _Ewma(alpha)      # coalescer cohort sizes
        self.partials_hits = [0, 0]     # [hits, total]
        self.result_hits = [0, 0]
        self.executions = 0
        self.decisions = 0              # advise_* calls that were ready
        self.overrides = 0              # decisions that beat the default
        self.drift_cooldown = 0         # observations until "converged"
        self._probe_tick = 0            # periodic default re-probe clock

    def convergence(self, min_samples: int) -> str:
        """"cold" (still warming up), "drifting" (a recent drift reset
        confidence), or "converged" (advice-ready) — the per-template
        state tools/querylog.py renders."""
        if self.drift_cooldown > 0:
            return "drifting"
        signals = [self.skip_ratio, self.groups, self.cohort,
                   *self.build_rows.values(), *self.gbps.values()]
        if any(s.ready(min_samples) for s in signals):
            return "converged"
        return "cold"

    def snapshot(self) -> dict:
        return {
            "executions": self.executions,
            "decisions": self.decisions,
            "overrides": self.overrides,
            "strategies": dict(self.strategies),
            "demotions": self.demotions,
            "skipRatio": round(self.skip_ratio.mean, 4)
            if self.skip_ratio.n else None,
            "groupsHi": self.groups_hi,
            "trimOverflows": self.trim_overflows,
            "cohortMean": round(self.cohort.mean, 2)
            if self.cohort.n else None,
        }


class PlanAdvisor:
    """Thread-safe per-process plan memo store + decision maker.

    ``observe`` records what actually happened; ``advise_*`` feed it
    back. Every advise method returns ``(value, note)`` where ``note``
    is the ``ADVISOR(...)`` stamp when the decision overrode the static
    default and None when it confirmed it (no stamp — a confirming
    decision is not an override and must not imply one)."""

    def __init__(self, max_memos: int = 256, min_samples: int = 3,
                 alpha: float = 0.3, reprobe_every: int = 16):
        self.max_memos = max(1, int(max_memos))
        self.min_samples = max(1, int(min_samples))
        self.alpha = float(alpha)
        self.reprobe_every = max(2, int(reprobe_every))
        self._memos: OrderedDict[str, PlanMemo] = OrderedDict()
        self._lock = threading.RLock()
        self.evictions = 0
        self.observations = 0
        self.decisions = 0
        self.overrides = 0

    @classmethod
    def from_config(cls, conf=None) -> "PlanAdvisor | None":
        """Config-built advisor, or None when disabled process-wide."""
        if conf is None:
            from pinot_tpu.common.config import Configuration

            conf = Configuration()
        if not conf.get_bool("pinot.advisor.enabled", True):
            return None
        return cls(
            max_memos=int(conf.get_float("pinot.advisor.max.memos", 256)),
            min_samples=int(conf.get_float("pinot.advisor.min.samples", 3)),
            alpha=conf.get_float("pinot.advisor.ewma.alpha", 0.3),
            reprobe_every=int(conf.get_float(
                "pinot.advisor.reprobe.every", 16)),
        )

    # ---- memo lifecycle --------------------------------------------------
    def _memo(self, key: str) -> PlanMemo:
        """Get-or-create under the lock; touches LRU order and evicts
        past the bound."""
        m = self._memos.get(key)
        if m is None:
            m = PlanMemo(key, self.alpha)
            self._memos[key] = m
            while len(self._memos) > self.max_memos:
                self._memos.popitem(last=False)
                self.evictions += 1
        else:
            self._memos.move_to_end(key)
        return m

    def peek(self, key: str) -> "PlanMemo | None":
        """Read-only lookup (no create, no LRU touch) — tools/tests."""
        with self._lock:
            return self._memos.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._memos)

    # ---- observation -----------------------------------------------------
    def observe(self, key: str, *, build_rows=None, join_strategy=None,
                demoted: bool = False, skip_ratio=None, label=None,
                gbps=None, groups=None, trim_keep=None, cohort=None,
                partials_hit=None, result_hit=None) -> None:
        """Fold one execution's measurements into the template's memo.
        Any subset of signals may be supplied; unknown templates create
        a memo. Never raises — a measurement must not fail a query."""
        if not key:
            return
        try:
            with self._lock:
                m = self._memo(key)
                self.observations += 1
                m.executions += 1
                if m.drift_cooldown > 0:
                    m.drift_cooldown -= 1
                drifted = False
                if build_rows:
                    for alias, n in build_rows.items():
                        e = m.build_rows.get(alias)
                        if e is None:
                            e = m.build_rows[alias] = _Ewma(self.alpha)
                        drifted |= e.add(n)
                if join_strategy:
                    m.strategies[join_strategy] = \
                        m.strategies.get(join_strategy, 0) + 1
                if demoted:
                    m.demotions += 1
                if skip_ratio is not None:
                    drifted |= m.skip_ratio.add(skip_ratio)
                if gbps is not None and label is not None:
                    base, rung = _split_label(label)
                    e = m.gbps.get((base, rung))
                    if e is None:
                        e = m.gbps[(base, rung)] = _Ewma(self.alpha)
                    e.add(gbps)
                if groups is not None:
                    g = int(groups)
                    drifted |= m.groups.add(g)
                    # decaying max: the trim safety bound follows the
                    # template's real group count down slowly, up fast
                    m.groups_hi = max(g, int(m.groups_hi * 0.9))
                    if trim_keep is not None and g > int(trim_keep):
                        # the advised keep was too tight: count the
                        # overflow and stand the advice down
                        m.trim_overflows += 1
                        m.groups.n = 0
                if cohort is not None:
                    m.cohort.add(cohort)
                if partials_hit is not None:
                    m.partials_hits[1] += 1
                    m.partials_hits[0] += bool(partials_hit)
                if result_hit is not None:
                    m.result_hits[1] += 1
                    m.result_hits[0] += bool(result_hit)
                if drifted:
                    m.drift_cooldown = self.min_samples
        except Exception:  # noqa: BLE001 — observation must never fail
            pass

    # ---- decisions -------------------------------------------------------
    def _decide(self, m: PlanMemo, overrode: bool) -> None:
        m.decisions += 1
        self.decisions += 1
        if overrode:
            m.overrides += 1
            self.overrides += 1

    def advise_join_strategy(self, key: str, default: str,
                             build_alias: str, threshold: int):
        """Measured build rows beat the static dim-table heuristic: a
        small measured build side broadcasts even off a fact table; a
        big one shuffles even off a dim table. Only flips between
        BROADCAST and SHUFFLE (DISTRIBUTED routing is the broker's call
        via measured_build_rows)."""
        if default not in ("BROADCAST", "SHUFFLE"):
            return default, None
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0:
                return default, None
            e = m.build_rows.get(build_alias)
            if e is None or not e.ready(self.min_samples):
                return default, None
            measured = int(e.mean)
            pick = "SHUFFLE" if measured > threshold else "BROADCAST"
            self._decide(m, pick != default)
            if pick == default:
                return default, None
            return pick, (f"ADVISOR(joinStrategy={pick}: "
                          f"measured={measured} default={default})")

    def measured_build_rows(self, key: str, build_alias: str):
        """Converged measured build-side row count, or None — the
        broker's distributed-demotion probe uses it in place of the
        registry doc-count estimate."""
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0:
                return None
            e = m.build_rows.get(build_alias)
            if e is None or not e.ready(self.min_samples):
                return None
            return int(e.mean)

    def advise_blockskip(self, key: str, default_frac: int):
        """(candidate fraction, note): 0 = run dense (the measured
        selectivity shows block skip prunes nothing), ``default_frac``
        when unconverged, a larger fraction (tighter static candidate
        bound → smaller gather) when the measured ratio leaves
        CAND_HEADROOM of room. Overflowing a tightened bound falls back
        to the dense branch in kernel (bit-exact), shows up here as a
        ratio-1.0 drift, and stands the advice down."""
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0 \
                    or not m.skip_ratio.ready(self.min_samples):
                return default_frac, None
            ratio = m.skip_ratio.mean
            if ratio >= DENSE_RATIO:
                # periodic re-probe: the ratio is only measurable on the
                # skip path, so an always-dense decision could never
                # un-stick after the table's shape changes
                m._probe_tick += 1
                if m._probe_tick % self.reprobe_every == 0:
                    return default_frac, None
                self._decide(m, True)
                return 0, (f"ADVISOR(blockSkip=dense: "
                           f"measured={ratio:.3f} default=1/{default_frac})")
            frac = default_frac
            for cand in (64, 32):
                if cand > default_frac and ratio * CAND_HEADROOM <= 1 / cand:
                    frac = cand
                    break
            self._decide(m, frac != default_frac)
            if frac == default_frac:
                return default_frac, None
            return frac, (f"ADVISOR(candBound=1/{frac}: "
                          f"measured={ratio:.3f} default=1/{default_frac})")

    def advise_pallas(self, key: str, default_mode: str, label: str):
        """Pallas-vs-XLA rung selection when BOTH rungs have measured
        GB/s for this template's pipeline label: demote to the XLA rung
        when it measured meaningfully faster (quarantine episodes and
        SET usePallas=false runs are where the XLA rung's numbers come
        from — the advisor never forces exploration)."""
        if default_mode == "off":
            return default_mode, None
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0:
                return default_mode, None
            base, _ = _split_label(label)
            ep = m.gbps.get((base, "pallas"))
            ex = m.gbps.get((base, "xla"))
            if ep is None or ex is None \
                    or not ep.ready(self.min_samples) \
                    or not ex.ready(self.min_samples):
                return default_mode, None
            if ex.mean > ep.mean * PALLAS_MARGIN:
                # periodic re-probe of the Pallas rung so a transiently
                # slow measurement can be revised
                m._probe_tick += 1
                if m._probe_tick % self.reprobe_every == 0:
                    return default_mode, None
                self._decide(m, True)
                return "off", (
                    f"ADVISOR(pallas=off: measured="
                    f"{ex.mean:.1f}GB/s>{ep.mean:.1f}GB/s "
                    f"default={default_mode})")
            self._decide(m, False)
            return default_mode, None

    def advise_trim(self, key: str, default_trim: int):
        """group_trim_size tightened toward the template's observed
        group count. NO-DROP rule: the tightened bound must still cover
        groups_hi (the decaying max) with TRIM_HEADROOM to spare, so no
        group the default bound would have kept is ever dropped — the
        only effect is a smaller device table + fetch buffer. An
        overflow observation (observe(groups=, trim_keep=)) resets the
        signal and the advice stands down to the default."""
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0 \
                    or not m.groups.ready(self.min_samples) \
                    or m.groups_hi <= 0:
                return default_trim, None
            tightened = _pow2_at_least(
                max(64, int(m.groups_hi * TRIM_HEADROOM) + 1))
            if tightened >= default_trim:
                self._decide(m, False)
                return default_trim, None
            self._decide(m, True)
            return tightened, (f"ADVISOR(groupTrim={tightened}: "
                               f"measured={m.groups_hi} "
                               f"default={default_trim})")

    def advise_cohort_window(self, key: str, default_s: float):
        """Cohort window sizing from observed arrival cohesion: a
        template whose cohorts stay solo shrinks its window (the wait
        buys nothing), one that reliably finds partners holds it open
        longer. Bounded to [0.5x, 2x] of the configured window."""
        with self._lock:
            m = self._memos.get(key)
            if m is None or m.drift_cooldown > 0 \
                    or not m.cohort.ready(self.min_samples):
                return default_s, None
            mean = m.cohort.mean
            if mean <= 1.25:
                w = default_s * 0.5
            elif mean >= 4.0:
                # cohorts fill fast — the full.wait exits early anyway;
                # keep the configured window
                self._decide(m, False)
                return default_s, None
            else:
                w = default_s * 2.0
            self._decide(m, True)
            return w, (f"ADVISOR(cohortWindow={w * 1e3:.1f}ms: "
                       f"measured={mean:.1f} default={default_s * 1e3:.1f}ms)")

    # ---- introspection ---------------------------------------------------
    def convergence(self, key: str) -> str:
        with self._lock:
            m = self._memos.get(key)
            return "cold" if m is None else m.convergence(self.min_samples)

    def snapshot(self) -> dict:
        """Advisor-wide stats + per-memo summaries (admin / tools)."""
        with self._lock:
            return {
                "memos": len(self._memos),
                "evictions": self.evictions,
                "observations": self.observations,
                "decisions": self.decisions,
                "overrides": self.overrides,
                "templates": {k: m.snapshot()
                              for k, m in self._memos.items()},
            }


def _split_label(label: str):
    """Roofline pipeline label → (base label, rung): the Pallas form of
    a pipeline carries "+pallas" (and possibly "+fused") suffixes; the
    base identifies the same logical pipeline across rungs so their
    measured GB/s compare like for like."""
    rung = "pallas" if "+pallas" in label else "xla"
    base = label.replace("+fused", "").replace("+pallas", "")
    return base, rung


def advisor_enabled(opts, default: bool = True) -> bool:
    """Per-query ``SET useAdvisor`` gate (common/options.py semantics:
    quoted 'false' opts out like bare FALSE)."""
    from pinot_tpu.common.options import bool_option

    v = bool_option(opts, "useadvisor", None)
    return default if v is None else bool(v)
