"""Device (JAX/XLA) query executor: the TPU hot path.

Replaces the reference's per-segment operator chains + combine thread pool
(§3.1 of SURVEY.md, BaseCombineOperator.java:79-145) with ONE jitted kernel
pipeline over the whole (S, L) segment batch:

    filter masks → (optional) global-id group keys → dense scatter aggregation

compiled once per *query template* (literals parameterized out — the explicit
form of InstancePlanMakerImplV2's per-shape plan dispatch) and cached. The
segment axis is the axis parallel/mesh.py shards over the device mesh; the
per-chip result is the same dense accumulator, combined with psum.

Group-by runs in global dictionary id space (engine/params.py), so the dense
(G,) accumulator directly replaces Pinot's ARRAY_BASED group-key regime
(DictionaryBasedGroupKeyGenerator.java:43-45) *and* its ConcurrentIndexedTable
merge: groups are already aligned across segments when the scatter lands.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pinot_tpu.engine import aggspec
from pinot_tpu.engine.params import (
    BatchContext,
    DeviceUnsupported,
    build_expr,
    build_filter,
    expr_bounds,
)
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.ops import agg as agg_ops
from pinot_tpu.ops import hll as hll_ops
from pinot_tpu.ops import masks as mask_ops
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import Expression, QueryContext
from pinot_tpu.storage.segment import Encoding

DEVICE_AGGS = {
    "count", "sum", "min", "max", "avg", "minmaxrange",
    "distinctcount", "distinctcountbitmap", "distinctcounthll",
    "segmentpartitioneddistinctcount",
}

MAX_DENSE_GROUPS = 1 << 22        # ARRAY_BASED regime guard (~4M groups)
MAX_PRESENCE_CELLS = 1 << 24      # distinctcount (G, C) presence guard


def segment_device_eligible(seg) -> bool:
    """Sealed, non-upsert-masked segments only: consuming (mutable) segments
    and segments with a validDocIds mask execute on the host scan path (the
    one place this rule lives — the engine partitions with it and the
    executor guards with it)."""
    return not getattr(seg, "is_mutable", False) and \
        getattr(seg, "valid_docs_mask", None) is None


# ---------------------------------------------------------------------------
# template evaluation (traced inside jit)
# ---------------------------------------------------------------------------


def _eval_expr(tpl, cols, params):
    kind = tpl[0]
    if kind == "lit":
        return params[tpl[1]]
    if kind == "raw":
        return cols[tpl[1]]
    if kind == "dictval":
        lut = params[f"vlut_{tpl[1]}"]  # (C,) global-id value table
        ids = jnp.clip(cols[tpl[1]], 0, lut.shape[0] - 1)
        return lut[ids]
    if kind == "cast":
        return get_function("cast").jnp_fn(_eval_expr(tpl[1], cols, params), tpl[2])
    fn = get_function(kind)
    args = [_eval_expr(a, cols, params) for a in tpl[1:]]
    return fn.jnp_fn(*args)


def _eval_filter(tpl, cols, params, shape):
    kind = tpl[0]
    if kind == "true":
        return jnp.ones(shape, dtype=bool)
    if kind == "false":
        return jnp.zeros(shape, dtype=bool)
    if kind == "and":
        m = _eval_filter(tpl[1], cols, params, shape)
        for c in tpl[2:]:
            m &= _eval_filter(c, cols, params, shape)
        return m
    if kind == "or":
        m = _eval_filter(tpl[1], cols, params, shape)
        for c in tpl[2:]:
            m |= _eval_filter(c, cols, params, shape)
        return m
    if kind == "not":
        return ~_eval_filter(tpl[1], cols, params, shape)
    if kind == "eq_dict":
        return mask_ops.eq_dict(cols[tpl[1]], params[tpl[2]])
    if kind == "in_dict":
        return mask_ops.in_dict(cols[tpl[1]], params[tpl[2]])
    if kind == "range_dict":
        return mask_ops.range_dict(cols[tpl[1]], params[tpl[2]], params[tpl[3]])
    if kind == "lut_dict":
        return mask_ops.lut_dict(cols[tpl[1]], params[tpl[2]])
    if kind == "eq_raw":
        return mask_ops.eq_raw(_eval_expr(tpl[1], cols, params), params[tpl[2]])
    if kind == "in_raw":
        return mask_ops.in_raw(_eval_expr(tpl[1], cols, params), params[tpl[2]])
    if kind == "range_raw":
        _, expr_tpl, klo, khi, has_lo, has_hi, lo_inc, hi_inc = tpl
        return mask_ops.range_raw(
            _eval_expr(expr_tpl, cols, params), params[klo], params[khi],
            lo_inc, hi_inc, has_lo, has_hi,
        )
    raise AssertionError(f"bad filter template node {kind}")


def _rows_per_block(values, int_rpb):
    """Two-stage sum block size at trace time: ints use the planner's
    metadata-derived bound (None → single-stage 64-bit scatter, exact but
    slow); floats always block at 2048 (f32 block partials, f64 reduce)."""
    if jnp.issubdtype(values.dtype, jnp.integer):
        return int_rpb if int_rpb else 1 << 62
    return 2048


def build_pipeline(template):
    """template (hashable) → jitted fn(cols, n_docs, params) → outputs dict."""
    shape, filter_tpl, group_cols, group_cards, aggs = template
    num_groups = 1
    for c in group_cards:
        num_groups *= c

    def pipeline(cols, n_docs, params):
        any_col = next(iter(cols.values()))
        sl = any_col.shape
        valid = mask_ops.valid_mask(n_docs, sl[1], batched=True)
        mask = _eval_filter(filter_tpl, cols, params, sl) & valid
        seg_matched = jnp.sum(mask, axis=1, dtype=jnp.int64)  # (S,) for stats
        outs = {"doc_count": jnp.sum(seg_matched), "seg_matched": seg_matched}

        if shape == "groupby":
            # columns are already global ids: the group key IS the column
            per_col = [cols[c] for c in group_cols]
            gid = agg_ops.group_ids_combine(per_col, group_cards, mask, num_groups)
            outs["gcount"] = agg_ops.group_count(gid, num_groups)
            for i, (name, argt, extra) in enumerate(aggs):
                k = f"a{i}"
                if name == "count":
                    pass  # gcount reused
                elif name in ("sum", "avg"):
                    v = _eval_expr(argt, cols, params)
                    rpb = _rows_per_block(v, extra)
                    outs[f"{k}_sum"] = agg_ops.group_sum(gid, v, num_groups, rpb)
                elif name == "min":
                    v = _eval_expr(argt, cols, params)
                    outs[f"{k}_min"] = agg_ops.group_min(gid, v, num_groups)
                elif name == "max":
                    v = _eval_expr(argt, cols, params)
                    outs[f"{k}_max"] = agg_ops.group_max(gid, v, num_groups)
                elif name == "minmaxrange":
                    v = _eval_expr(argt, cols, params)
                    outs[f"{k}_min"] = agg_ops.group_min(gid, v, num_groups)
                    outs[f"{k}_max"] = agg_ops.group_max(gid, v, num_groups)
                elif name == "distinctcount":
                    card = extra
                    sub = jnp.clip(cols[argt], 0, card - 1)
                    gid2 = jnp.where(mask, gid * card + sub, num_groups * card)
                    pres = jnp.zeros(num_groups * card + 1, dtype=jnp.int8)
                    pres = pres.at[gid2.reshape(-1)].max(1)
                    outs[f"{k}_pres"] = pres[: num_groups * card].reshape(num_groups, card)
                elif name == "distinctcounthll":
                    log2m = extra
                    m = 1 << log2m
                    hlut = params[f"hlut_{argt}"]  # (C,) per-global-id hashes
                    ids = jnp.clip(cols[argt], 0, hlut.shape[0] - 1)
                    h = hlut[ids]
                    idx, rho = hll_ops.hll_idx_rho(h, log2m)
                    slot = jnp.where(mask, gid * m + idx, num_groups * m)
                    regs = jnp.zeros(num_groups * m + 1, dtype=jnp.int32)
                    regs = regs.at[slot.reshape(-1)].max(rho.reshape(-1))
                    outs[f"{k}_regs"] = regs[: num_groups * m].reshape(num_groups, m)
            return outs

        # scalar aggregation shape
        for i, (name, argt, extra) in enumerate(aggs):
            k = f"a{i}"
            if name == "count":
                pass  # doc_count reused
            elif name in ("sum", "avg"):
                v = _eval_expr(argt, cols, params)
                outs[f"{k}_sum"] = agg_ops.agg_sum(v, mask)
            elif name == "min":
                outs[f"{k}_min"] = agg_ops.agg_min(_eval_expr(argt, cols, params), mask)
            elif name == "max":
                outs[f"{k}_max"] = agg_ops.agg_max(_eval_expr(argt, cols, params), mask)
            elif name == "minmaxrange":
                v = _eval_expr(argt, cols, params)
                outs[f"{k}_min"] = agg_ops.agg_min(v, mask)
                outs[f"{k}_max"] = agg_ops.agg_max(v, mask)
            elif name == "distinctcount":
                card = extra
                sub = jnp.clip(cols[argt], 0, card - 1)
                slot = jnp.where(mask, sub, card)
                outs[f"{k}_pres"] = agg_ops.distinct_presence(slot, card)
            elif name == "distinctcounthll":
                log2m = extra
                hlut = params[f"hlut_{argt}"]
                ids = jnp.clip(cols[argt], 0, hlut.shape[0] - 1)
                h = hlut[ids]
                outs[f"{k}_regs"] = hll_ops.hll_registers_prehashed(h, mask, log2m)
        return outs

    return pipeline  # caller jits (single-device) or shard_maps (mesh)


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


class DeviceExecutor:
    MAX_CACHED_BATCHES = 4  # LRU cap: a batch holds full columns in HBM

    def __init__(self, mesh=None):
        """``mesh``: optional jax Mesh — shard the segment axis over it with
        psum-combined accumulators (parallel/mesh.py) instead of a
        single-device batched launch."""
        self.mesh = mesh
        self._batches: dict = {}     # segment-set key -> BatchContext (LRU)
        self._pipelines: dict = {}   # template -> jitted/sharded fn

    # cheap static check (EXPLAIN backend display)
    def supports(self, q: QueryContext) -> bool:
        aggs = q.aggregations()
        if q.distinct or not aggs:
            return False
        return all(a.name in DEVICE_AGGS for a in aggs)

    def batch_for(self, segments) -> BatchContext:
        key = tuple(s.dir for s in segments)
        ctx = self._batches.pop(key, None)
        if ctx is None:
            ctx = BatchContext(segments)
            while len(self._batches) >= self.MAX_CACHED_BATCHES:
                # evict least-recently-used (insertion order == recency)
                self._batches.pop(next(iter(self._batches)))
        self._batches[key] = ctx
        return ctx

    def try_execute(self, q: QueryContext, segments):
        """list[IntermediateResult] (length 1) or None → host fallback."""
        try:
            return [self._execute(q, segments)]
        except DeviceUnsupported:
            return None

    # ---- template build --------------------------------------------------
    def _agg_template(self, a: Expression, ctx: BatchContext, params, counter):
        name = a.name
        if name in ("distinctcountbitmap", "segmentpartitioneddistinctcount"):
            name = "distinctcount"
        if name not in DEVICE_AGGS:
            raise DeviceUnsupported(f"aggregation {name} not on device")
        if name == "count":
            return ("count", None, None)
        if name == "distinctcount":
            arg = a.args[0]
            if not arg.is_identifier or ctx.encoding(arg.name) != Encoding.DICT:
                raise DeviceUnsupported("distinctcount needs a dict column")
            return ("distinctcount", arg.name, ctx.cardinality(arg.name))
        if name == "distinctcounthll":
            arg = a.args[0]
            if not arg.is_identifier or ctx.encoding(arg.name) != Encoding.DICT:
                raise DeviceUnsupported("distinctcounthll device path needs a dict column")
            spec = aggspec.make_spec(a)
            params[f"hlut_{arg.name}"] = ctx.hash_lut(arg.name)
            return ("distinctcounthll", arg.name, spec.log2m)
        # numeric-arg aggregations
        argt = build_expr(a.args[0], ctx, params, counter)
        self._register_vluts(argt, ctx, params)
        rpb = None
        if name in ("sum", "avg"):
            # metadata interval arithmetic sizes the two-stage int32 blocks
            bounds = expr_bounds(a.args[0], ctx)
            if bounds is not None:
                rpb = agg_ops.rows_per_block_for(max(abs(bounds[0]), abs(bounds[1])))
        return (name, argt, rpb)

    def _register_vluts(self, tpl, ctx: BatchContext, params):
        if not isinstance(tpl, tuple):
            return
        if tpl[0] == "dictval":
            params[f"vlut_{tpl[1]}"] = ctx.value_lut(tpl[1])
            return
        for t in tpl[1:]:
            self._register_vluts(t, ctx, params)

    def _execute(self, q: QueryContext, segments) -> IntermediateResult:
        aggs = q.aggregations()
        if q.distinct or not aggs:
            raise DeviceUnsupported("selection/distinct on host path")
        for a in aggs:
            if a.name not in DEVICE_AGGS:
                raise DeviceUnsupported(f"agg {a.name}")
        for s in segments:
            if not segment_device_eligible(s):
                raise DeviceUnsupported("mutable/upsert segment needs host scan path")

        ctx = self.batch_for(segments)
        params: dict = {}
        counter = [0]

        filter_tpl = ("true",) if q.filter is None else build_filter(
            q.filter, ctx, params, counter
        )
        self._register_filter_vluts(filter_tpl, ctx, params)

        group_cols, group_cards = (), ()
        if q.group_by:
            gcols = []
            gcards = []
            for g in q.group_by:
                if not g.is_identifier or ctx.encoding(g.name) != Encoding.DICT:
                    raise DeviceUnsupported("group-by must be dict columns on device")
                gcols.append(g.name)
                gcards.append(ctx.cardinality(g.name))
            group_cols, group_cards = tuple(gcols), tuple(gcards)
            total = 1
            for c in group_cards:
                total *= c
            if total > MAX_DENSE_GROUPS:
                raise DeviceUnsupported(f"dense group space too large ({total})")

        agg_tpls = tuple(self._agg_template(a, ctx, params, counter) for a in aggs)
        for name, argt, extra in agg_tpls:
            if group_cols and name in ("distinctcount", "distinctcounthll"):
                total = extra if name == "distinctcount" else (1 << extra)
                for c in group_cards:
                    total *= c
                if total > MAX_PRESENCE_CELLS:
                    raise DeviceUnsupported(f"{name} per-group state too large ({total})")

        shape = "groupby" if group_cols else "agg"
        template = (shape, filter_tpl, group_cols, group_cards, agg_tpls)

        pipeline = self._pipelines.get(template)
        if pipeline is None:
            raw = build_pipeline(template)
            if self.mesh is not None:
                from pinot_tpu.parallel.mesh import shard_pipeline

                pipeline = shard_pipeline(raw, self.mesh)
            else:
                pipeline = jax.jit(raw)
            self._pipelines[template] = pipeline

        needed = self._needed_columns(filter_tpl) | set(group_cols)
        for name, argt, extra in agg_tpls:
            if name in ("distinctcount", "distinctcounthll"):
                needed.add(argt)
            elif argt is not None:
                needed |= self._needed_columns(argt)
        cols = {c: ctx.column(c) for c in sorted(needed)}
        if not cols:  # COUNT(*) with no filter: still need one column for shape
            first = segments[0].column_names()[0]
            cols = {first: ctx.column(first)}

        n_docs = ctx.n_docs_dev
        if self.mesh is not None:
            from pinot_tpu.parallel.mesh import pad_to_multiple

            cols, n_docs, params, _ = pad_to_multiple(
                cols, n_docs, params, self.mesh.devices.size
            )

        # single batched host transfer: per-leaf np.asarray costs one tunnel
        # round-trip each, device_get overlaps them (measured 4-5x)
        outs = jax.device_get(pipeline(cols, n_docs, params))
        outs = {k: np.asarray(v) for k, v in outs.items()}
        return self._to_intermediate(q, ctx, template, outs, aggs)

    def _register_filter_vluts(self, tpl, ctx, params):
        if not isinstance(tpl, tuple):
            return
        if tpl[0] in ("eq_raw", "in_raw", "range_raw"):
            self._register_vluts(tpl[1], ctx, params)
        else:
            for t in tpl[1:]:
                self._register_filter_vluts(t, ctx, params)

    @staticmethod
    def _needed_columns(tpl) -> set:
        out = set()

        def walk(t):
            if not isinstance(t, tuple):
                return
            if t[0] in ("raw", "dictval"):
                out.add(t[1])
                return
            if t[0] in ("eq_dict", "in_dict", "range_dict", "lut_dict"):
                out.add(t[1])
            for x in t[1:]:
                walk(x)

        walk(tpl)
        return out

    # ---- device outputs → canonical IntermediateResult -------------------
    def _to_intermediate(self, q, ctx: BatchContext, template, outs, aggs):
        shape, _, group_cols, group_cards, agg_tpls = template
        doc_count = int(outs["doc_count"])
        # mirror the host executor's stats accounting so responses are
        # backend-independent (host.py execute_segment)
        entries_in_filter = 0
        if q.filter is not None:
            entries_in_filter = int(ctx.n_docs.sum()) * len(q.filter.columns())
        entries_post = sum(
            doc_count * len(aggspec.make_spec(a).args) for a in q.aggregations()
        )
        stats = ExecutionStats(
            num_docs_scanned=doc_count,
            num_entries_scanned_in_filter=entries_in_filter,
            num_entries_scanned_post_filter=entries_post,
            num_segments_processed=ctx.S,
            num_segments_queried=ctx.S,
            num_segments_matched=int((outs["seg_matched"] > 0).sum()),
            total_docs=int(ctx.n_docs.sum()),
        )

        if shape == "agg":
            partials = [
                self._scalar_partial(i, t, outs, ctx) for i, t in enumerate(agg_tpls)
            ]
            return IntermediateResult("aggregation", agg_partials=partials, stats=stats)

        gcount = outs["gcount"]
        present = np.nonzero(gcount > 0)[0]
        # decode dense gid → per-column global ids → values
        keys = []
        rem = present.copy()
        for card in reversed(group_cards[1:]):
            keys.append(rem % card)
            rem = rem // card
        keys.append(rem)
        keys.reverse()
        key_values = tuple(
            ctx.global_dict(col).take(k) for col, k in zip(group_cols, keys)
        )
        partials = [
            self._group_partial(i, t, outs, ctx, present) for i, t in enumerate(agg_tpls)
        ]
        return IntermediateResult(
            "group_by", group_keys=key_values, agg_partials=partials, stats=stats
        )

    def _scalar_partial(self, i, tpl, outs, ctx):
        name, argt, extra = tpl
        k = f"a{i}"
        if name == "count":
            return {"count": np.array([outs["doc_count"]], dtype=np.int64)}
        if name == "sum":
            return {"sum": np.asarray([outs[f"{k}_sum"]], dtype=np.float64)}
        if name == "avg":
            return {
                "sum": np.asarray([outs[f"{k}_sum"]], dtype=np.float64),
                "count": np.array([outs["doc_count"]], dtype=np.int64),
            }
        if name == "min":
            return {"min": np.asarray([outs[f"{k}_min"]], dtype=np.float64)}
        if name == "max":
            return {"max": np.asarray([outs[f"{k}_max"]], dtype=np.float64)}
        if name == "minmaxrange":
            return {
                "min": np.asarray([outs[f"{k}_min"]], dtype=np.float64),
                "max": np.asarray([outs[f"{k}_max"]], dtype=np.float64),
            }
        if name == "distinctcount":
            pres = outs[f"{k}_pres"]
            vals = ctx.global_dict(argt).take(np.nonzero(pres > 0)[0])
            s = np.empty(1, dtype=object)
            s[0] = set(np.asarray(vals).tolist())
            return {"sets": s}
        if name == "distinctcounthll":
            return {"regs": outs[f"{k}_regs"].reshape(1, -1)}
        raise AssertionError(name)

    def _group_partial(self, i, tpl, outs, ctx, present):
        name, argt, extra = tpl
        k = f"a{i}"
        if name == "count":
            return {"count": outs["gcount"][present].astype(np.int64)}
        if name == "sum":
            return {"sum": outs[f"{k}_sum"][present].astype(np.float64)}
        if name == "avg":
            return {
                "sum": outs[f"{k}_sum"][present].astype(np.float64),
                "count": outs["gcount"][present].astype(np.int64),
            }
        if name == "min":
            return {"min": outs[f"{k}_min"][present].astype(np.float64)}
        if name == "max":
            return {"max": outs[f"{k}_max"][present].astype(np.float64)}
        if name == "minmaxrange":
            return {
                "min": outs[f"{k}_min"][present].astype(np.float64),
                "max": outs[f"{k}_max"][present].astype(np.float64),
            }
        if name == "distinctcount":
            pres = outs[f"{k}_pres"][present]
            gvals = np.asarray(ctx.global_dict(argt).values)
            sets = np.empty(len(present), dtype=object)
            for j in range(len(present)):
                sets[j] = set(gvals[np.nonzero(pres[j] > 0)[0]].tolist())
            return {"sets": sets}
        if name == "distinctcounthll":
            return {"regs": outs[f"{k}_regs"][present]}
        raise AssertionError(name)
