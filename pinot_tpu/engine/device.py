"""Device (JAX/XLA) query executor: the TPU hot path.

Replaces the reference's per-segment operator chains + combine thread pool
(§3.1 of SURVEY.md, BaseCombineOperator.java:79-145) with ONE jitted kernel
pipeline over the whole (S, L) segment batch:

    filter masks → (optional) global-id group keys → dense scatter aggregation

compiled once per *query template* (literals parameterized out — the explicit
form of InstancePlanMakerImplV2's per-shape plan dispatch) and cached. The
segment axis is the axis parallel/mesh.py shards over the device mesh; the
per-chip result is the same dense accumulator, combined with psum.

Group-by runs in global dictionary id space (engine/params.py), so the dense
(G,) accumulator directly replaces Pinot's ARRAY_BASED group-key regime
(DictionaryBasedGroupKeyGenerator.java:43-45) *and* its ConcurrentIndexedTable
merge: groups are already aligned across segments when the scatter lands.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from pinot_tpu.common import faults
from pinot_tpu.common.metrics import get_metrics
from pinot_tpu.common.options import bool_option
from pinot_tpu.common.trace import span as trace_span
from pinot_tpu.engine import aggspec
from pinot_tpu.engine.advisor import PlanAdvisor, advisor_enabled
from pinot_tpu.engine.inflight import InflightLaunch, LaunchCoalescer
from pinot_tpu.engine.params import (
    BatchContext,
    DeviceUnsupported,
    build_expr,
    build_filter,
    expr_bounds,
)
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.ops import agg as agg_ops
from pinot_tpu.ops import blockskip as bs_ops
from pinot_tpu.ops import device_reduce as dr_ops
from pinot_tpu.ops import hll as hll_ops
from pinot_tpu.ops import masks as mask_ops
from pinot_tpu.ops import radix_groupby as radix_ops
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import Expression, QueryContext
from pinot_tpu.storage.segment import Encoding

DEVICE_AGGS = {
    "count", "sum", "min", "max", "avg", "minmaxrange",
    "distinctcount", "distinctcountbitmap", "distinctcounthll",
    "segmentpartitioneddistinctcount",
    "hllmerge",  # star-tree sketch-state re-merge (engine/startree_exec.py)
    "firstwithtime", "lastwithtime",  # argmax-by-time combine family
}

MAX_DENSE_GROUPS = 1 << 22        # ARRAY_BASED regime guard (~4M groups)
MAX_PRESENCE_CELLS = 1 << 24      # distinctcount (G, C) presence guard
# sort-based high-cardinality regime (MAP_BASED analog): hard ceiling on
# the per-launch group table (the effective cap is
# min(num_groups_limit, this)); overflow falls back to the host path
MAX_SORTED_GROUPS = 1 << 17
SORTED_AGGS = ("count", "sum", "avg", "min", "max", "minmaxrange")

log = logging.getLogger("pinot_tpu.engine.device")

# device-runtime failure detection (launch/fetch recovery): jaxlib raises
# XlaRuntimeError for device-side faults (RESOURCE_EXHAUSTED / INTERNAL /
# device OOM); exact types vary across jax versions, so match by type
# name across the MRO, plus the fault harness's simulated form
_DEVICE_ERROR_NAMES = frozenset(
    ("XlaRuntimeError", "InternalError", "ResourceExhausted",
     "ResourceExhaustedError"))


def _is_device_runtime_error(e) -> bool:
    """True for failures of the DEVICE runtime (recoverable by evict +
    retry + host fallback) as opposed to template-build/user errors."""
    if isinstance(e, faults.InjectedDeviceError):
        return True
    if any(t.__name__ in _DEVICE_ERROR_NAMES for t in type(e).__mro__):
        return True
    return isinstance(e, RuntimeError) and "RESOURCE_EXHAUSTED" in str(e)


def segment_device_eligible(seg) -> bool:
    """Sealed, non-upsert-masked segments only: consuming (mutable) segments
    and segments with a validDocIds mask execute on the host scan path (the
    one place this rule lives — the engine partitions with it and the
    executor guards with it). Consuming segments re-enter through their
    CHUNKLETS (realtime/chunklet.py): the sealed frozen-prefix blocks pass
    this check (immutable, mask None while clean) and join the batch LRU +
    in-flight refcounting like any sealed segment — an upsert invalidation
    inside a block flips its mask non-None, failing this check back to the
    host path.

    Tiering (ISSUE 12, server/tiering.py): segments demoted below the
    hot tier route to the host too — warm segments scan their lazily
    mmap'd planes without ever occupying HBM, and cold placeholders are
    split out by the engine before this check matters. Segments without
    a tier attribute (every pre-tiering caller) are hot."""
    return not getattr(seg, "is_mutable", False) and \
        getattr(seg, "valid_docs_mask", None) is None and \
        (getattr(seg, "tier", None) or "hot") == "hot"


# ---------------------------------------------------------------------------
# template evaluation (traced inside jit)
# ---------------------------------------------------------------------------


def _col_width(widths, key):
    """Width-plan tuple (dtype, bits, has_offset, wide) for a cols key, or
    None (legacy wide plane / keys the planner doesn't narrow)."""
    return widths.get(key) if widths else None


def _ids_col(cols, key, widths):
    """Dict-id plane at LOGICAL width: sub-byte plans unpack in-register
    (ops/masks.py unpack_subbyte); byte-aligned narrow ids pass through —
    predicates/group arithmetic consume them at native width."""
    v = cols[key]
    w = _col_width(widths, key)
    if w is not None and w[1]:
        return mask_ops.unpack_subbyte(v, w[1])
    return v


def _data_col(cols, params, key, widths):
    """Raw / decoded (dv::) value plane DECODED to its plan's wide dtype:
    frame-of-reference planes add the per-batch "fo::<key>" offset param.
    Both the cast and the add are register-level (XLA fuses them into the
    consumer); the HBM read stays at the stored width. Decoding always
    widens — two narrow planes multiplied in an expression must not wrap
    at the storage width."""
    v = cols[key]
    w = _col_width(widths, key)
    if w is None or not w[3]:
        return v
    v = v.astype(jnp.dtype(w[3]))
    if w[2]:
        fo = params.get("fo::" + key)
        if fo is not None:
            v = v + fo
    return v


def _eval_expr(tpl, cols, params, widths=None):
    kind = tpl[0]
    if kind == "lit":
        return params[tpl[1]]
    if kind == "raw":
        return _data_col(cols, params, tpl[1], widths)
    if kind == "dictval":
        # decoded on the host at upload (BatchContext.decoded_column) — a
        # device (C,)-LUT gather here costs ~80ms/query at 12M docs on v5e
        return _data_col(cols, params, "dv::" + tpl[1], widths)
    if kind == "cast":
        return get_function("cast").jnp_fn(
            _eval_expr(tpl[1], cols, params, widths), tpl[2])
    fn = get_function(kind)
    args = [_eval_expr(a, cols, params, widths) for a in tpl[1:]]
    return fn.jnp_fn(*args)


def _eval_filter(tpl, cols, params, shape, widths=None):
    kind = tpl[0]
    if kind == "true":
        return jnp.ones(shape, dtype=bool)
    if kind == "false":
        return jnp.zeros(shape, dtype=bool)
    if kind == "and":
        m = _eval_filter(tpl[1], cols, params, shape, widths)
        for c in tpl[2:]:
            m &= _eval_filter(c, cols, params, shape, widths)
        return m
    if kind == "or":
        m = _eval_filter(tpl[1], cols, params, shape, widths)
        for c in tpl[2:]:
            m |= _eval_filter(c, cols, params, shape, widths)
        return m
    if kind == "not":
        return ~_eval_filter(tpl[1], cols, params, shape, widths)
    if kind == "mv_any":
        # per-entry mask over the (S, L, K) id block, -1 padding masked out,
        # reduced match-any over K (ForwardIndexReader.getDictIdMV semantics)
        ids = cols[tpl[1]]
        m = _eval_filter(tpl[2], cols, params, ids.shape, widths)
        return jnp.any(m & (ids >= 0), axis=-1)
    if kind == "eq_dict":
        return mask_ops.eq_dict(_ids_col(cols, tpl[1], widths), params[tpl[2]])
    if kind == "in_dict":
        return mask_ops.in_dict(_ids_col(cols, tpl[1], widths), params[tpl[2]])
    if kind == "range_dict":
        return mask_ops.range_dict(
            _ids_col(cols, tpl[1], widths), params[tpl[2]], params[tpl[3]])
    if kind == "lut_dict":
        return mask_ops.lut_dict(_ids_col(cols, tpl[1], widths), params[tpl[2]])
    if kind == "eq_raw":
        return mask_ops.eq_raw(
            _eval_expr(tpl[1], cols, params, widths), params[tpl[2]])
    if kind == "in_raw":
        return mask_ops.in_raw(
            _eval_expr(tpl[1], cols, params, widths), params[tpl[2]])
    if kind == "range_raw":
        _, expr_tpl, klo, khi, has_lo, has_hi, lo_inc, hi_inc = tpl
        return mask_ops.range_raw(
            _eval_expr(expr_tpl, cols, params, widths), params[klo],
            params[khi], lo_inc, hi_inc, has_lo, has_hi,
        )
    raise AssertionError(f"bad filter template node {kind}")


def _rows_per_block(values, int_rpb):
    """Two-stage sum block size at trace time: ints use the planner's
    metadata-derived bound (None → single-stage 64-bit scatter, exact but
    slow); floats always block at 2048 (f32 block partials, f64 reduce)."""
    if jnp.issubdtype(values.dtype, jnp.integer):
        return int_rpb if int_rpb else 1 << 62
    return 2048


def _legacy_rpb(extra):
    """Agg-template ``extra`` is (nplanes, rpb) since the matmul kernel;
    accept the bare legacy rpb int/None (older templates, __graft_entry__)."""
    return extra[1] if isinstance(extra, tuple) else extra


def _hll_regs(slot, rho, num_groups, log2m, mm_mode, pallas_mode="off"):
    """(num_groups, m) HLL registers: the Pallas register-max scatter
    (ops/pallas_scatter.py — partitioned presence channels, ISSUE 15)
    when the slot space is in its regime, else the matmul threshold-
    channel build when VMEM allows, else the scatter-max (all exact
    max-of-rho, bit-identical). Returned as int8 (rho <= 33 - log2m <
    127): the register matrix rides the device->host tunnel 4x smaller
    — ~450ms saved per 2000-group query."""
    from pinot_tpu.ops import groupby_mm as mm

    m = 1 << log2m
    n_total = 1
    for d in slot.shape:
        n_total *= d
    if pallas_mode != "off":
        from pinot_tpu.ops import pallas_scatter as ps

        nrho = mm.hll_nrho(log2m)
        if ps.hll_supported(num_groups * m, nrho) and (
                pallas_mode == "interpret"
                or n_total >= ps.PALLAS_MIN_ROWS):
            regs = ps.hll_register_max(
                slot, rho, num_groups * m, nrho,
                interpret=(pallas_mode == "interpret"))
            return regs.reshape(num_groups, m).astype(jnp.int8)
    use_mm = (
        mm_mode != "off"
        and mm.hll_supported(num_groups, log2m)
        and (mm_mode == "interpret" or n_total >= mm.MM_MIN_ROWS)
    )
    if use_mm:
        regs = mm.hll_registers(
            slot.reshape(-1), rho.reshape(-1), num_groups, log2m,
            interpret=(mm_mode == "interpret"),
        )
        return regs.astype(jnp.int8)
    # f32 scatter-max: ~16% faster than int32 on v5e at 100M rows (951 vs
    # 1136 ms) and exact for rho <= 23 < 2^24
    regs = jnp.zeros(num_groups * m + 1, dtype=jnp.float32)
    regs = regs.at[slot.reshape(-1)].max(rho.reshape(-1).astype(jnp.float32))
    return regs[: num_groups * m].reshape(num_groups, m).astype(jnp.int8)


def _try_mm_groupby(aggs, gid, cols, params, num_groups, mm_mode, outs,
                    widths=None, pallas_mode="off"):
    """Route COUNT/SUM/AVG through ONE factored one-hot launch when
    eligible: the Pallas tiled local-accumulate scatter
    (ops/pallas_scatter.py plane_group_sums — group-range partitioned,
    so its coverage extends past the single-VMEM-accumulator ceiling)
    when the pallas tier is on, else the single-accumulator matmul
    kernel (ops/groupby_mm.py). Fills outs["gcount"] +
    outs[f"a{i}_sum"] and returns the set of agg indexes handled;
    scatter code covers the rest. All decisions are trace-time static."""
    from pinot_tpu.ops import groupby_mm as mm
    from pinot_tpu.ops import pallas_scatter as ps

    if mm_mode == "off" and pallas_mode == "off":
        return set()
    n_total = 1
    for d in gid.shape:
        n_total *= d

    # plan: which aggs become channels, and how many
    plans = []  # (i, kind, nplanes, values)
    total_ch = 1  # ones channel
    for i, (name, argt, extra) in enumerate(aggs):
        if name not in ("sum", "avg") or not isinstance(extra, tuple):
            continue
        nplanes_int = extra[0]
        v = _eval_expr(argt, cols, params, widths)
        if jnp.issubdtype(v.dtype, jnp.integer):
            if nplanes_int is None:  # unknown range → exact scatter instead
                continue
            kind, nplanes = "int", nplanes_int
        else:
            kind, nplanes = "float", 3
        if total_ch + nplanes > mm.MAX_CHANNELS + 1:
            continue
        plans.append((i, kind, nplanes, v))
        total_ch += nplanes
    use_pallas = (
        pallas_mode != "off"
        and ps.sums_supported(num_groups, total_ch)
        and (pallas_mode == "interpret" or n_total >= ps.PALLAS_MIN_ROWS)
    )
    use_mm = (
        not use_pallas
        and mm_mode != "off"
        and mm.mm_supported(num_groups, total_ch - 1)
        and (mm_mode == "interpret" or n_total >= mm.MM_MIN_ROWS)
    )
    if not use_pallas and not use_mm:
        return set()
    has_count_or_avg = any(a[0] in ("count", "avg") for a in aggs)
    if not plans and not has_count_or_avg:
        return set()

    channels = [jnp.ones(n_total, dtype=jnp.bfloat16)]
    specs = []  # (i, kind, slice into channel rows, offset param key)
    row = 1
    for i, kind, nplanes, v in plans:
        flat = v.reshape(-1)
        if kind == "int":
            off = params[f"off{i}"]
            channels.extend(mm.int_planes(flat, off, nplanes))
        else:
            channels.extend(mm.float_planes(flat))
        specs.append((i, kind, slice(row, row + nplanes)))
        row += nplanes

    if use_pallas:
        sums = ps.plane_group_sums(
            gid.reshape(-1), jnp.stack(channels), num_groups,
            interpret=(pallas_mode == "interpret"),
            first_channel_ones=True,
        )
    else:
        sums = mm.group_sums(
            gid.reshape(-1), jnp.stack(channels), num_groups,
            interpret=(mm_mode == "interpret"), first_channel_ones=True,
        )
    gcount = jnp.round(sums[0]).astype(jnp.int64)
    outs["gcount"] = gcount
    done = set()
    for i, kind, sl in specs:
        planes = [sums[j] for j in range(sl.start, sl.stop)]
        if kind == "int":
            outs[f"a{i}_sum"] = mm.recombine_int(planes, gcount, params[f"off{i}"])
        else:
            outs[f"a{i}_sum"] = mm.recombine_float(planes)
        done.add(i)
    return done


def _resolve_mm_mode(mm_mode: str) -> str:
    if mm_mode == "auto":
        return "tpu" if jax.default_backend() == "tpu" else "off"
    return mm_mode


def _template_uses_pallas(template, widths, fused: bool,
                          pallas_mode: str = "interpret",
                          n_total: int | None = None) -> bool:
    """Static: does this template route at least one op to the Pallas
    tier?  Gates the roofline label's "+pallas" suffix AND the failure
    attribution of launch()'s fallback ladder — a pipeline that compiles
    ZERO Pallas kernels (the sorted radix regime, plain scalar
    aggregations, out-of-regime group counts, sub-PALLAS_MIN_ROWS
    batches on TPU) must not be attributed to the tier, or
    roofline/EXPLAIN ANALYZE rows silently change between tier-on and
    tier-off rounds and a device failure burns a Pallas-rung drop on a
    byte-identical recompile. Mirrors the trace-time routing
    conservatively: dtypes of computed expressions are unknowable here
    and count as routed. ``n_total``: batch rows (S * L) — the same
    minimum-rows gate every routing site applies outside interpret
    mode (None = unknown, treated as large)."""
    from pinot_tpu.ops import groupby_mm as mmod
    from pinot_tpu.ops import pallas_scatter as ps

    if fused:
        return True  # the fused kernel has no minimum-rows gate
    if pallas_mode != "interpret" and n_total is not None \
            and n_total < ps.PALLAS_MIN_ROWS:
        return False
    shape, _ft, _gc, group_cards, agg_tpls, _sk, _final = template

    def _arg_dtype(argt):
        ck = ps._direct_colkey(argt)
        w = (widths or {}).get(ck) if ck else None
        if w is None:
            return None
        return np.dtype(w[3]) if w[3] else np.dtype(w[0])

    if shape == "agg":
        # scalar shape: only the HLL register-max routes (scalar
        # min/max/sum are dense reductions, never scatters)
        return any(
            name == "distinctcounthll"
            and ps.hll_supported(1 << extra, mmod.hll_nrho(extra))
            for name, _a, extra in agg_tpls)
    if shape != "groupby":
        return False  # the sorted radix regime never consults the tier
    num_groups = 1
    for c in group_cards:
        num_groups *= c
    for name, argt, extra in agg_tpls:
        if name in ("count", "sum", "avg"):
            if ps.sums_supported(num_groups, 2):
                return True
        elif name in ("min", "max", "minmaxrange"):
            dt = _arg_dtype(argt) or np.dtype(np.int32)
            if ps.minmax_supported(num_groups, dt):
                return True
        elif name == "distinctcounthll":
            if ps.hll_supported(num_groups * (1 << extra),
                                mmod.hll_nrho(extra)):
                return True
    return False


def _group_extreme(gid, v, num_groups: int, ops: tuple, pallas_mode: str):
    """Per-group min/max: the Pallas masked-select scatter
    (ops/pallas_scatter.py group_minmax — the aggregation family with no
    MXU identity) when the value dtype and group count are in its
    regime, else the XLA scatter. Empty-group fills come from the
    ORIGINAL value dtype's extremes on both paths, so results are
    bit-identical."""
    from pinot_tpu.ops import pallas_scatter as ps

    n_total = 1
    for d in v.shape:
        n_total *= d
    if (pallas_mode != "off" and ps.minmax_supported(num_groups, v.dtype)
            and (pallas_mode == "interpret"
                 or n_total >= ps.PALLAS_MIN_ROWS)):
        if jnp.issubdtype(v.dtype, jnp.integer):
            info = jnp.iinfo(v.dtype)
            fills = tuple(info.max if op == "min" else info.min
                          for op in ops)
        else:
            fills = tuple(agg_ops.POS_INF if op == "min" else
                          agg_ops.NEG_INF for op in ops)
        res = ps.group_minmax(gid, v, num_groups, ops,
                              interpret=(pallas_mode == "interpret"),
                              fills=fills)
        return tuple(r.astype(v.dtype) for r in res)
    return tuple(
        agg_ops.group_min(gid, v, num_groups) if op == "min"
        else agg_ops.group_max(gid, v, num_groups) for op in ops)


def _finalize_sketch_outs(outs, agg_tpls):
    """TERMINAL-query device finalize (traced, applied AFTER the mesh
    combine so multi-shard presence/register merges stay max-semantics):
    HLL registers → int64 estimates, distinct presence → int64 popcounts.
    Only answer-sized arrays cross the host link instead of G×m mergeable
    state — on the bench tunnel (~5MB/s) a 2000-group log2m=11 register
    plane is 4MB ≈ 1s of transfer for 16KB of answers."""
    outs = dict(outs)
    for i, (name, _argt, _extra) in enumerate(agg_tpls):
        k = f"a{i}"
        if name == "distinctcount" and f"{k}_pres" in outs:
            pres = outs.pop(f"{k}_pres")
            outs[f"{k}_cnt"] = jnp.sum(pres, axis=-1, dtype=jnp.int64)
        elif name == "distinctcounthll" and f"{k}_hs" in outs:
            # sorted register-free build (_hll_sorted_sums): scaled sums →
            # estimates, bit-identical to the dense-register math
            sums = outs.pop(f"{k}_hs")
            outs[f"{k}_est"] = hll_ops.estimate_from_sums_jnp(sums, _extra)
        elif name in ("distinctcounthll", "hllmerge") and f"{k}_regs" in outs:
            regs = outs.pop(f"{k}_regs")
            if regs.ndim == 1:
                outs[f"{k}_est"] = hll_ops.estimate_jnp(regs[None, :])[0]
            else:
                outs[f"{k}_est"] = hll_ops.estimate_jnp(regs)
    return outs


def _hll_sums_from_sorted(sk, num_groups, log2m, mm_mode):
    """(3, G) scaled register sums from an already-SORTED packed key array
    (slot << 5 | rho): each slot's run ends at its MAX rho; three bf16
    power-of-two channels over the boundary rows ride ONE group_sums
    matmul (see estimate_from_sums_jnp for the exactness argument)."""
    from pinot_tpu.ops import groupby_mm as mm

    m = 1 << log2m
    rho_max = 33 - log2m
    split = rho_max // 2
    slot_s = sk >> 5
    is_end = jnp.concatenate(
        [slot_s[1:] != slot_s[:-1], jnp.ones(1, dtype=bool)])
    valid = slot_s < num_groups * m  # masked rows pack the overflow slot
    e = is_end & valid
    rho_s = (sk & 31).astype(jnp.float32)
    gid_s = jnp.where(valid, slot_s >> log2m, num_groups).astype(jnp.int32)
    zero = jnp.float32(0)
    ch1 = jnp.where(e, jnp.float32(1), zero).astype(jnp.bfloat16)
    ch2 = jnp.where(e & (rho_s <= split),
                    jnp.exp2(jnp.float32(split) - rho_s),
                    zero).astype(jnp.bfloat16)
    ch3 = jnp.where(e & (rho_s > split),
                    jnp.exp2(jnp.float32(rho_max) - rho_s),
                    zero).astype(jnp.bfloat16)
    return mm.group_sums(gid_s, jnp.stack([ch1, ch2, ch3]), num_groups,
                         interpret=(mm_mode == "interpret"))


def _hll_sorted_sums(slot, rho, num_groups, log2m, mm_mode):
    """TERMINAL-only register-free HLL build for group counts too large
    for the matmul register kernel: chunk-local sorts of packed
    (slot << 5 | rho) int32 keys dedupe (register, rank) pairs down to
    per-slot maxima (ops/radix_groupby.py hll_chunked_sorted_keys — the
    radix-partitioned replacement for the old monolithic lax.sort, which
    ran HBM-bound at ~1.6 GB/s over the full row-scale key array), then
    _hll_sums_from_sorted reduces the surviving keys to per-GROUP scaled
    sums that recombine to the exact Σ 2^-reg (ops/hll.py
    estimate_from_sums_jnp). NOT mergeable across shards/servers (same
    slot on two shards would double-count), hence terminal-only; the
    scatter path remains the mergeable form. FILTERLESS queries skip the
    sort entirely via the batch's cached sorted projection
    (params.BatchContext.sorted_hll_keys)."""
    key = (slot.reshape(-1).astype(jnp.int32) << 5) \
        | rho.reshape(-1).astype(jnp.int32)
    sk = radix_ops.hll_chunked_sorted_keys(key, num_groups * (1 << log2m))
    return _hll_sums_from_sorted(sk, num_groups, log2m, mm_mode)


def _hll_sort_eligible(final, sorted_hll_ok, num_groups, log2m, mm_mode):
    """Shared gate for the sorted terminal HLL paths (build_pipeline AND
    the executor's needed-columns resolution must agree)."""
    from pinot_tpu.ops import groupby_mm as mm

    m = 1 << log2m
    return (final and sorted_hll_ok and mm_mode != "off"
            and not mm.hll_supported(num_groups, log2m)
            and num_groups * m < (1 << 26)
            and mm.mm_supported(num_groups, 3))


def _with_time_partial(name: str, outs: dict, k: str, present):
    """Device (time, value) outputs → the canonical {"val","time"} partial
    of FirstLastWithTimeSpec; empty groups keep the time sentinel and a
    NaN value (the device's -inf fill is a kernel artifact, not a value)."""
    first = name == "firstwithtime"
    suff = "tmin" if first else "tmax"
    t = np.asarray(outs[f"{k}_{suff}"]).reshape(-1)
    v = np.asarray(outs[f"{k}_v{suff}"], dtype=np.float64).reshape(-1)
    if present is not None:
        t, v = t[present], v[present]
    t = t.astype(np.int64)
    sentinel = np.iinfo(np.int64).max if first else np.iinfo(np.int64).min
    # -inf is the kernel's "no non-NaN winner" encoding (all-NaN winner
    # rows), kept as -inf through the mesh pmax so it stays associative;
    # it becomes NaN only here at the canonical boundary
    return {"val": np.where((t == sentinel) | np.isneginf(v), np.nan, v),
            "time": t}


def amortized_launch_time(timed, base_iters: int = 8,
                          target_s: float = 0.6, max_iters: int = 256) -> float:
    """Per-launch device seconds from a ``timed(k)`` closure (k launches +
    one token fetch). The link's RTT jitter (±10ms on the bench tunnel)
    contaminates a fixed-iteration estimate for SHORT kernels, so the
    iteration count adapts until the amortized span dwarfs the jitter."""
    import time as _time  # noqa: F401 — callers' closures time themselves

    timed(1)  # warm (compile cache hit; steady-state dispatch)
    t1 = min(timed(1) for _ in range(3))
    tn = timed(base_iters)
    per = max(1e-6, (tn - t1) / (base_iters - 1))
    if (base_iters - 1) * per < target_s:
        iters = int(min(max_iters, max(base_iters, round(target_s / per))))
        if iters > base_iters:
            tn = timed(iters)
            per = max(0.0, (tn - t1) / (iters - 1))
    return per


def _is_f64(dt) -> bool:
    return np.dtype(dt) == np.float64


def _pack_outs(outs):
    """Flatten the output leaves into at most TWO arrays: a uint8 buffer
    (bitcast + concat) and a float64 buffer (concat only).

    The result crosses the host link as few arrays as possible:
    jax.device_get fetches tree leaves serially, and on a high-latency
    link (the bench tunnel RTT is ~100ms) each extra leaf is an extra
    round trip — a 3-leaf scalar aggregation paid 3x the floor. float64
    rides its own buffer because the TPU AOT x64 rewriter has no
    bitcast-convert lowering for f64 (i64 works). Bitcast leaves are
    ordered by descending itemsize so every offset stays naturally
    aligned for zero-copy np views on the host side."""
    names = sorted(outs, key=lambda n: (-jnp.dtype(outs[n].dtype).itemsize, n))
    bleaves, fleaves = [], []
    for n in names:
        x = outs[n]
        if _is_f64(x.dtype):
            fleaves.append(x.reshape(-1))
            continue
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.uint8)
        bleaves.append(jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(-1))
    packed = {}
    if bleaves:
        packed["b"] = jnp.concatenate(bleaves) if len(bleaves) > 1 else bleaves[0]
    if fleaves:
        packed["f"] = jnp.concatenate(fleaves) if len(fleaves) > 1 else fleaves[0]
    return packed


def _out_layout(out_shapes) -> list:
    """[(name, np_dtype, shape, buffer_key, offset_elems_or_bytes, nbytes)]
    matching _pack_outs order, from a jax.eval_shape result (no device
    work). Offsets are bytes in the "b" buffer, elements in "f"."""
    items = sorted(
        out_shapes.items(),
        key=lambda kv: (-np.dtype(kv[1].dtype).itemsize, kv[0]),
    )
    layout, boff, foff = [], 0, 0
    for name, sds in items:
        dt = np.dtype(sds.dtype)
        n_elems = int(np.prod(sds.shape, dtype=np.int64))
        if _is_f64(dt):
            layout.append((name, dt, tuple(sds.shape), "f", foff, n_elems))
            foff += n_elems
            continue
        if dt == np.bool_:
            dt = np.dtype(np.uint8)
        nbytes = dt.itemsize * n_elems
        layout.append((name, dt, tuple(sds.shape), "b", boff, nbytes))
        boff += nbytes
    return layout


# the kernels' empty/masked fill convention moved to ops/device_reduce.py
# (the trim masks beyond-kept rows with the same fills); this alias keeps
# the one-copy contract and the historical import site
# (tests/test_blockskip.py::TestKernelNeutralFills)
_neutral_fill = dr_ops.neutral_fill


# device executors alive in this process: the chunklet/seal/upsert
# invalidation hooks (realtime/chunklet.py, storage/mutable.py) fan out
# partials-cache drops through this registry without holding an executor
# reference in ingest code
_EXECUTORS: "weakref.WeakSet" = weakref.WeakSet()


def invalidate_cached_partials(match: str) -> None:
    """Drop cached device partials whose batch involves a segment dir
    containing ``match`` on EVERY live executor — the chunklet
    promotion/seal/upsert-invalidation hook. Correctness never depends
    on it (batch keys change with the chunklet set, so stale entries are
    unreachable); it frees the HBM bytes those entries pin."""
    for ex in list(_EXECUTORS):
        ex.invalidate_partials(match)


def _neutral_outs(layout) -> dict:
    """Host-synthesized pipeline outputs for a FULLY-pruned launch: every
    leaf takes the exact fill its kernel produces under an all-false mask,
    keyed off the eval_shape layout so dtypes match the compiled pipeline
    bit-for-bit."""
    return {name: np.full(shp, _neutral_fill(name, dt), dtype=dt)
            for name, dt, shp, _which, _off, _size in layout}


def _width_audit(ctx, cols: dict, widths: dict) -> None:
    """PINOT_TPU_WIDTH_AUDIT=1 debug mode: after the column gather, assert
    no plane silently upcast past its planned storage dtype and log the
    per-column width table (plane dtype, sub-byte bits, FOR offset,
    register decode target, resident bytes). EXPLAIN renders the same
    table (engine/explain.py)."""
    import logging

    rows = []
    for key, sig in sorted(widths.items()):
        dt, bits, has_off, wide = sig
        arr = cols.get(key)
        if arr is None:
            continue
        got = np.dtype(arr.dtype)
        planned = np.dtype(np.uint8) if bits else np.dtype(dt)
        if got != planned:
            raise AssertionError(
                f"width audit: plane {key!r} upcast to {got} past its "
                f"planned {planned} (plan {sig})")
        rows.append(
            f"{key}: {np.dtype(dt).name}"
            + (f" packed={bits}b" if bits else "")
            + (" for-offset" if has_off else "")
            + (f" wide={np.dtype(wide).name}" if wide else "")
            + f" bytes={arr.nbytes}")
    logging.getLogger("pinot_tpu.device").info(
        "width audit (%d segments, pad_to=%d):\n  %s",
        ctx.S, ctx.pad_to, "\n  ".join(rows) if rows else "(no data planes)")


def _unpack_outs(bufs: dict, layout) -> dict:
    outs = {}
    for name, dt, shp, which, off, size in layout:
        buf = bufs[which]
        if which == "f":
            outs[name] = buf[off:off + size].reshape(shp)
        else:
            outs[name] = buf[off:off + size].view(dt).reshape(shp)
    return outs


def build_pipeline(template, mm_mode: str = "auto",
                   sorted_hll_ok: bool = False, blockskip=False,
                   widths=None, pallas_mode: str = "off"):
    """template (hashable) → jitted fn(cols, n_docs, params) → outputs dict.

    ``mm_mode``: "auto" → the factored one-hot matmul kernel
    (ops/groupby_mm.py) on TPU, scatter elsewhere; "interpret" forces the
    kernel in Pallas interpret mode (CPU tests); "off" forces scatter.

    The trailing ``final`` template field is mostly consumed OUTSIDE this
    function (``_finalize_sketch_outs``, applied after the mesh combine);
    with ``sorted_hll_ok`` (single-device executors only — the sorted
    sums are not shard-mergeable) a final template routes large-G HLL
    through the register-free sorted build (_hll_sorted_sums).

    ``blockskip``: compile the zone-map block-skip form (ops/blockskip.py):
    per-block verdicts from (S, NB) zone arrays, static-bound candidate
    compaction, and a gathered (B, R) filter+aggregation — with the dense
    form as the in-kernel overflow fallback (lax.cond), so an unselective
    query costs only the verdict + compaction work extra. The executor
    requests it for templates whose filter has interval structure.
    Truthiness selects the form; an int value > 1 additionally overrides
    the candidate-bound fraction (``ceil(total/frac)`` candidates instead
    of the static ``CAND_FRACTION``) — the plan advisor tightens it for
    templates whose measured selectivity leaves headroom, and a bound
    overflow still lands on the in-kernel dense fallback bit-exactly.

    Every pipeline honors the optional ``ps_alive`` param — the per-query
    (S,) segment-alive vector from launch-time stats pruning (Level 1).
    It is a PARAM, not part of the batch: the (S, L) batch, its compiled
    templates, and the cohort coalescer key stay stable across queries
    that prune different segment subsets.

    ``widths``: the batch's column width plan — {cols key: (dtype, bits,
    has_offset, wide)} from BatchContext.width_plan (None = every plane at
    its legacy wide dtype, the pre-narrowing form __graft_entry__ and the
    kernel-parity tests build directly). The executor folds the same
    mapping into its pipeline cache key, so one compiled template serves
    exactly the batches that share its width plan.

    ``pallas_mode``: "off" (the XLA scatter reference — the default, and
    the form the PINOT_TPU_PALLAS=0 / SET usePallas=false escape hatch
    and the quarantine XLA rung compile), "tpu", or "interpret" (CPU
    tests) — routes the scatter-bound ops through the Pallas kernel tier
    (ops/pallas_scatter.py): tiled local-accumulate group sums, min/max
    scatter, HLL register-max, and the fused filter+gather+aggregate
    form of the block-skip path.
    """
    shape, filter_tpl, group_cols, group_cards, aggs, sorted_k, _final = template
    mm_mode = _resolve_mm_mode(mm_mode)
    num_groups = 1
    for c in group_cards:
        num_groups *= c
    fused_plan = None
    if pallas_mode != "off" and blockskip and shape == "agg":
        from pinot_tpu.ops import pallas_scatter as ps_ops

        # the fused kernel gathers ONE zone block per grid step: a
        # retuned ZONE_BLOCK_ROWS must decline the plan, not silently
        # read a FUSED_BLOCK_ROWS prefix of every candidate block
        if bs_ops.BLOCK_ROWS == ps_ops.FUSED_BLOCK_ROWS:
            fused_plan = ps_ops.plan_fused(filter_tpl, aggs, widths or {})

    def _kfactor(key: str) -> int:
        """ids per stored byte-axis element (sub-byte plans pack 8//bits
        ids per uint8; everything else is 1:1)."""
        w = _col_width(widths, key)
        return 8 // w[1] if (w is not None and w[1]) else 1

    def pipeline(cols, n_docs, params):
        # zone cols are (S, NB) and sk:: sorted projections are 1-D — the
        # (S, L) shape inference must skip both; sub-byte planes store
        # L // factor bytes, so the LOGICAL row count multiplies back
        data_cols = {k: v for k, v in cols.items()
                     if not k.startswith((bs_ops.ZLO, bs_ops.ZHI))}
        any_key = next(k for k in data_cols if not k.startswith("sk::"))
        any_col = data_cols[any_key]
        S = any_col.shape[0]  # MV blocks are (S, L, K); masks are (S, L)
        L = any_col.shape[1] * _kfactor(any_key)
        alive = params.get("ps_alive")
        alive_b = jnp.ones((S,), dtype=bool) if alive is None \
            else alive.astype(bool)
        nd64 = n_docs.astype(jnp.int64)
        R = bs_ops.BLOCK_ROWS

        def _stat_outs(seg_matched, rows_filter, blocks_total, blocks_scanned):
            """Observability leaves every branch emits identically (mesh:
            seg_matched reassembles per-shard, the rest psum)."""
            return {
                "doc_count": jnp.sum(seg_matched),
                "seg_matched": seg_matched,
                "n_alive": jnp.sum(alive_b, dtype=jnp.int64),
                "rows_filter": rows_filter,
                "blocks_total": blocks_total,
                "blocks_scanned": blocks_scanned,
            }

        def dense(blocks_total):
            valid = mask_ops.valid_mask(n_docs, L, batched=True) \
                & alive_b[:, None]
            mask = _eval_filter(filter_tpl, data_cols, params, (S, L),
                                widths) & valid
            seg_matched = jnp.sum(mask, axis=1, dtype=jnp.int64)
            outs = _stat_outs(
                seg_matched, jnp.sum(jnp.where(alive_b, nd64, 0)),
                blocks_total, blocks_total)
            return _aggregate(data_cols, params, mask, outs)

        if not blockskip or L % R:
            return dense(jnp.int64(0))

        # ---- zone-map block skip (ops/blockskip.py) ----------------------
        NB = L // R
        blocks_total = jnp.sum(jnp.where(alive_b, (nd64 + R - 1) // R, 0))
        verdict = bs_ops.zone_verdict(filter_tpl, cols, params, (S, NB),
                                      widths)
        block_start = jnp.arange(NB, dtype=jnp.int32) * R
        verdict = verdict & (block_start[None, :] < n_docs[:, None]) \
            & alive_b[:, None]
        flat = verdict.reshape(-1)
        total = S * NB
        frac = bs_ops.CAND_FRACTION if blockskip is True \
            or int(blockskip) <= 1 else int(blockskip)
        B = min(total, max(1, -(-total // frac)))
        n_cand = jnp.sum(flat, dtype=jnp.int32)
        cand, cand_valid = bs_ops.compact_candidates(flat, B)

        def fused_skip(ps_ops):
            """Fused filter+gather+aggregate (ops/pallas_scatter.py): the
            kernel's scalar-prefetched candidate indices drive its DMA,
            so the (B, R) gather buffer the generic branch materializes
            never exists. Aggregation runs over STORAGE-space values;
            decode (widening + frame-of-reference offsets) applies to the
            answer-scale per-block partials here — Σ(v+fo) = Σv + fo·n
            and min(v+fo) = min(v)+fo are exact — so the leaves match the
            dense branch's dtypes and values bit-for-bit (lax.cond
            requires the former; the differential suite pins the
            latter)."""
            seg_of = cand // NB
            rows_in = jnp.where(
                cand_valid,
                jnp.clip(n_docs[seg_of] - (cand % NB) * R, 0, R),
                0).astype(jnp.int32)
            col_arrays = {
                key: data_cols[key].reshape(S * NB, R // 128, 128)
                for key in fused_plan.cols}
            par_arrays = {}
            for key, (ck, kindp) in fused_plan.pred_params.items():
                p = params[key].reshape(-1)
                if kindp == "storage":
                    w = widths.get(ck)
                    p64 = p.astype(jnp.int64)
                    if w[2]:
                        fo = params.get("fo::" + ck)
                        if fo is not None:
                            p64 = p64 - fo.astype(jnp.int64)
                    # clip into the plane's value range ±1: storage values
                    # are a strict subset, so every comparison survives
                    info = np.iinfo(np.dtype(w[0]))
                    p64 = jnp.clip(p64, int(info.min) - 1,
                                   int(info.max) + 1)
                    par_arrays[key] = p64.astype(jnp.int32)
                else:
                    par_arrays[key] = p.astype(jnp.int32)
            ints, flts = ps_ops.fused_filter_agg(
                cand, rows_in, col_arrays, par_arrays, fused_plan,
                interpret=(pallas_mode == "interpret"))
            block_matched = ints[:, 0].astype(jnp.int64)
            seg_matched = jnp.zeros(S + 1, dtype=jnp.int64).at[
                jnp.where(cand_valid, seg_of, S)].add(block_matched)[:S]
            outs = _stat_outs(
                seg_matched, jnp.sum(rows_in, dtype=jnp.int64),
                blocks_total, n_cand.astype(jnp.int64))
            dc = outs["doc_count"]
            by_idx: dict = {}
            for spec in fused_plan.aggs:
                by_idx.setdefault(spec[0], []).append(spec)
            for i, (name, argt, extra) in enumerate(aggs):
                k = f"a{i}"
                if name == "count" or i not in by_idx:
                    continue
                for (_i, op, ck, buf, slot, _fill) in by_idx[i]:
                    w = widths.get(ck)
                    wide = jnp.dtype(w[3]) if w[3] else jnp.dtype(w[0])
                    fo = params.get("fo::" + ck) if w[2] else None
                    if op == "sum":
                        s = jnp.sum(ints[:, slot].astype(jnp.int64))
                        if fo is not None:
                            s = s + fo.astype(jnp.int64) * dc
                        outs[f"{k}_sum"] = s
                    elif buf == "int":
                        col = ints[:, slot]
                        red = (col.min() if op == "min" else
                               col.max()).astype(wide)
                        if fo is not None:
                            red = red + fo
                        info = jnp.iinfo(wide)
                        empty = info.max if op == "min" else info.min
                        outs[f"{k}_{op}"] = jnp.where(dc > 0, red, empty)
                    else:
                        col = flts[:, slot]
                        red = col.min() if op == "min" else col.max()
                        outs[f"{k}_{op}"] = red.astype(wide)
            return outs

        def skip():
            if fused_plan is not None:
                from pinot_tpu.ops import pallas_scatter as ps_ops

                if ps_ops.fused_params_ok(fused_plan, params):
                    return fused_skip(ps_ops)
            seg_of = cand // NB
            row_idx = ((cand % NB) * R)[:, None] \
                + jnp.arange(R, dtype=jnp.int32)[None, :]
            rvalid = cand_valid[:, None] & (row_idx < n_docs[seg_of][:, None])
            # sub-byte planes gather at their PACKED block width (R // f
            # bytes per block; R = 4096 divides by every pack factor) and
            # unpack post-gather at the access site (_ids_col)
            g_cols = {k: bs_ops.gather_blocks(v, cand, NB, R // _kfactor(k))
                      for k, v in data_cols.items()}
            mask = _eval_filter(filter_tpl, g_cols, params, (B, R),
                                widths) & rvalid
            block_matched = jnp.sum(mask, axis=1, dtype=jnp.int64)
            seg_matched = jnp.zeros(S + 1, dtype=jnp.int64).at[
                jnp.where(cand_valid, seg_of, S)].add(block_matched)[:S]
            outs = _stat_outs(
                seg_matched, jnp.sum(rvalid, dtype=jnp.int64),
                blocks_total, n_cand.astype(jnp.int64))
            return _aggregate(g_cols, params, mask, outs)

        def _pad_table(outs):
            """Sorted-regime (radix) tables size as min(rows, K), and the
            cond's branches see different row counts — pad both to the
            template K with each reduction's NEUTRAL fill (identical to
            the kernel's own empty-slot fills, so merges see nothing
            new). Non-sorted shapes are already K-independent."""
            if shape != "groupby_sorted":
                return outs
            out2 = {}
            for k, v in outs.items():
                # ops/device_reduce.py STAT_KEYS is the ONE list of
                # non-group-table leaves (apply_trim shares it — a new
                # stat leaf added to _stat_outs must land there or the
                # trim would gather it as a table column)
                if k in dr_ops.STAT_KEYS or v.ndim == 0 \
                        or v.shape[0] >= sorted_k:
                    out2[k] = v
                    continue
                fill = _neutral_fill(k, v.dtype)
                out2[k] = jnp.concatenate(
                    [v, jnp.full((sorted_k - v.shape[0],), fill, v.dtype)])
            return out2

        # overflow (candidates past the static bound) falls back to the
        # DENSE branch of the same compiled kernel — no host round trip,
        # no result-shape change; just the verdict work wasted
        return jax.lax.cond(n_cand > B,
                            lambda: _pad_table(dense(blocks_total)),
                            lambda: _pad_table(skip()))

    def _aggregate(cols, params, mask, outs):
        """Filter mask → aggregation outputs; shape-agnostic over the row
        layout (dense (S, L) or gathered (B, R) — every reduction lands in
        template-shaped accumulators either way)."""
        if shape == "groupby_sorted":
            # RADIX-PARTITIONED high-cardinality regime (the MAP_BASED
            # analog of DictionaryBasedGroupKeyGenerator): dense
            # accumulators would blow HBM past MAX_DENSE_GROUPS, so the
            # packed group key rides ops/radix_groupby.py — chunk-local
            # sorts + run-end partials + compacted multi-level merge —
            # instead of the old monolithic lax.sort of the full (n,)
            # int64 key array (~1.6 GB/s at 100M rows; BENCH_r05
            # micro.sortkey_int64). Keys pack int32 when the cartesian
            # key space allows (half the comparator bytes). K comes from
            # the engine's num_groups_limit (template-encoded); overflow
            # is detected host-side and falls back to the host path so
            # device truncation policy never leaks into results. The
            # (K,) table this emits is keyed, so parallel/mesh.py can
            # merge per-shard tables (merge_tables) — the old basis was
            # not mesh-combinable at all.
            K = sorted_k
            per_col = [_ids_col(cols, c, widths) for c in group_cols]
            key = radix_ops.pack_keys(per_col, group_cards, mask)
            # dedup payloads by argument template: MIN(x)+MAX(x)+AVG(x)
            # must carry ONE copy of x through the level-1 sort, not three
            payloads, pname_of = {}, {}
            sums, mins, maxs = set(), set(), set()
            for i, (name, argt, extra) in enumerate(aggs):
                if name == "count":
                    continue
                if argt not in pname_of:
                    v = _eval_expr(argt, cols, params, widths)
                    # integer args accumulate exactly in int64 (the host /
                    # dense paths are exact; per-doc f64 adds would round)
                    as_int = jnp.issubdtype(v.dtype, jnp.integer)
                    dt = jnp.int64 if as_int else jnp.float64
                    pname = f"p{len(payloads)}"
                    pname_of[argt] = pname
                    payloads[pname] = (v.astype(dt).reshape(-1),
                                       "int" if as_int else "float")
                pname = pname_of[argt]
                if name in ("sum", "avg"):
                    sums.add(pname)
                if name in ("min", "minmaxrange"):
                    mins.add(pname)
                if name in ("max", "minmaxrange"):
                    maxs.add(pname)
            tbl = radix_ops.chunked_group_aggregate(
                key.reshape(-1), payloads, sums, mins, maxs, K)
            empty = tbl["empty"]
            outs["n_groups_total"] = tbl["n_groups_total"]
            outs["skeys"] = tbl["skeys"]
            outs["gcount"] = tbl["gcount"]
            # empty-slot fills are each reduction's NEUTRAL element, so a
            # cross-shard merge of partially-filled tables stays exact
            for i, (name, argt, extra) in enumerate(aggs):
                k = f"a{i}"
                if name == "count":
                    continue
                pname = pname_of[argt]
                if name in ("sum", "avg"):
                    s = tbl["sum::" + pname]
                    outs[f"{k}_sum"] = jnp.where(
                        empty, jnp.zeros((), s.dtype), s)
                if name in ("min", "minmaxrange"):
                    col = tbl["min::" + pname]
                    outs[f"{k}_min"] = jnp.where(
                        empty, _neutral_fill(f"{k}_min", col.dtype), col)
                if name in ("max", "minmaxrange"):
                    col = tbl["max::" + pname]
                    outs[f"{k}_max"] = jnp.where(
                        empty, _neutral_fill(f"{k}_max", col.dtype), col)
            return outs

        if shape == "groupby":
            # columns are already global ids: the group key IS the column
            per_col = [_ids_col(cols, c, widths) for c in group_cols]
            gid = agg_ops.group_ids_combine(per_col, group_cards, mask, num_groups)
            mm_done = _try_mm_groupby(
                aggs, gid, cols, params, num_groups, mm_mode, outs, widths,
                pallas_mode=pallas_mode,
            )
            if "gcount" not in outs:
                outs["gcount"] = agg_ops.group_count(gid, num_groups)
            for i, (name, argt, extra) in enumerate(aggs):
                k = f"a{i}"
                if i in mm_done or name == "count":
                    pass  # produced by the matmul kernel / gcount reused
                elif name in ("sum", "avg"):
                    v = _eval_expr(argt, cols, params, widths)
                    rpb = _rows_per_block(v, _legacy_rpb(extra))
                    outs[f"{k}_sum"] = agg_ops.group_sum(gid, v, num_groups, rpb)
                elif name == "min":
                    v = _eval_expr(argt, cols, params, widths)
                    outs[f"{k}_min"], = _group_extreme(
                        gid, v, num_groups, ("min",), pallas_mode)
                elif name == "max":
                    v = _eval_expr(argt, cols, params, widths)
                    outs[f"{k}_max"], = _group_extreme(
                        gid, v, num_groups, ("max",), pallas_mode)
                elif name == "minmaxrange":
                    v = _eval_expr(argt, cols, params, widths)
                    outs[f"{k}_min"], outs[f"{k}_max"] = _group_extreme(
                        gid, v, num_groups, ("min", "max"), pallas_mode)
                elif name == "distinctcount":
                    card = extra
                    # ids widen in-register: uint8 * weak-int arithmetic
                    # would wrap at the storage width
                    sub = jnp.clip(_ids_col(cols, argt, widths), 0,
                                   card - 1).astype(jnp.int32)
                    gid2 = jnp.where(mask, gid * card + sub, num_groups * card)
                    pres = jnp.zeros(num_groups * card + 1, dtype=jnp.int8)
                    pres = pres.at[gid2.reshape(-1)].max(1)
                    outs[f"{k}_pres"] = pres[: num_groups * card].reshape(num_groups, card)
                elif name == "distinctcounthll":
                    log2m = extra
                    m = 1 << log2m
                    if _hll_sort_eligible(_final, sorted_hll_ok, num_groups,
                                          log2m, mm_mode):
                        sk_key = f"sk::{argt}::{log2m}"
                        if filter_tpl == ("true",) and sk_key in cols:
                            # FILTERLESS: the batch's cached sorted
                            # projection already holds the packed keys —
                            # no per-query sort at all
                            outs[f"{k}_hs"] = _hll_sums_from_sorted(
                                cols[sk_key], num_groups, log2m, mm_mode)
                            continue
                        h = cols["hh::" + argt]
                        idx, rho = hll_ops.hll_idx_rho(h, log2m)
                        slot = jnp.where(mask, gid * m + idx,
                                         num_groups * m)
                        outs[f"{k}_hs"] = _hll_sorted_sums(
                            slot, rho, num_groups, log2m, mm_mode)
                    else:
                        # per-doc value hashes, gathered host-side at upload
                        h = cols["hh::" + argt]
                        idx, rho = hll_ops.hll_idx_rho(h, log2m)
                        slot = jnp.where(mask, gid * m + idx,
                                         num_groups * m)
                        outs[f"{k}_regs"] = _hll_regs(
                            slot, rho, num_groups, log2m, mm_mode,
                            pallas_mode,
                        )
                elif name == "hllmerge":
                    # cube rows carry whole register planes: scatter-max the
                    # (rows, m) planes into (G, m) — rows ≈ distinct dim
                    # combos, so this is answer-sized work
                    m = 1 << extra
                    planes = cols["bp::" + argt].astype(jnp.int32)
                    gid2 = jnp.where(mask, gid, num_groups).reshape(-1)
                    regs = jnp.zeros((num_groups + 1, m), dtype=jnp.int32)
                    regs = regs.at[gid2].max(planes.reshape(-1, m))
                    outs[f"{k}_regs"] = regs[:num_groups]
                elif name in ("firstwithtime", "lastwithtime"):
                    v = _eval_expr(argt[0], cols, params, widths)
                    t = _eval_expr(argt[1], cols, params, widths)
                    first = name == "firstwithtime"
                    tb, vb = agg_ops.group_arg_time(gid, v, t, num_groups, first)
                    suff = "tmin" if first else "tmax"
                    outs[f"{k}_{suff}"] = tb
                    outs[f"{k}_v{suff}"] = vb
            return outs

        # scalar aggregation shape
        for i, (name, argt, extra) in enumerate(aggs):
            k = f"a{i}"
            if name == "count":
                pass  # doc_count reused
            elif name in ("sum", "avg"):
                v = _eval_expr(argt, cols, params, widths)
                outs[f"{k}_sum"] = agg_ops.agg_sum(v, mask)
            elif name == "min":
                outs[f"{k}_min"] = agg_ops.agg_min(
                    _eval_expr(argt, cols, params, widths), mask)
            elif name == "max":
                outs[f"{k}_max"] = agg_ops.agg_max(
                    _eval_expr(argt, cols, params, widths), mask)
            elif name == "minmaxrange":
                v = _eval_expr(argt, cols, params, widths)
                outs[f"{k}_min"] = agg_ops.agg_min(v, mask)
                outs[f"{k}_max"] = agg_ops.agg_max(v, mask)
            elif name == "distinctcount":
                card = extra
                sub = jnp.clip(_ids_col(cols, argt, widths), 0,
                               card - 1).astype(jnp.int32)
                slot = jnp.where(mask, sub, card)
                outs[f"{k}_pres"] = agg_ops.distinct_presence(slot, card)
            elif name == "distinctcounthll":
                log2m = extra
                m = 1 << log2m
                h = cols["hh::" + argt]
                idx, rho = hll_ops.hll_idx_rho(h, log2m)
                slot = jnp.where(mask, idx, m)
                outs[f"{k}_regs"] = _hll_regs(
                    slot, rho, 1, log2m, mm_mode, pallas_mode)[0]
            elif name == "hllmerge":
                m = 1 << extra
                planes = cols["bp::" + argt].astype(jnp.int32)
                outs[f"{k}_regs"] = jnp.max(
                    jnp.where(mask[..., None], planes, 0), axis=(0, 1))
            elif name in ("firstwithtime", "lastwithtime"):
                v = _eval_expr(argt[0], cols, params, widths)
                t = _eval_expr(argt[1], cols, params, widths)
                first = name == "firstwithtime"
                tb, vb = agg_ops.agg_arg_time(v, t, mask, first)
                suff = "tmin" if first else "tmax"
                outs[f"{k}_{suff}"] = tb
                outs[f"{k}_v{suff}"] = vb
        return outs

    return pipeline  # caller jits (single-device) or shard_maps (mesh)


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


class DeviceExecutor:
    MAX_CACHED_BATCHES = 4  # LRU cap: a batch holds full columns in HBM
    # byte-aware cap: column blocks are materialized lazily, so the byte
    # check runs again as each in-flight launch drains (_release_launch)
    MAX_CACHED_BYTES = int(os.environ.get("PINOT_TPU_BATCH_CACHE_BYTES", 6 << 30))

    def __init__(self, mesh=None, mm_mode: str = "auto",
                 num_groups_limit: int = 100_000,
                 pallas_mode: str | None = None):
        """``mesh``: optional jax Mesh — shard the segment axis over it with
        psum-combined accumulators (parallel/mesh.py) instead of a
        single-device batched launch. ``mm_mode``: see build_pipeline.
        ``num_groups_limit``: the sorted high-card regime's group-table
        cap, matching the engine's numGroupsLimit. ``pallas_mode``:
        the scatter-kernel tier's mode (None = follow ``mm_mode``, so
        DeviceExecutor(mm_mode="interpret") exercises the Pallas tier in
        CPU tests exactly like the matmul kernel); per-process
        PINOT_TPU_PALLAS=0 and per-query SET usePallas=false force the
        XLA scatter path end to end."""
        self.mesh = mesh
        self.mm_mode = mm_mode
        self.pallas_mode = pallas_mode
        self.num_groups_limit = max(1, num_groups_limit)
        self._batches: dict = {}     # segment-set key -> BatchContext (LRU)
        # (template, mm_mode, blockskip, width_sig, trim, pallas) -> entry
        self._pipelines: dict = {}
        # thread safety: server query threads launch/fetch concurrently —
        # one lock guards the caches, refcounts, and observability fields
        # (BatchContext guards its own lazy column materialization)
        self._lock = threading.RLock()
        self._inflight_launches: dict = {}  # batch key -> in-flight count
        self.inflight = 0            # launches between dispatch and fetch
        self.coalescer = LaunchCoalescer()
        # cumulative host-link observability (bench reads deltas per query)
        self.fetch_bytes_total = 0
        self.fetch_leaves_total = 0
        # device-resident per-template partials cache (sub-RTT serving): a
        # repeat query — same pipeline entry, same batch, same literal
        # values / ps_alive verdicts — skips the column gather, dispatch,
        # and kernel entirely and re-fetches the CACHED packed output
        # buffer (one link RTT, zero device work). Keys are
        # (pipeline-key, batch_key, host-bytes digest): PR-4 made
        # template/cohort keys literal-independent, so the literal VALUES
        # digest is exactly what distinguishes repeat executions. Entries
        # die with their batch (_drop_partials_for_batch at every evict
        # site) and on chunklet promotion/seal/upsert via
        # invalidate_partials; bytes/hit/miss/eviction counters surface
        # through hbm_stats() and the server's /metrics gauges.
        self.partials_cache_enabled = os.environ.get(
            "PINOT_TPU_PARTIALS_CACHE", "1") not in ("", "0")
        self.MAX_CACHED_PARTIALS = int(os.environ.get(
            "PINOT_TPU_PARTIALS_CACHE_ENTRIES", 256))
        self.MAX_PARTIALS_BYTES = int(os.environ.get(
            "PINOT_TPU_PARTIALS_CACHE_BYTES", 128 << 20))
        self.PARTIALS_ENTRY_MAX_BYTES = 4 << 20  # don't pin huge tables
        self._partials: dict = {}  # key -> (bufs_dev, layout, nbytes)
        self.partials_bytes = 0
        self.partials_hits = 0
        self.partials_misses = 0
        # evictions = capacity pressure (size the cache from this);
        # invalidations = batch-eviction/chunklet/upsert/seal drops
        # (ingest churn — conflating the two would misread a realtime
        # table's promote cycle as an undersized cache)
        self.partials_evictions = 0
        self.partials_invalidations = 0
        # on-device final-reduce observability: queries whose group trim
        # ran in-kernel, and the host-side completion time of that reduce
        # (decode of the trimmed table — the full host reduce this
        # replaces walked O(G) accumulators)
        self.device_reduce_queries = 0
        self.device_reduce_ms_total = 0.0
        # server-partial trim bound (engine/reduce.py trim_bound's
        # min_trim_size); ServerInstance overwrites it with its
        # group_trim_size so device and host trims share one policy
        self.group_trim_size = 5000
        _EXECUTORS.add(self)
        # batch-LRU / HBM observability: cache hit/miss/eviction counters
        # plus per-batch resident bytes and bytes the width planning saved
        # (hbm_stats — surfaced through server /metrics gauges and bench
        # detail.narrow)
        self.batch_hits = 0
        self.batch_misses = 0
        self.batch_evictions = 0
        # device-error recovery (failure-domain hardening): per-(template,
        # batch) failure counts feed a quarantine circuit breaker — a
        # pipeline that keeps failing on device routes to the host path
        # so one poisoned shape can't take down the executor. Counters
        # surface through hbm_stats() and the server's /metrics gauges.
        self.launch_failures = 0         # device-runtime failures observed
        self._pipeline_failures: dict = {}   # (template, batch_key) -> n
        self._quarantined: dict = {}         # key -> quarantined-at ts
        self._poisoned_batches: set = set()  # evict once their pins drain
        # Pallas-tier quarantine rung (ISSUE 15): a failing Pallas
        # pipeline drops to the XLA scatter form ON DEVICE first — host
        # only when the XLA rung fails too. One failure blocks the
        # (template, batch) pair for QUARANTINE_TTL_S; the host-path
        # quarantine's strike counting only ever sees XLA-rung failures.
        self._pallas_blocked: dict = {}      # (template, batch_key) -> ts
        self.pallas_fallbacks = 0            # pallas → XLA rung drops
        # kernel roofline accounting (ISSUE 11): per-pipeline-label
        # aggregates of the static bytes-moved cost model (ColPlan-width
        # column planes, block-skip gather ratio, trimmed fetch bytes)
        # against the measured kernel/link wall — achieved GB/s vs the
        # per-process HBM peak probe (ops/roofline.py), surfaced through
        # hbm_stats()["roofline"], the deviceKernelGbps histogram, and
        # per-query IntermediateResult.roofline records
        self._roofline: dict = {}
        # last-launch capture for kernel profiling (bench breakdown):
        # (pipeline, cols, n_docs, params, bytes_in). OPT-IN: retaining
        # the launch pins a whole batch's HBM past the batch cache's
        # eviction budget, so production executes must not capture it.
        self.profile_enabled = False
        self._last_launch = None
        self.last_get_wait_s = None
        # device launch/fetch latency histograms ride the server registry
        # (ISSUE 7: the hot timers share ONE histogram-backed truth)
        self.metrics = get_metrics("server")
        # feedback-driven plan advisor (engine/advisor.py): per-template
        # memos of measured skip selectivity / rung GB/s / group counts /
        # cohort cohesion feed the next execution's candidate-bound, rung,
        # trim, and cohort-window choices. None disables process-wide
        # (pinot.advisor.enabled=false); SET useAdvisor=false per query.
        self.advisor = PlanAdvisor.from_config()
        # stateless launch-time stats pruner (engine.SegmentPruner), built
        # lazily to keep the engine module import one-directional
        self._stats_pruner = None
        # NOTE: predicate-literal device caching lives in params._slot —
        # keyed on host bytes BEFORE upload (keying device arrays here
        # would cost a blocking device→host read per literal)

    def profile_last_launch(self, iters: int = 8):
        """Amortized pure-DEVICE time of the last executed pipeline:
        dispatch the identical launch ``iters`` times and fetch a TINY
        token that depends on the final launch — on the bench tunnel,
        ``block_until_ready`` is a no-op (completion is only observable
        through device_get), and async dispatches pipeline, so
        (T_iters - T_1) / (iters - 1) isolates per-launch kernel time
        from the round-trip floor. Returns (kernel_seconds, bytes_read)
        or None when nothing was captured."""
        import time as _time

        if self._last_launch is None:
            return None
        pipeline, cols, n_docs, params, bytes_in = self._last_launch
        token = jax.jit(
            lambda o: sum(jnp.sum(v.reshape(-1)[:1].astype(jnp.float32))
                          for v in o.values()))

        def timed(k):
            outs = None
            t0 = _time.perf_counter()
            for _ in range(k):
                outs = pipeline(cols, n_docs, params)
            jax.device_get(token(outs))
            return _time.perf_counter() - t0

        kernel_s = amortized_launch_time(timed, iters)
        return kernel_s, bytes_in

    # cheap static check (EXPLAIN backend display)
    def supports(self, q: QueryContext) -> bool:
        aggs = q.aggregations()
        if q.distinct:
            return not aggs and all(e.is_identifier
                                    for e in q.select_expressions)
        if not aggs:
            return False
        return all(a.name in DEVICE_AGGS for a in aggs)

    @staticmethod
    def _batch_key(segments):
        return tuple(s.dir for s in segments)

    def batch_for(self, segments, retain: bool = False) -> BatchContext:
        """LRU-cached BatchContext for this segment set. ``retain=True``
        takes the in-flight pin ATOMICALLY with the cache insert (same
        lock hold) — pinning after return would leave a window where a
        concurrent _evict drops the still-unpinned batch and the next hit
        rebuilds a duplicate at transiently ~2x the byte budget."""
        key = self._batch_key(segments)
        with self._lock:
            ctx = self._batches.pop(key, None)
            if ctx is None:
                ctx = BatchContext(segments)
                self.batch_misses += 1
            else:
                self.batch_hits += 1
            self._batches[key] = ctx
            if retain:
                self._retain_launch(key)  # RLock: reentrant
        self._evict(keep=key)
        return ctx

    def _evict(self, keep=None):
        """LRU eviction by count AND resident HBM bytes (a 100M-row batch's
        decoded/prehashed blocks alone can approach HBM capacity — count
        caps alone don't bound that). Batches with in-flight launches are
        PINNED (refcounted via _retain_launch): evicting one would drop
        HBM blocks a dispatched-but-unfetched query is still reading.

        The byte sum runs OUTSIDE the executor lock: device_bytes takes
        each batch's materialization lock, and a cold multi-GB column
        build can hold that for seconds — holding the executor lock
        across it would serialize every concurrent launch/fetch. The
        snapshot is racy by design; eviction is best-effort LRU."""
        while True:
            with self._lock:
                batches = list(self._batches.values())
                over = len(batches) > self.MAX_CACHED_BATCHES
            if not over:
                total = sum(b.device_bytes() for b in batches)
                if not (total > self.MAX_CACHED_BYTES and len(batches) > 1):
                    return
            with self._lock:
                lru = next(
                    (k for k in self._batches
                     if k != keep and k not in self._inflight_launches), None)
                if lru is None:
                    return  # everything else is pinned by in-flight launches
                self._batches.pop(lru)
                self.batch_evictions += 1
                # cached partials read from the evicted batch's launch:
                # they die with it (a rebuilt same-key batch would answer
                # identically, but the entries' HBM buffers must not
                # outlive the LRU decision that freed the batch)
                self._drop_partials_for_batch(lru)

    def _batch_list(self) -> list:
        with self._lock:
            return list(self._batches.values())

    def resident_bytes(self) -> int:
        """Total HBM bytes of cached batches (lock-free per-batch counter
        reads; one short lock hold to snapshot the batch list)."""
        return sum(b.device_bytes() for b in self._batch_list())

    def narrow_saved_bytes(self) -> int:
        """Total bytes the width planning saved vs the wide layout across
        cached batches."""
        return sum(b.narrow_saved_bytes() for b in self._batch_list())

    # ---- device partials cache (sub-RTT repeat queries) ------------------
    def _partials_get(self, key):
        """LRU lookup; counts the hit/miss. Returns (bufs_dev, layout) or
        None."""
        with self._lock:
            ent = self._partials.pop(key, None)
            if ent is None:
                self.partials_misses += 1
                return None
            self._partials[key] = ent  # LRU touch
            self.partials_hits += 1
            return ent[0], ent[1]

    def _partials_put(self, key, bufs_dev, layout) -> None:
        """Insert a just-dispatched packed buffer. The buffer is the
        SAME device array the in-flight fetch resolves — jax arrays are
        immutable, so caching it costs no extra HBM beyond keeping it
        alive. Entries past the per-entry byte cap are skipped (a huge
        untrimmed table would evict the whole cache for one query)."""
        nbytes = sum(sz if which == "b" else sz * 8
                     for _n, _dt, _shp, which, _off, sz in layout)
        if nbytes > self.PARTIALS_ENTRY_MAX_BYTES:
            return
        with self._lock:
            if key in self._partials:
                return
            self._partials[key] = (bufs_dev, layout, nbytes)
            self.partials_bytes += nbytes
            while self._partials and (
                    len(self._partials) > self.MAX_CACHED_PARTIALS
                    or self.partials_bytes > self.MAX_PARTIALS_BYTES):
                old = next(iter(self._partials))
                self._partials_drop_locked(old)

    def _partials_drop_locked(self, key, invalidation: bool = False) -> None:
        ent = self._partials.pop(key, None)
        if ent is not None:
            self.partials_bytes -= ent[2]
            if invalidation:
                self.partials_invalidations += 1
            else:
                self.partials_evictions += 1

    def _drop_partials_for_batch(self, batch_key) -> None:
        """Caller holds self._lock (RLock): drop every cache entry tied
        to an evicted/poisoned batch."""
        for k in [k for k in self._partials if k[1] == batch_key]:
            self._partials_drop_locked(k, invalidation=True)

    def invalidate_partials(self, match: str) -> None:
        """Drop entries whose batch contains a segment dir matching
        ``match`` (substring) — the chunklet promotion/seal/upsert hook
        (module-level invalidate_cached_partials fans this out)."""
        with self._lock:
            dead = [k for k in self._partials
                    if any(match in d for d in k[1])]
            for k in dead:
                self._partials_drop_locked(k, invalidation=True)

    def hbm_stats(self) -> dict:
        """HBM / batch-LRU observability snapshot: per-batch resident
        bytes and narrowing savings, cumulative hit/miss/eviction
        counters, and the byte budget. Byte reads are the batches'
        lock-free insert-time counters (see BatchContext.device_bytes), so
        this never stalls a cold column build."""
        with self._lock:
            batches = list(self._batches.items())
            snap = {
                "batch_hits": self.batch_hits,
                "batch_misses": self.batch_misses,
                "batch_evictions": self.batch_evictions,
                # device-error recovery counters (failure-domain view):
                # launch/fetch device-runtime failures and pipelines the
                # circuit breaker has routed to host
                "device_failures": self.launch_failures,
                "quarantined_pipelines": len(self._quarantined),
                # Pallas scatter tier (ISSUE 15): (template, batch) pairs
                # currently dropped to the XLA scatter rung, and the
                # cumulative drop count
                "pallas_quarantined": len(self._pallas_blocked),
                "pallas_fallbacks": self.pallas_fallbacks,
                # sub-RTT serving (ISSUE 9): device partials cache +
                # on-device final-reduce counters
                "partials_cache_entries": len(self._partials),
                "partials_cache_bytes": self.partials_bytes,
                "partials_cache_hits": self.partials_hits,
                "partials_cache_misses": self.partials_misses,
                "partials_cache_evictions": self.partials_evictions,
                "partials_cache_invalidations": self.partials_invalidations,
                "device_reduce_queries": self.device_reduce_queries,
                "device_reduce_ms": round(self.device_reduce_ms_total, 3),
            }
        per_batch = [
            {
                "segments": len(key),
                "resident_bytes": ctx.device_bytes(),
                "narrow_saved_bytes": ctx.narrow_saved_bytes(),
            }
            for key, ctx in batches
        ]
        snap.update(
            cached_batches=len(per_batch),
            resident_bytes=sum(b["resident_bytes"] for b in per_batch),
            narrow_saved_bytes=sum(
                b["narrow_saved_bytes"] for b in per_batch),
            max_cached_bytes=self.MAX_CACHED_BYTES,
            batches=per_batch,
        )
        # kernel roofline accounting (ISSUE 11): per-pipeline achieved
        # GB/s vs the probed HBM peak
        snap["roofline"] = self.roofline_stats()
        return snap

    def _retain_launch(self, key) -> None:
        with self._lock:
            self._inflight_launches[key] = \
                self._inflight_launches.get(key, 0) + 1
            self.inflight += 1

    def _release_launch(self, key) -> None:
        with self._lock:
            n = self._inflight_launches.get(key, 0) - 1
            if n > 0:
                self._inflight_launches[key] = n
            else:
                self._inflight_launches.pop(key, None)
            self.inflight -= 1
            # a fetch-time device failure marked this batch poisoned:
            # evict it as soon as the last in-flight pin drains, so the
            # next query re-uploads fresh device buffers
            if key in self._poisoned_batches \
                    and key not in self._inflight_launches:
                self._poisoned_batches.discard(key)
                if self._batches.pop(key, None) is not None:
                    self.batch_evictions += 1
                self._drop_partials_for_batch(key)
        # byte cap re-check after the fetch (columns materialize lazily,
        # so the batch may have grown during this query)
        self._evict(keep=key)

    # ---- device-error recovery (launch/fetch failures) -------------------
    QUARANTINE_AFTER = 2       # failures of one (template, batch) → host
    QUARANTINE_TTL_S = 300.0   # then probe the device again (half-open)
    MAX_FAILURE_KEYS = 1024    # failure-count map bound (diverse workloads)

    def _record_device_failure(self, template, batch_key) -> bool:
        """Count a device-runtime failure against (template, batch) and
        trip the quarantine breaker past the threshold. Compiled
        pipelines for the template are dropped (a retry recompiles from
        scratch). Returns True when the key is now quarantined."""
        with self._lock:
            self.launch_failures += 1
            key = (template, batch_key)
            if key not in self._pipeline_failures and \
                    len(self._pipeline_failures) >= self.MAX_FAILURE_KEYS:
                self._pipeline_failures.pop(
                    next(iter(self._pipeline_failures)))
            n = self._pipeline_failures.get(key, 0) + 1
            self._pipeline_failures[key] = n
            if n >= self.QUARANTINE_AFTER:
                self._quarantined[key] = time.monotonic()
            for pk in [pk for pk in self._pipelines if pk[0] == template]:
                self._pipelines.pop(pk)
            return key in self._quarantined

    def _note_device_success(self, template, batch_key) -> None:
        """A successful fetch clears the key's strike count: the breaker
        trips on failures close together, not on two transient faults a
        week apart over thousands of good launches."""
        with self._lock:
            self._pipeline_failures.pop((template, batch_key), None)

    def _resolve_pallas(self, opts: dict) -> str:
        """Per-launch Pallas-tier mode: env kill switch, per-query SET
        opt-out, then the executor's configured mode (None = follow
        mm_mode, mirroring how the tier is exercised in interpret-mode
        tests)."""
        if os.environ.get("PINOT_TPU_PALLAS", "1") in ("", "0"):
            return "off"
        if bool_option(opts, "usepallas", None) is False:
            return "off"
        mode = self.mm_mode if self.pallas_mode is None else self.pallas_mode
        return _resolve_mm_mode(mode)

    def _is_pallas_blocked(self, template, batch_key) -> bool:
        with self._lock:
            ts = self._pallas_blocked.get((template, batch_key))
            if ts is None:
                return False
            if time.monotonic() - ts >= self.QUARANTINE_TTL_S:
                # half-open: probe the Pallas form again after cooldown
                self._pallas_blocked.pop((template, batch_key), None)
                return False
            return True

    def _block_pallas(self, template, batch_key) -> None:
        """Drop a failing (template, batch) pair to the XLA scatter rung:
        the NEXT launch compiles the pallas_mode="off" pipeline variant —
        still on device. Compiled Pallas-form entries for the template
        are dropped so the rung takes effect immediately."""
        with self._lock:
            if (template, batch_key) not in self._pallas_blocked and \
                    len(self._pallas_blocked) >= self.MAX_FAILURE_KEYS:
                self._pallas_blocked.pop(next(iter(self._pallas_blocked)))
            self._pallas_blocked[(template, batch_key)] = time.monotonic()
            self.pallas_fallbacks += 1
            for pk in [pk for pk in self._pipelines
                       if pk[0] == template and pk[5] != "off"]:
                self._pipelines.pop(pk)

    def _is_quarantined(self, template, batch_key) -> bool:
        with self._lock:
            key = (template, batch_key)
            ts = self._quarantined.get(key)
            if ts is None:
                return False
            if time.monotonic() - ts >= self.QUARANTINE_TTL_S:
                # half-open: after the cooldown the next launch probes the
                # device again with a fresh strike count — two more
                # failures re-quarantine for another window
                self._quarantined.pop(key, None)
                self._pipeline_failures.pop(key, None)
                return False
            return True

    def reset_quarantine(self) -> None:
        """Operational reset (tests / admin): forget failure history."""
        with self._lock:
            self._pipeline_failures.clear()
            self._quarantined.clear()
            self._pallas_blocked.clear()

    def evict_segment_dir(self, seg_dir: str) -> int:
        """Evict every cached batch whose key contains ``seg_dir`` — the
        tier-demotion hook (server/tiering.py): a segment leaving the hot
        tier must free its HBM blocks NOW, not at LRU depth. Batches a
        dispatched launch still pins defer to _release_launch via the
        poisoned set, exactly like the device-failure eviction path.
        Returns the number of batches dropped immediately."""
        with self._lock:
            keys = [k for k in self._batches if seg_dir in k]
        return sum(1 for k in keys if self._evict_batch(k))

    def _evict_batch(self, key) -> bool:
        """Drop the implicated BatchContext after a device failure so a
        retry re-uploads fresh buffers (RESOURCE_EXHAUSTED usually means
        this batch's blocks are what needs freeing). Batches other
        launches still pin are deferred to _release_launch via the
        poisoned set."""
        with self._lock:
            if key in self._inflight_launches:
                self._poisoned_batches.add(key)
                return False
            dropped = self._batches.pop(key, None) is not None
            if dropped:
                self.batch_evictions += 1
            # a device failure taints anything derived from the batch's
            # buffers: cached partials go with it either way
            self._drop_partials_for_batch(key)
            return dropped

    def on_fetch_device_error(self, e, template, batch_key,
                              used_pallas: bool = False) -> None:
        """InflightLaunch.fetch error hook: a device-runtime failure on
        the blocking fetch counts toward the quarantine breaker, marks
        the batch for eviction, and converts to DeviceUnsupported — the
        engine then re-runs THIS query's batch on the host through its
        fallback gate (a dispatched flight can't be relaunched). When the
        failing pipeline was the Pallas form, the failure blocks only the
        Pallas rung — the NEXT query on this (template, batch) compiles
        the XLA scatter form and stays on device, and no host-quarantine
        strike is recorded. Non-device errors return so the caller
        re-raises the original."""
        if not _is_device_runtime_error(e):
            return
        # a coalesced cohort re-raises ONE shared exception to every
        # member: count the failure event once, not once per member —
        # otherwise a single transient fault on a 2+-member cohort trips
        # the 2-strike quarantine instantly
        if not getattr(e, "_pinot_failure_counted", False):
            try:
                e._pinot_failure_counted = True
            except Exception:  # noqa: BLE001 — slotted exceptions
                pass
            if used_pallas:
                with self._lock:
                    self.launch_failures += 1
                self._block_pallas(template, batch_key)
                self._evict_batch(batch_key)
                log.warning(
                    "pallas pipeline fetch failed (%s: %s); batch "
                    "evicted, XLA scatter rung takes over — this query "
                    "falls back to host", type(e).__name__, e)
            else:
                quarantined = self._record_device_failure(template,
                                                          batch_key)
                self._evict_batch(batch_key)
                log.warning(
                    "device fetch failed (%s: %s); batch evicted%s — host "
                    "fallback", type(e).__name__, e,
                    ", pipeline QUARANTINED to host" if quarantined else "")
        raise DeviceUnsupported(
            f"device fetch failed ({type(e).__name__}); host fallback"
        ) from e

    @staticmethod
    def _fault_target(q) -> str:
        """Stable per-query-shape label the fault harness matches
        ``target`` filters against (lets a chaos test poison ONE
        template while others keep running on device)."""
        bits = [q.table_name or ""]
        for a in (q.aggregations() or ()):
            arg = a.args[0].name if a.args and a.args[0].is_identifier \
                else ""
            bits.append(f"{a.name}({arg})")
        bits.extend(g.name for g in (q.group_by or ()) if g.is_identifier)
        return ":".join(bits)

    def _make_resolve(self, bufs_dev, layout, tracer=None, flight=None):
        """fetch-phase closure shared by solo and cohort launches: ONE
        blocking device_get of the dispatched packed buffer, observability
        accounting under the lock, unpack by the precomputed layout.

        The blocking wait always splits into a KERNEL wait
        (block_until_ready — remaining device compute since dispatch) and
        a LINK wait (device_get — the host transfer): the split feeds the
        ALWAYS-ON roofline accounting (ISSUE 11 — achieved GB/s needs
        kernel-ms without tracing armed), and ``tracer`` (the dispatching
        query's, cohorts: the LEADER's) additionally records the pair as
        spans — the waterfall's kernel-ms vs link-ms separation. The
        untraced overhead is one extra no-op call on an already-complete
        buffer.

        ``flight``: the launch's roofline flight dict (None = no
        accounting, e.g. the bench's profile captures); filled with the
        per-flight record via _note_flight after the unpack."""
        def resolve():
            import time as _time

            if faults.ACTIVE:
                faults.inject("device.fetch")
            _t_get = _time.perf_counter()
            if tracer is not None:
                with trace_span("kernel", tracer):
                    jax.block_until_ready(bufs_dev)
            else:
                jax.block_until_ready(bufs_dev)
            _t_kernel = _time.perf_counter()
            if tracer is not None:
                with trace_span("link", tracer):
                    bufs = jax.device_get(bufs_dev)
            else:
                bufs = jax.device_get(bufs_dev)
            # blocking wait = link round trip + kernel; bench subtracts it
            # from wall time for a MEASURED host_ms (floor-subtraction
            # overstated host work by the link's RTT variance)
            _t_link = _time.perf_counter()
            wait = _t_link - _t_get
            bufs = {k: np.asarray(v) for k, v in bufs.items()}
            fetched = sum(v.nbytes for v in bufs.values())
            with self._lock:
                self.last_get_wait_s = wait
                # observability: what actually crossed the host link
                self.fetch_bytes_total += fetched
                self.fetch_leaves_total += len(bufs)
            self.metrics.time_ms("deviceFetchMs", wait * 1e3)
            outs = _unpack_outs(bufs, layout)
            if flight is not None:
                self._note_flight(flight, outs, fetched,
                                  _t_kernel - _t_get, _t_link - _t_kernel)
            return outs

        return resolve

    # ---- kernel roofline accounting (ISSUE 11) ---------------------------
    @staticmethod
    def _pipeline_label(template, blockskip: bool, trim,
                        pallas: bool = False, fused: bool = False) -> str:
        """Human-stable per-pipeline label the roofline aggregates key on:
        the template SHAPE plus the compile-affecting execution modes —
        coarse on purpose (per-template keys would fragment the stats
        into one-row buckets per literal-free query shape). The Pallas
        scatter tier and the fused filter+gather+aggregate form carry
        their own suffixes so hbm_stats()["roofline"] and EXPLAIN
        ANALYZE's %-of-HBM-peak line attribute each kernel correctly."""
        label = template[0]
        if blockskip:
            label += "+bskip"
        if fused:
            label += "+fused"
        if pallas:
            label += "+pallas"
        if trim is not None:
            label += "+trim"
        return label

    def _new_flight(self, label: str, cache_hit: bool = False,
                    fused: bool = False) -> dict:
        """Per-launch roofline flight record skeleton. ``data_bytes`` /
        ``zone_bytes`` are the static cost model's inputs (filled after
        the column gather); the resolve fills timings and the final
        record via _note_flight. ``fused``: the block-skip gather runs
        inside the fused Pallas kernel — the bytes-moved model must not
        charge the (B, R) gather-buffer round trip the XLA form pays."""
        return {"label": label, "cache_hit": cache_hit, "fused": fused,
                "data_bytes": 0, "zone_bytes": 0, "record": None}

    def _note_flight(self, flight: dict, outs: dict, fetched_bytes: int,
                     kernel_s: float, link_s: float) -> None:
        """Fold one resolved flight into the roofline accounting: the
        modeled bytes (column planes at their ColPlan widths, data planes
        scaled by the block-skip gather ratio the kernel reported, plus
        the packed fetch buffer) over the measured kernel wall → achieved
        GB/s, compared against the once-probed HBM peak. Cache hits (no
        kernel ran) count separately and never feed the GB/s histogram."""
        from pinot_tpu.ops import roofline as rl

        try:
            cache_hit = bool(flight.get("cache_hit"))
            ratio = 1.0
            skip_obs = None  # measured selectivity (skip path only)
            bt, bs = outs.get("blocks_total"), outs.get("blocks_scanned")
            if bt is not None and bs is not None:
                total_b = float(np.sum(np.asarray(bt)))
                if total_b > 0:
                    ratio = min(1.0, float(np.sum(np.asarray(bs))) / total_b)
                    skip_obs = ratio
            # block-skip gather-buffer round trip: the XLA form
            # materializes the gathered (B, R) planes in HBM (one write +
            # one read of every gathered byte) before the filter runs;
            # the fused Pallas kernel streams candidate blocks straight
            # into VMEM, so it must NOT be charged for the eliminated
            # round trip (ISSUE 15 bytes-moved model fix)
            gather_bytes = 0
            if ratio < 1.0 and not flight.get("fused"):
                gather_bytes = int(2 * flight["data_bytes"] * ratio)
            bytes_moved = 0 if cache_hit else int(
                flight["zone_bytes"] + flight["data_bytes"] * ratio
                + gather_bytes + fetched_bytes)
            kernel_ms = kernel_s * 1e3
            link_ms = link_s * 1e3
            rec = {"kernel": flight["label"],
                   "bytesMoved": bytes_moved,
                   "bytesFetched": int(fetched_bytes),
                   "kernelMs": round(kernel_ms, 3),
                   "linkMs": round(link_ms, 3),
                   "cacheHit": cache_hit}
            if gather_bytes:
                rec["gatherBytes"] = gather_bytes
            gbps = None
            if not cache_hit and kernel_s > 1e-9:
                gbps = bytes_moved / kernel_s / 1e9
                rec["gbps"] = round(gbps, 3)
                # the probe runs ONCE per process, lazily, on the first
                # accounted flight (~tens of ms; warm queries never pay)
                peak = rl.hbm_peak_gbps()
                pct = rl.pct_of_peak(gbps, peak)
                if pct is not None:
                    rec["peakGbps"] = round(peak, 1)
                    rec["pctOfPeak"] = pct
            flight["record"] = rec
            with self._lock:
                agg = self._roofline.setdefault(
                    flight["label"],
                    {"queries": 0, "cache_hits": 0, "bytes_moved": 0,
                     "kernel_ms": 0.0, "link_ms": 0.0})
                agg["queries"] += 1
                agg["link_ms"] += link_ms
                if cache_hit:
                    agg["cache_hits"] += 1
                else:
                    agg["bytes_moved"] += bytes_moved
                    agg["kernel_ms"] += kernel_ms
            if gbps is not None:
                self.metrics.observe("deviceKernelGbps", gbps)
            # plan-advisor feedback: measured skip selectivity (only the
            # skip path emits blocks_total>0 — the dense form measures
            # nothing, by design) and per-rung achieved GB/s keyed by the
            # pipeline label (advisor splits off the +pallas suffix)
            adv_key = flight.get("adv_key")
            if adv_key and self.advisor is not None and not cache_hit:
                self.advisor.observe(
                    adv_key, skip_ratio=skip_obs,
                    label=flight["label"], gbps=gbps)
        except Exception:  # noqa: BLE001 — accounting must never fail a fetch
            log.exception("roofline flight accounting failed")

    def roofline_stats(self) -> dict:
        """Per-pipeline roofline snapshot: modeled bytes / kernel wall →
        achieved GB/s per label, against the probed peak (None until the
        first accounted flight triggers the probe — reading stats never
        spends device time on the probe itself)."""
        from pinot_tpu.ops import roofline as rl

        with self._lock:
            aggs = {k: dict(v) for k, v in self._roofline.items()}
        peak = rl.peak_if_probed()
        kernels = {}
        for label, agg in aggs.items():
            entry = dict(agg)
            entry["kernel_ms"] = round(entry["kernel_ms"], 3)
            entry["link_ms"] = round(entry["link_ms"], 3)
            if agg["kernel_ms"] > 0:
                gbps = agg["bytes_moved"] / (agg["kernel_ms"] / 1e3) / 1e9
                entry["gbps"] = round(gbps, 3)
                pct = rl.pct_of_peak(gbps, peak)
                if pct is not None:
                    entry["pct_of_peak"] = pct
            kernels[label] = entry
        return {"peak_gbps": round(peak, 1) if peak else None,
                "kernels": kernels}

    # ---- template build --------------------------------------------------
    def _agg_template(self, i: int, a: Expression, ctx: BatchContext, params, counter):
        name = a.name
        if name in ("distinctcountbitmap", "segmentpartitioneddistinctcount"):
            name = "distinctcount"
        if name not in DEVICE_AGGS:
            raise DeviceUnsupported(f"aggregation {name} not on device")
        if name == "count":
            return ("count", None, None)
        if name == "distinctcount":
            arg = a.args[0]
            if not arg.is_identifier or ctx.encoding(arg.name) != Encoding.DICT:
                raise DeviceUnsupported("distinctcount needs a dict column")
            return ("distinctcount", arg.name, ctx.cardinality(arg.name))
        if name == "distinctcounthll":
            arg = a.args[0]
            if not arg.is_identifier or ctx.encoding(arg.name) != Encoding.DICT:
                raise DeviceUnsupported("distinctcounthll device path needs a dict column")
            spec = aggspec.make_spec(a)
            return ("distinctcounthll", arg.name, spec.log2m)
        if name == "hllmerge":
            arg = a.args[0]
            if not arg.is_identifier or ctx.encoding(arg.name) != Encoding.DICT:
                raise DeviceUnsupported("hllmerge needs a dict BYTES column")
            spec = aggspec.make_spec(a)
            width = ctx.bytes_width(arg.name)
            if width != spec.m:
                raise DeviceUnsupported(
                    f"hllmerge plane width {width} != m {spec.m}")
            return ("hllmerge", arg.name, spec.log2m)
        if name in ("firstwithtime", "lastwithtime"):
            # value + time expression pair; STRING dataType can't ride the
            # float64 value plane — build_expr already rejects non-numeric
            # dict columns, sending those to the host path
            vt = build_expr(a.args[0], ctx, params, counter)
            tt = build_expr(a.args[1], ctx, params, counter)
            return (name, (vt, tt), "pair")
        # numeric-arg aggregations
        argt = build_expr(a.args[0], ctx, params, counter)
        rpb = None
        nplanes = None
        if name in ("sum", "avg"):
            # metadata interval arithmetic sizes the two-stage scatter blocks
            # AND the matmul kernel's byte planes (ops/groupby_mm.py)
            bounds = expr_bounds(a.args[0], ctx)
            if bounds is not None:
                from pinot_tpu.ops import groupby_mm as mm

                rpb = agg_ops.rows_per_block_for(max(abs(bounds[0]), abs(bounds[1])))
                nplanes = mm.int_planes_needed(bounds[0], bounds[1])
                import math

                off = math.floor(bounds[0])
                params[f"off{i}"] = jnp.int64(off)
                sig = params.get("__hostsig__")
                if sig is not None:
                    sig.append((f"off{i}", "<i8", (),
                                np.int64(off).tobytes()))
            return (name, argt, (nplanes, rpb))
        return (name, argt, rpb)

    def launch(self, q: QueryContext, segments,
               final: bool = False, alive=None,
               tracer=None, reduce_mode=None) -> InflightLaunch:
        """LAUNCH phase: template build + column gather + NON-BLOCKING XLA
        dispatch (JAX dispatch is async; only device_get blocks). Returns
        an InflightLaunch whose ``fetch()`` resolves the packed output
        buffer — N concurrent queries overlap their link round trips
        instead of serializing them. Under concurrency, same-cohort
        launches (one batch, one template, same param shapes) coalesce
        into a single vmapped dispatch (engine/inflight.py). Raises
        DeviceUnsupported for shapes the device path doesn't cover.

        ``alive``: optional per-segment bool sequence from a caller that
        already ran the stats pruner (engine.execute_segments_async) —
        skips re-deriving Level-1 verdicts here. None = derive them.

        ``tracer``: the query's explicit Tracer (common/trace.py) —
        carried by reference through the handle and the fetch closure so
        spans recorded on OTHER threads (deferred fetch, cohort leader)
        land on THIS query's trace, not a thread-local's.

        ``reduce_mode``: None | "partial" | "terminal" — whether this
        batch is the SOLE partial of its execution (engine decides), and
        whether anything merges after it. Gates the on-device final
        reduce (ops/device_reduce.py): trimming a non-sole partial would
        lose group contributions a later merge needs."""
        t_launch = time.perf_counter()
        aggs = q.aggregations()
        if q.distinct:
            # DISTINCT == group-by over the select columns with no aggs:
            # the dense/sorted group machinery yields the distinct combos
            # (the reference's DistinctAggregationFunction is the same
            # group-keys-only special case)
            if aggs:
                raise DeviceUnsupported("DISTINCT over aggregations")
            aggs = []
        elif not aggs:
            raise DeviceUnsupported("selection on host path")
        for a in aggs:
            if a.name not in DEVICE_AGGS:
                raise DeviceUnsupported(f"agg {a.name}")
        for s in segments:
            if not segment_device_eligible(s):
                raise DeviceUnsupported("mutable/upsert segment needs host scan path")

        # the batch stays pinned for the WHOLE launch — template build and
        # column materialization included, not just the dispatched flight
        # (retain=True takes the pin atomically with the cache insert)
        batch_key = self._batch_key(segments)
        last_err = None
        xla_attempts = 0
        # fallback ladder: Pallas form → XLA scatter form (still on
        # device) → one XLA retry → host. A Pallas-only failure never
        # leaves the device (ISSUE 15 quarantine rung); host-quarantine
        # strikes count XLA-rung failures only.
        for _attempt in range(3):
            ctx = self.batch_for(segments, retain=True)
            tpl_box: list = []
            try:
                handle = self._launch_pinned(q, ctx, batch_key, segments,
                                             aggs, final, alive, tpl_box,
                                             tracer, reduce_mode)
                handle.tracer = tracer
                self.metrics.time_ms(
                    "deviceLaunchMs",
                    (time.perf_counter() - t_launch) * 1e3)
                return handle
            except BaseException as e:
                self._release_launch(batch_key)
                if not _is_device_runtime_error(e):
                    raise
                # device-runtime failure (XlaRuntimeError /
                # RESOURCE_EXHAUSTED, real or injected): evict the
                # implicated batch so the retry re-uploads fresh buffers
                last_err = e
                tpl = tpl_box[0] if tpl_box else None
                pmode_used = tpl_box[1] if len(tpl_box) > 1 else "off"
                if pmode_used != "off" and tpl is not None:
                    # Pallas rung: block the Pallas form for this
                    # (template, batch) and retry the XLA scatter form on
                    # device — no host-quarantine strike
                    with self._lock:
                        self.launch_failures += 1
                    self._block_pallas(tpl, batch_key)
                    self._evict_batch(batch_key)
                    log.warning(
                        "pallas pipeline failed (%s: %s); batch evicted, "
                        "dropping to the XLA scatter rung on device",
                        type(e).__name__, e)
                    continue
                quarantined = False
                if tpl is not None:
                    quarantined = self._record_device_failure(
                        tpl, batch_key)
                else:
                    with self._lock:
                        self.launch_failures += 1
                self._evict_batch(batch_key)
                xla_attempts += 1
                if xla_attempts <= 1 and not quarantined:
                    log.warning(
                        "device launch failed (%s: %s); batch evicted, "
                        "retrying once on device", type(e).__name__, e)
                    continue
                break
        raise DeviceUnsupported(
            f"device launch failed after retry "
            f"({type(last_err).__name__}: {last_err}); host fallback"
        ) from last_err

    def _launch_pinned(self, q, ctx, batch_key, segments, aggs,
                       final, alive_hint=None, tpl_box=None,
                       tracer=None, reduce_mode=None) -> InflightLaunch:
        params: dict = {}
        # host-bytes side channel: engine/params.py _slot records each
        # literal's (dtype, shape, bytes) here BEFORE upload, so the
        # partials-cache digest never reads a device array back. Only
        # installed when the cache could actually be consulted — a big
        # IN-list/regex LUT would otherwise be memcpy'd per launch just
        # to be thrown away
        opts = q.options_ci()
        cacheable = (self.partials_cache_enabled
                     and not self.profile_enabled
                     and bool_option(opts, "usepartialscache", None)
                     is not False)
        # feedback-driven plan advisor (engine/advisor.py): keyed by the
        # PR-7 literal-free template key. SET useAdvisor=false bypasses
        # BOTH the reads (advice) and the writes (observation) — a
        # bypassed query leaves zero memo effect, so advisor-off runs are
        # bit-exact against advisor-on by construction.
        adv_key = None
        adv_notes: list = []
        if self.advisor is not None and not self.profile_enabled \
                and advisor_enabled(opts):
            from pinot_tpu.broker.querylog import template_key

            adv_key = template_key(q)
        if cacheable:
            params["__hostsig__"] = []
        counter = [0]

        filter_tpl = ("true",) if q.filter is None else build_filter(
            q.filter, ctx, params, counter
        )

        group_cols, group_cards = (), ()
        group_exprs = q.select_expressions if q.distinct else q.group_by
        if group_exprs:
            gcols = []
            gcards = []
            for g in group_exprs:
                if not g.is_identifier or ctx.encoding(g.name) != Encoding.DICT:
                    raise DeviceUnsupported("group-by must be dict columns on device")
                gcols.append(g.name)
                gcards.append(ctx.cardinality(g.name))
            group_cols, group_cards = tuple(gcols), tuple(gcards)
            total = 1
            for c in group_cards:
                total *= c
        elif q.distinct:
            raise DeviceUnsupported("DISTINCT needs dict columns on device")

        agg_tpls = tuple(
            self._agg_template(i, a, ctx, params, counter) for i, a in enumerate(aggs)
        )
        shape = "groupby" if group_cols else "agg"
        if group_cols and total > MAX_DENSE_GROUPS:
            # sort-based high-cardinality regime (MAP_BASED analog): no
            # dense accumulators, so only the additive/extremal aggs fit
            if total >= (1 << 62):
                raise DeviceUnsupported(
                    f"combined group key overflows int64 ({total})")
            # per-shard radix tables are KEYED (skeys + neutral empty-slot
            # fills), so the mesh combine merges them by key
            # (parallel/mesh.py _combine_sorted_table via
            # ops/radix_groupby.py merge_tables) — no dense psum alignment
            # needed; multi-chip high-card no longer routes to the host
            for a in aggs:
                if a.name not in SORTED_AGGS:
                    raise DeviceUnsupported(
                        f"agg {a.name} not on the sorted group-by path")
            shape = "groupby_sorted"
        for name, argt, extra in agg_tpls:
            if shape == "groupby" and name in (
                    "distinctcount", "distinctcounthll", "hllmerge"):
                cells = extra if name == "distinctcount" else (1 << extra)
                for c in group_cards:
                    cells *= c
                if cells > MAX_PRESENCE_CELLS:
                    raise DeviceUnsupported(f"{name} per-group state too large ({cells})")
        sorted_k = min(self.num_groups_limit, MAX_SORTED_GROUPS) \
            if shape == "groupby_sorted" else 0
        # final only changes sketch outputs; don't fork the jit cache for
        # templates where it is a no-op
        final = final and any(
            name in ("distinctcount", "distinctcounthll", "hllmerge")
            for name, _, _ in agg_tpls
        )
        template = (shape, filter_tpl, group_cols, group_cards, agg_tpls,
                    sorted_k, final)
        if tpl_box is not None:
            # publish the template to launch()'s recovery handler so a
            # device-runtime failure below is counted per-(template, batch)
            tpl_box.append(template)
        # Pallas scatter tier (ISSUE 15): env kill switch + per-query SET
        # usePallas opt-out + the quarantine XLA rung — a blocked
        # (template, batch) pair compiles the pallas_mode="off" variant
        # and stays ON DEVICE
        pmode = self._resolve_pallas(opts)
        if pmode != "off" and self._is_pallas_blocked(template, batch_key):
            pmode = "off"
        # failure ATTRIBUTION for launch()'s fallback ladder: a template
        # that routes nothing to the tier must not charge its failures
        # to the Pallas rung (the "XLA retry" would recompile a
        # byte-identical pipeline and skip the host-quarantine strike).
        # Widths aren't planned yet, so this conservative estimate is
        # refined once the width plan and fused eligibility exist.
        routes_pallas = pmode != "off" and _template_uses_pallas(
            template, None, False, pmode, ctx.S * ctx.pad_to)
        if tpl_box is not None:
            tpl_box.append(pmode if routes_pallas else "off")
        if self._is_quarantined(template, batch_key):
            # circuit breaker: this (template, batch) failed on device
            # QUARANTINE_AFTER times — route it to the host path while
            # every other template keeps running on device
            raise DeviceUnsupported(
                "pipeline quarantined to host after repeated device "
                "failures")
        if faults.ACTIVE:
            faults.inject("device.launch", target=self._fault_target(q))

        # Level-2 eligibility: the filter has interval structure the zone
        # maps can act on, the batch is block-aligned, and the query didn't
        # opt out (SET useBlockSkip = false — the force-dense form the
        # differential parity suite compares against)
        use_bs, zone_cols = False, set()
        if filter_tpl[0] not in ("true", "false") \
                and bool_option(opts, "useblockskip", None) is not False \
                and ctx.pad_to % bs_ops.BLOCK_ROWS == 0:
            prunable, zone_cols = bs_ops.prunable_columns(filter_tpl)
            use_bs = prunable and bool(zone_cols)
        # advisor: skip-vs-dense and candidate-bound selection from the
        # template's MEASURED selectivity. ``use_bs`` carries the choice
        # as its truthiness: False = dense, True = static CAND_FRACTION,
        # int>1 = tightened fraction — the pipeline key/entry/label all
        # fork on the value, so each advised form compiles once. Either
        # way the results are bit-exact: the dense form and the skip form
        # agree by the differential suite, and an over-tight bound
        # overflows onto the in-kernel dense fallback.
        if use_bs and adv_key is not None:
            frac, note = self.advisor.advise_blockskip(
                adv_key, bs_ops.CAND_FRACTION)
            if frac == 0:
                use_bs, zone_cols = False, set()
            elif frac != bs_ops.CAND_FRACTION:
                use_bs = frac
            if note:
                adv_notes.append(note)

        # Level-1 launch-time segment skip: evaluate the filter tree against
        # per-segment column stats (min/max, dictionary membership, bloom
        # for EQ/IN) with the broker pruner's conservative tri-state
        # semantics. The result is a per-query VECTOR PARAM, not a batch
        # key: pruned members stay in the (S, L) batch, dead.
        if alive_hint is not None:
            alive = np.asarray(alive_hint, dtype=bool)
        else:
            alive = np.ones(ctx.S, dtype=bool)
            if q.filter is not None:
                pruner = self._stats_pruner
                if pruner is None:
                    from pinot_tpu.engine.engine import SegmentPruner

                    pruner = self._stats_pruner = SegmentPruner()
                for i, s in enumerate(segments):
                    alive[i] = not pruner.prune(q, s)
        params["ps_alive"] = jnp.asarray(alive)

        # SET useSortedProjection=false keeps the per-query in-pipeline
        # sort (the cold-scan measurement form); default taps the batch's
        # cached sorted projection for filterless terminal HLL
        sorted_proj_ok = bool_option(
            opts, "usesortedprojection", None) is not False
        needed = self._needed_columns(filter_tpl) | set(group_cols)
        if use_bs:
            for zc in zone_cols:
                needed.add(bs_ops.ZLO + zc)
                needed.add(bs_ops.ZHI + zc)
        for name, argt, extra in agg_tpls:
            if name == "distinctcount":
                needed.add(argt)
            elif name == "distinctcounthll":
                if (shape == "groupby" and filter_tpl == ("true",)
                        and sorted_proj_ok
                        and _hll_sort_eligible(final, self.mesh is None,
                                               total, extra, self.mm_mode)):
                    needed.add(f"sk::{argt}::{extra}")
                else:
                    needed.add("hh::" + argt)
            elif name == "hllmerge":
                needed.add("bp::" + argt)
            elif name in ("firstwithtime", "lastwithtime"):
                needed |= self._needed_columns(argt[0])
                needed |= self._needed_columns(argt[1])
            elif argt is not None:
                needed |= self._needed_columns(argt)
        if not needed:  # COUNT(*) no filter: one column carries the shape
            needed.add(segments[0].column_names()[0])

        # per-column width plan (engine/params.py ColPlan): part of the
        # pipeline cache key — narrow dict-id planes, frame-of-reference
        # raw/decoded planes, and the opt-in sub-byte tier each compile
        # their own template form, and cohort coalescing keys on the entry
        # so same-plan queries still stack. FOR offsets ride as per-batch
        # "fo::<key>" params (replicated on the mesh, stacked per cohort
        # member) — the offset VALUE stays out of the compiled template.
        widths = {}
        host_sigs = params.pop("__hostsig__", [])
        for c in sorted(needed):
            if c.startswith(("dv::",)) or not c.startswith(
                    (bs_ops.ZLO, bs_ops.ZHI, "sk::", "hh::", "bp::", "mv::")):
                plan = ctx.width_plan(c)
                widths[c] = plan.sig()
                if plan.offset is not None:
                    fo = np.asarray(plan.offset, dtype=np.dtype(plan.wide))
                    params["fo::" + c] = jnp.asarray(fo)
                    host_sigs.append(("fo::" + c, fo.dtype.str, (),
                                      fo.tobytes()))
        wsig = tuple(sorted(widths.items()))

        # on-device final reduce (ops/device_reduce.py): plan the ORDER
        # BY trim when this batch is the sole partial of its execution.
        # The spec is static (pow2 bound + order signature) and keys the
        # pipeline entry; the exact keep count rides as the tr_k param.
        trim = None
        adv_trim_keep = None
        if reduce_mode is not None and shape in ("groupby",
                                                 "groupby_sorted"):
            # advisor: group_trim_size tightened toward the template's
            # observed group count (trim_bound still floors the keep at
            # the reference's 5*(offset+limit), so parity semantics
            # hold; the tightened bound covers every observed group with
            # headroom — overflow observations stand the advice down)
            gts = self.group_trim_size
            if adv_key is not None:
                gts2, note = self.advisor.advise_trim(adv_key, gts)
                if note:
                    gts = gts2
                    adv_notes.append(note)
            table_len = total if shape == "groupby" else sorted_k
            trim = dr_ops.plan_trim(q, group_exprs, aggs, shape, table_len,
                                    reduce_mode, gts)
            if trim is not None:
                tr_k = np.int32(dr_ops.trim_keep_count(
                    q, reduce_mode, gts))
                params["tr_k"] = jnp.asarray(tr_k)
                host_sigs.append(("tr_k", "<i4", (), tr_k.tobytes()))
                if adv_key is not None:
                    adv_trim_keep = int(tr_k)

        # advisor: Pallas-vs-XLA rung selection — demote to the XLA
        # scatter rung when BOTH rungs have measured GB/s for this
        # pipeline label and XLA measured meaningfully faster (the rungs
        # are differential-pinned, so the flip is bit-exact)
        if adv_key is not None and pmode != "off":
            prov_label = self._pipeline_label(template, use_bs, trim,
                                              pallas=True)
            pmode2, note = self.advisor.advise_pallas(adv_key, pmode,
                                                      prov_label)
            if note:
                pmode = pmode2
                adv_notes.append(note)

        pkey = self._pipeline_key(template, use_bs, wsig, trim, pmode)
        entry = self._pipeline_entry(template, agg_tpls, final, use_bs,
                                     widths, wsig, trim, pmode)
        # fused filter+gather+aggregate eligibility (label + bytes-moved
        # model): the plan walk is cheap and mirrors the one
        # build_pipeline compiled into the pipeline
        fused = False
        if pmode != "off" and use_bs and shape == "agg":
            from pinot_tpu.ops import pallas_scatter as ps_ops

            if bs_ops.BLOCK_ROWS == ps_ops.FUSED_BLOCK_ROWS:
                fplan = ps_ops.plan_fused(filter_tpl, agg_tpls, widths)
                fused = fplan is not None and ps_ops.fused_params_ok(
                    fplan, params)
        # refine the rung attribution now that the width plan and fused
        # eligibility are known (labels, handles, and launch()'s handler
        # all read the same verdict)
        routes_pallas = pmode != "off" and _template_uses_pallas(
            template, widths, fused, pmode, ctx.S * ctx.pad_to)
        if tpl_box is not None and len(tpl_box) > 1:
            tpl_box[1] = pmode if routes_pallas else "off"
        # roofline flight (ISSUE 11): always-on except under profile
        # capture (the bench's amortized kernel probe re-dispatches the
        # same launch and would pollute the per-query aggregates)
        flight = None if self.profile_enabled else self._new_flight(
            self._pipeline_label(template, use_bs, trim,
                                 pallas=routes_pallas, fused=fused),
            fused=fused)
        if flight is not None and adv_key is not None:
            # _note_flight's observation hook: measured skip selectivity
            # and per-rung GB/s feed the template's memo at resolve time
            flight["adv_key"] = adv_key

        # device partials cache: a repeat execution — same pipeline, same
        # batch, same literal/ps_alive/param VALUES — skips the gather +
        # dispatch + kernel and re-fetches the cached packed buffer (one
        # link RTT of trimmed bytes, zero device work)
        cache_key = None
        if cacheable and alive.any():
            h = hashlib.blake2b(digest_size=16)
            h.update(repr(sorted(
                (k, d, s) for k, d, s, _b in host_sigs)).encode())
            for _k, _d, _s, b in sorted(host_sigs,
                                        key=lambda e: (e[0], e[1], e[2])):
                h.update(b)
            h.update(b"ps_alive")
            h.update(alive.tobytes())
            cache_key = (pkey, batch_key, h.digest())
            hit = self._partials_get(cache_key)
            if hit is not None:
                bufs_dev, clayout = hit
                if flight is not None:
                    flight["cache_hit"] = True
                resolve = self._make_resolve(bufs_dev, clayout, tracer,
                                             flight)
                handle = InflightLaunch(self, q, ctx, template, aggs,
                                        batch_key, resolve)
                handle.cache_hit = True
                handle.flight = flight
                handle.used_pallas = routes_pallas
                handle.adv_key = adv_key
                handle.advisor_notes = adv_notes
                handle.adv_trim_keep = adv_trim_keep
                return handle
        cols = {}
        with trace_span("gather", tracer):
            for c in sorted(needed):
                if c.startswith(bs_ops.ZLO):
                    cols[c] = ctx.zone_map(c[len(bs_ops.ZLO):])[0]
                elif c.startswith(bs_ops.ZHI):
                    cols[c] = ctx.zone_map(c[len(bs_ops.ZHI):])[1]
                elif c.startswith("dv::"):
                    cols[c] = ctx.decoded_column(c[4:])
                elif c.startswith("sk::"):
                    _, colname, l2m = c.split("::")
                    cols[c] = ctx.sorted_hll_keys(
                        group_cols, group_cards, colname, int(l2m))
                elif c.startswith("hh::"):
                    cols[c] = ctx.prehashed_column(c[4:])
                elif c.startswith("bp::"):
                    cols[c] = ctx.bytes_plane_column(c[4:])
                elif c.startswith("mv::"):
                    cols[c] = ctx.mv_column(c[4:])
                else:
                    cols[c] = ctx.column(c)
        if os.environ.get("PINOT_TPU_WIDTH_AUDIT", "") not in ("", "0"):
            _width_audit(ctx, cols, widths)

        n_docs = ctx.n_docs_dev
        if self.mesh is not None:
            from pinot_tpu.parallel.mesh import pad_to_multiple

            cols, n_docs, params, _ = pad_to_multiple(
                cols, n_docs, params, self.mesh.devices.size
            )
        if flight is not None:
            # static cost-model inputs: plane bytes at their ColPlan
            # widths (the arrays ARE stored narrow), split data vs zone —
            # the block-skip form reads zone planes fully but data planes
            # only for gathered blocks (_note_flight applies the ratio
            # the kernel reports)
            for ck, cv in cols.items():
                nb = int(getattr(cv, "nbytes", 0))
                if ck.startswith((bs_ops.ZLO, bs_ops.ZHI)):
                    flight["zone_bytes"] += nb
                else:
                    flight["data_bytes"] += nb

        # ONE packed buffer crosses the host link: device_get fetches tree
        # leaves serially, so on a high-RTT link every leaf would be a full
        # round trip (measured ~100ms each on the bench tunnel). The layout
        # is shape-deterministic per (template, batch shapes) — eval_shape
        # traces without touching the device.
        lkey = (ctx.S, next(
            v for k, v in cols.items()
            if not k.startswith(("sk::", bs_ops.ZLO, bs_ops.ZHI))).shape[1])
        layout = entry["layouts"].get(lkey)
        if layout is None:
            layout = _out_layout(
                jax.eval_shape(entry["inner"], cols, n_docs, params))
            with self._lock:
                entry["layouts"][lkey] = layout
        if not alive.any():
            # FULLY pruned: skip the device launch (and its link round
            # trip) entirely — synthesize the outputs host-side from the
            # layout with the kernels' own all-masked fills, so pruned vs
            # force-dense results stay bit-identical
            synth = _neutral_outs(layout)
            return InflightLaunch(self, q, ctx, template, aggs, batch_key,
                                  lambda: synth)
        with trace_span("dispatch", tracer):
            resolve = self._dispatch(
                entry, batch_key, cols, n_docs, params, lkey, layout, tracer,
                cache_key, flight, adv_key=adv_key, adv_notes=adv_notes)
        handle = InflightLaunch(self, q, ctx, template, aggs, batch_key,
                                resolve)
        handle.flight = flight
        handle.used_pallas = routes_pallas
        handle.adv_key = adv_key
        handle.advisor_notes = adv_notes
        handle.adv_trim_keep = adv_trim_keep
        return handle

    # ---- dispatch: solo vs coalesced -------------------------------------
    def _pipeline_key(self, template, blockskip, wsig, trim,
                      pallas: str = "off") -> tuple:
        """The ONE composition of the compiled-pipeline cache key — the
        partials cache namespaces its entries by the same tuple, so a
        future compile-affecting component added here automatically
        splits both caches together. ``pallas`` keys the scatter-tier
        mode so the Pallas form and the XLA scatter form (the
        PINOT_TPU_PALLAS=0 / SET usePallas=false escape hatch and the
        quarantine XLA rung) coexist compiled in one process."""
        return (template, self.mm_mode, blockskip, wsig, trim, pallas)

    @staticmethod
    def _post_chain(template, agg_tpls, final, trim):
        """Post-combine transform list, applied in order AFTER the
        cross-shard combine: terminal sketch finalize (regs → estimates),
        then the device-reduce trim (full table → top-K rows). Shared by
        the solo inner fn and the cohort per-member post."""
        post_fns = []
        if final:
            post_fns.append(
                lambda outs, p, _t=agg_tpls: _finalize_sketch_outs(outs, _t))
        if trim is not None:
            post_fns.append(
                lambda outs, p, _tpl=template, _s=trim:
                dr_ops.apply_trim(outs, p, _tpl, _s))
        return tuple(post_fns)

    def _pipeline_entry(self, template, agg_tpls, final,
                        blockskip=False, widths=None,
                        wsig: tuple = (), trim=None,
                        pallas: str = "off") -> dict:
        """Compiled-pipeline cache entry for (template, mm_mode, blockskip,
        width-plan sig, trim sig): the solo jitted pipeline, the pre-pack
        inner fn (eval_shape layouts), the raw pipeline (cohort rebuilds
        compose vmap/mesh from it), and the layout caches. The width sig
        keys the entry because plane dtypes shape BOTH the compiled
        kernels and the packed output layouts (a uint8 MIN emits a uint8
        leaf); the trim sig keys it because the device reduce reshapes
        the output table to its static bound. Cohort coalescing keys on
        id(entry), so only same-width same-trim queries stack. Built
        under the executor lock so concurrent same-template launches
        share ONE entry."""
        pkey = self._pipeline_key(template, blockskip, wsig, trim,
                                  pallas)
        with self._lock:
            entry = self._pipelines.get(pkey)
            if entry is not None:
                return entry
            raw = build_pipeline(template, self.mm_mode,
                                 sorted_hll_ok=(self.mesh is None),
                                 blockskip=blockskip, widths=widths,
                                 pallas_mode=pallas)
            # cohorts vmap the pipeline over stacked member params, and a
            # vmapped lax.cond lowers to select — BOTH branches would run
            # for every member. Cohorts therefore ride the DENSE form;
            # per-member ps_alive still applies Level-1 segment pruning
            # inside the vmap, so members pruning different segment
            # subsets stay correct.
            raw_cohort = build_pipeline(
                template, self.mm_mode, sorted_hll_ok=(self.mesh is None),
                widths=widths, pallas_mode=pallas,
            ) if blockskip else raw
            if self.mesh is not None:
                from pinot_tpu.parallel.mesh import shard_pipeline

                sharded = shard_pipeline(raw, self.mesh)
            else:
                sharded = raw
            # sketch finalize and the device-reduce trim both run AFTER
            # the cross-shard combine (on replicated combined outs)
            post_fns = self._post_chain(template, agg_tpls, final, trim)
            if post_fns:
                def inner(cols, n_docs, params, _fn=sharded, _pfs=post_fns):
                    outs = _fn(cols, n_docs, params)
                    for pf in _pfs:
                        outs = pf(outs, params)
                    return outs
            else:
                inner = sharded
            pipeline = jax.jit(
                lambda cols, n_docs, params: _pack_outs(
                    inner(cols, n_docs, params))
            )
            entry = {
                "pipeline": pipeline, "inner": inner, "raw": raw_cohort,
                "agg_tpls": agg_tpls, "final": final,
                "template": template, "trim": trim, "pallas": pallas,
                "layouts": {}, "cohort": None, "cohort_layouts": {},
            }
            self._pipelines[pkey] = entry
            return entry

    def _dispatch(self, entry, batch_key, cols, n_docs, params, lkey, layout,
                  tracer=None, cache_key=None, flight=None, adv_key=None,
                  adv_notes=None):
        """Dispatch one query: through the coalescer when concurrency makes
        a cohort partner likely, else solo. Returns the resolve() closure
        the InflightLaunch fetch phase blocks on. Coalescing is disabled
        under profile capture (the bench must see per-query launches).

        ``tracer`` rides into the resolve closure: a solo launch's fetch
        spans land on the launching query's trace; a COHORT's shared
        fetch spans land on the leader's (whoever opened the window
        supplies the launch_fn, hence the tracer) — member queries still
        get their own fetch-phase span from InflightLaunch.fetch."""
        co = self.coalescer
        if (co is not None and not self.profile_enabled
                and co.should_window(self.inflight)):
            # cohort key: same pipeline entry + same batch + same column
            # set + same param shapes/dtypes → params stack along a
            # leading axis into one vmapped launch
            sig = tuple(sorted(
                (k, tuple(v.shape), str(v.dtype)) for k, v in params.items()))
            ckey = (id(entry), batch_key, lkey, tuple(sorted(cols)), sig)
            # advisor: cohort window sized from the template's OBSERVED
            # arrival cohesion (templates whose cohorts stay solo stop
            # paying the window wait; ones that reliably stack hold it
            # open longer), and every dispatched cohort's size feeds the
            # memo back via the launch closure
            window_s = None
            if adv_key is not None:
                w, note = self.advisor.advise_cohort_window(
                    adv_key, co.window_s)
                if note:
                    window_s = w
                    if adv_notes is not None:
                        adv_notes.append(note)

            def _launch(members, _ak=adv_key):
                if _ak is not None and self.advisor is not None:
                    self.advisor.observe(_ak, cohort=len(members))
                return self._cohort_launch(
                    entry, cols, n_docs, members, lkey, tracer, flight)

            cohort, idx = co.join(ckey, params, _launch, window_s=window_s)

            def resolve(_c=cohort, _i=idx):
                return _c.resolve_member(_i)

            # abandoned-handle hook (InflightLaunch.release): an
            # all-abandoned cohort still signals fetch_done so the next
            # stream window doesn't poll out its cap
            resolve.abandon = cohort.note_abandoned
            return resolve
        return self._solo_launch(entry, cols, n_docs, params, layout, tracer,
                                 cache_key, flight)

    def _solo_launch(self, entry, cols, n_docs, params, layout, tracer=None,
                     cache_key=None, flight=None):
        pipeline = entry["pipeline"]
        if self.profile_enabled:
            with self._lock:
                self._last_launch = (
                    pipeline, cols, n_docs, params,
                    sum(int(np.prod(v.shape, dtype=np.int64))
                        * v.dtype.itemsize for v in cols.values()),
                )
        bufs_dev = pipeline(cols, n_docs, params)  # async dispatch
        if cache_key is not None:
            # cache the dispatched buffer itself (immutable): the repeat
            # query fetches it again without gather/dispatch/kernel.
            # Cohort members never insert — their buffer interleaves the
            # whole cohort's rows
            self._partials_put(cache_key, bufs_dev, layout)
        return self._make_resolve(bufs_dev, layout, tracer, flight)

    def _cohort_launch(self, entry, cols, n_docs, members, lkey, tracer=None,
                       flight=None):
        """Leader side of a coalesced cohort: stack every member's params
        along a leading axis and dispatch ONE vmapped launch; the shared
        resolve() fetches ONE packed buffer for the whole cohort (each
        member then slices its row — engine/inflight.py _Cohort)."""
        if len(members) == 1:
            # window opened but nobody joined: the already-compiled solo
            # pipeline serves it — a size-1 vmapped variant would be a
            # whole extra compile of the template for nothing
            layout = entry["layouts"][lkey]
            base = self._solo_launch(entry, cols, n_docs, members[0], layout,
                                     tracer, flight=flight)
            return lambda: {k: v[None] for k, v in base().items()}
        pipeline_v, inner_v = self._cohort_pipeline(entry)
        # pad the cohort to the next power of two (repeating the last
        # member's params): jit re-specializes per stack size, and ragged
        # cohort sizes under churn would compile up to max_cohort variants
        # of the whole pipeline — pow2 bucketing caps that at
        # log2(max_cohort) for at most 2x padded lanes, and member slices
        # (idx < real size) never see the padding
        n_real = len(members)
        n_pad = 1 << (n_real - 1).bit_length()
        padded = list(members) + [members[-1]] * (n_pad - n_real)
        pstack = {k: jnp.stack([m[k] for m in padded])
                  for k in members[0]}
        # literal-free templates have EMPTY params; vmap needs at least one
        # batched leaf, so every cohort rides a synthetic member index
        # (templates index params by name — an extra key is never read)
        pstack["__member__"] = jnp.arange(n_pad, dtype=jnp.int32)
        ck = (lkey, n_pad)
        layout = entry["cohort_layouts"].get(ck)
        if layout is None:
            layout = _out_layout(
                jax.eval_shape(inner_v, cols, n_docs, pstack))
            with self._lock:
                entry["cohort_layouts"][ck] = layout
        bufs_dev = pipeline_v(cols, n_docs, pstack)  # async dispatch
        return self._make_resolve(bufs_dev, layout, tracer, flight)

    def _cohort_pipeline(self, entry):
        """(jitted packed pipeline, inner fn) over params carrying a
        leading cohort axis. Single device: vmap the solo inner (finalize
        included) over the stacked params. Mesh: one shard_map whose body
        vmaps pipeline + combine (+ finalize) per member —
        parallel/mesh.py shard_pipeline(cohort=True). jit re-specializes
        per cohort size; the coalescer's max_cohort bounds that."""
        with self._lock:
            cached = entry["cohort"]
        if cached is not None:
            return cached
        raw, agg_tpls, final = entry["raw"], entry["agg_tpls"], entry["final"]
        post_fns = self._post_chain(
            entry["template"], agg_tpls, final, entry["trim"])
        post = None
        if post_fns:
            def post(outs, p, _pfs=post_fns):
                for pf in _pfs:
                    outs = pf(outs, p)
                return outs
        if self.mesh is not None:
            from pinot_tpu.parallel.mesh import shard_pipeline

            inner_v = shard_pipeline(raw, self.mesh, cohort=True, post=post)
        else:
            one = raw
            if post is not None:
                def one(cols, n_docs, p, _raw=raw, _post=post):
                    return _post(_raw(cols, n_docs, p), p)

            def inner_v(cols, n_docs, pstack, _one=one):
                return jax.vmap(
                    lambda p: _one(cols, n_docs, p))(pstack)
        pipeline_v = jax.jit(
            lambda cols, n_docs, pstack: _pack_outs(
                inner_v(cols, n_docs, pstack)))
        with self._lock:
            if entry["cohort"] is None:
                entry["cohort"] = (pipeline_v, inner_v)
            return entry["cohort"]

    @staticmethod
    def _needed_columns(tpl) -> set:
        out = set()

        def walk(t):
            if not isinstance(t, tuple):
                return
            if t[0] == "raw":
                out.add(t[1])
                return
            if t[0] == "dictval":
                out.add("dv::" + t[1])
                return
            if t[0] in ("eq_dict", "in_dict", "range_dict", "lut_dict", "mv_any"):
                out.add(t[1])
            for x in t[1:]:
                walk(x)

        walk(tpl)
        return out

    # ---- device outputs → canonical IntermediateResult -------------------
    def _to_intermediate(self, q, ctx: BatchContext, template, outs, aggs,
                         cache_hit: bool = False, adv_key=None,
                         adv_trim_keep=None):
        shape, _, group_cols, group_cards, agg_tpls, sorted_k, _final = template
        doc_count = int(outs["doc_count"])
        # mirror the host executor's stats accounting so responses are
        # backend-independent (host.py execute_segment) — HONEST under
        # pruning: entries count only alive segments' rows, and only the
        # gathered blocks' rows when the block-skip path ran
        n_alive = min(int(outs["n_alive"]), ctx.S) \
            if "n_alive" in outs else ctx.S
        entries_in_filter = 0
        if q.filter is not None:
            rows_filter = int(outs["rows_filter"]) if "rows_filter" in outs \
                else int(ctx.n_docs.sum())
            entries_in_filter = rows_filter * len(q.filter.columns())
        entries_post = sum(
            doc_count * len(aggspec.make_spec(a).args) for a in q.aggregations()
        )
        blocks_total = int(outs.get("blocks_total", 0))
        blocks_scanned = int(outs.get("blocks_scanned", 0))
        stats = ExecutionStats(
            num_docs_scanned=doc_count,
            num_entries_scanned_in_filter=entries_in_filter,
            num_entries_scanned_post_filter=entries_post,
            num_segments_processed=n_alive,
            num_segments_queried=ctx.S,
            num_segments_matched=int((outs["seg_matched"] > 0).sum()),
            num_segments_pruned=ctx.S - n_alive,
            num_blocks_pruned=max(0, blocks_total - blocks_scanned),
            # pruned segments still count toward totalDocs (reference
            # semantics)
            total_docs=int(ctx.n_docs.sum()),
        )

        if shape == "agg":
            partials = [
                self._scalar_partial(i, t, outs, ctx) for i, t in enumerate(agg_tpls)
            ]
            return IntermediateResult("aggregation", agg_partials=partials, stats=stats)

        if shape == "groupby_sorted" and \
                int(outs["n_groups_total"]) > sorted_k:
            # the capped table dropped groups: re-run on the host so device
            # truncation policy never shapes results (host applies its own
            # numGroupsLimit semantics)
            raise DeviceUnsupported(
                f"sorted group table overflow "
                f"({int(outs['n_groups_total'])} > {sorted_k})")
        opts = q.options_ci()
        # numGroupsLimit applies on the device path too (engine default or
        # per-query SET override): excess groups drop arbitrarily-but-
        # deterministically (gid order), like the reference's hash-order
        # drops, and the stats flag marks the result plan-dependent-partial
        limit = self.num_groups_limit
        if "numgroupslimit" in opts:
            limit = max(1, int(opts["numgroupslimit"]))
        trimmed = "trim_keys" in outs
        t_reduce = time.perf_counter()
        # plan-advisor group-count feedback: the template's OBSERVED
        # group count (trimmed tables report n_present_total — the real
        # present count, not the kept count — so an advised keep that
        # proved too tight registers as an overflow and the trim advice
        # stands down). Cache hits replay the original execution's
        # buffer and are not re-observed.
        if adv_key is not None and self.advisor is not None \
                and not cache_hit:
            if trimmed:
                obs_groups = int(outs["n_present_total"])
            elif shape == "groupby_sorted":
                obs_groups = int(outs["n_groups_total"])
            else:
                obs_groups = int((np.asarray(outs["gcount"]) > 0).sum())
            self.advisor.observe(adv_key, groups=obs_groups,
                                 trim_keep=adv_trim_keep)
        if trimmed:
            # on-device final reduce ran (ops/device_reduce.py): the
            # fetched table is already ordered + trimmed, keys packed in
            # trim_keys. If numGroupsLimit would have truncated the FULL
            # table, its present-order drop policy is irreproducible from
            # the ORDER-BY-trimmed rows — host fallback keeps the limit
            # semantics device-independent.
            if int(outs["n_present_total"]) > limit:
                raise DeviceUnsupported(
                    f"device-trimmed table under numGroupsLimit pressure "
                    f"({int(outs['n_present_total'])} > {limit})")
            present = np.arange(int(outs["trim_n"]))
            rem = np.asarray(outs["trim_keys"])[present].astype(np.int64)
        else:
            gcount = outs["gcount"]
            present = np.nonzero(gcount > 0)[0]
            if len(present) > limit:
                present = present[:limit]
                stats.num_groups_limit_reached = True
            # decode the combined key (dense: the gid itself; sorted: the
            # int64 key recorded per table slot) → per-column global ids
            # → values
            if shape == "groupby_sorted":
                rem = outs["skeys"][present].astype(np.int64)
            else:
                rem = present.copy()
        keys = []
        for card in reversed(group_cards[1:]):
            keys.append(rem % card)
            rem = rem // card
        keys.append(rem)
        keys.reverse()
        key_values = tuple(
            ctx.global_dict(col).take(k) for col, k in zip(group_cols, keys)
        )
        if q.distinct:
            return IntermediateResult(
                "distinct", group_keys=key_values, stats=stats)
        partials = [
            self._group_partial(i, t, outs, ctx, present) for i, t in enumerate(agg_tpls)
        ]
        if trimmed and not cache_hit:
            # host-side completion of the device reduce: key decode +
            # partial assembly over the KEPT rows only (the host reduce
            # this replaces walked the full (G,) table). Cache hits
            # re-read a buffer whose trim ran on the ORIGINAL execution —
            # counting them would overstate in-kernel reduces by ~the
            # cache hit rate.
            dt_ms = (time.perf_counter() - t_reduce) * 1e3
            with self._lock:
                self.device_reduce_queries += 1
                self.device_reduce_ms_total += dt_ms
            self.metrics.time_ms("deviceReduceMs", dt_ms)
        return IntermediateResult(
            "group_by", group_keys=key_values, agg_partials=partials, stats=stats
        )

    def _scalar_partial(self, i, tpl, outs, ctx):
        name, argt, extra = tpl
        k = f"a{i}"
        if name == "count":
            return {"count": np.array([outs["doc_count"]], dtype=np.int64)}
        if name == "sum":
            return {"sum": np.asarray([outs[f"{k}_sum"]], dtype=np.float64)}
        if name == "avg":
            return {
                "sum": np.asarray([outs[f"{k}_sum"]], dtype=np.float64),
                "count": np.array([outs["doc_count"]], dtype=np.int64),
            }
        if name == "min":
            return {"min": np.asarray([outs[f"{k}_min"]], dtype=np.float64)}
        if name == "max":
            return {"max": np.asarray([outs[f"{k}_max"]], dtype=np.float64)}
        if name == "minmaxrange":
            return {
                "min": np.asarray([outs[f"{k}_min"]], dtype=np.float64),
                "max": np.asarray([outs[f"{k}_max"]], dtype=np.float64),
            }
        if name == "distinctcount":
            if f"{k}_cnt" in outs:  # terminal: popcount came from device
                return {"cnt": np.asarray([outs[f"{k}_cnt"]], dtype=np.int64)}
            pres = outs[f"{k}_pres"]
            vals = ctx.global_dict(argt).take(np.nonzero(pres > 0)[0])
            s = np.empty(1, dtype=object)
            s[0] = set(np.asarray(vals).tolist())
            return {"sets": s}
        if name in ("distinctcounthll", "hllmerge"):
            if f"{k}_est" in outs:  # terminal: estimated on device
                return {"est": np.asarray([outs[f"{k}_est"]], dtype=np.int64)}
            return {"regs": outs[f"{k}_regs"].reshape(1, -1)}
        if name in ("firstwithtime", "lastwithtime"):
            return _with_time_partial(name, outs, k, None)
        raise AssertionError(name)

    def _group_partial(self, i, tpl, outs, ctx, present):
        name, argt, extra = tpl
        k = f"a{i}"
        if name == "count":
            return {"count": outs["gcount"][present].astype(np.int64)}
        if name == "sum":
            return {"sum": outs[f"{k}_sum"][present].astype(np.float64)}
        if name == "avg":
            return {
                "sum": outs[f"{k}_sum"][present].astype(np.float64),
                "count": outs["gcount"][present].astype(np.int64),
            }
        if name == "min":
            return {"min": outs[f"{k}_min"][present].astype(np.float64)}
        if name == "max":
            return {"max": outs[f"{k}_max"][present].astype(np.float64)}
        if name == "minmaxrange":
            return {
                "min": outs[f"{k}_min"][present].astype(np.float64),
                "max": outs[f"{k}_max"][present].astype(np.float64),
            }
        if name == "distinctcount":
            if f"{k}_cnt" in outs:  # terminal: popcounts came from device
                return {"cnt": outs[f"{k}_cnt"][present].astype(np.int64)}
            pres = outs[f"{k}_pres"][present]
            gvals = np.asarray(ctx.global_dict(argt).values)
            sets = np.empty(len(present), dtype=object)
            for j in range(len(present)):
                sets[j] = set(gvals[np.nonzero(pres[j] > 0)[0]].tolist())
            return {"sets": sets}
        if name in ("distinctcounthll", "hllmerge"):
            if f"{k}_est" in outs:  # terminal: estimated on device
                return {"est": outs[f"{k}_est"][present].astype(np.int64)}
            return {"regs": outs[f"{k}_regs"][present]}
        if name in ("firstwithtime", "lastwithtime"):
            return _with_time_partial(name, outs, k, present)
        raise AssertionError(name)
