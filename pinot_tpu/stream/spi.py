"""Stream ingestion SPI: pluggable consumers, offsets, decoders.

Equivalent of pinot-spi/.../stream/: ``StreamConsumerFactory``,
``PartitionGroupConsumer``, ``MessageBatch``, ``StreamPartitionMsgOffset``
(orderable opaque offsets), ``StreamMessageDecoder``. Concrete streams
register under a type key (reference: StreamConsumerFactoryProvider +
isolated plugin classloaders; here a plain registry — python imports are the
plugin boundary).
"""

from __future__ import annotations

import abc
import dataclasses
import functools
import json
from typing import Callable, Optional, Sequence

from pinot_tpu.common.table_config import StreamConfig


@functools.total_ordering
class StreamPartitionMsgOffset:
    """Orderable opaque offset (StreamPartitionMsgOffset.java). Wraps a long
    for the built-in streams; subclasses may carry richer state as long as
    comparison and string round-trip hold."""

    def __init__(self, value: int):
        self.value = int(value)

    def __eq__(self, other):
        return isinstance(other, StreamPartitionMsgOffset) and self.value == other.value

    def __lt__(self, other):
        return self.value < other.value

    def __repr__(self):
        return f"Offset({self.value})"

    def to_string(self) -> str:
        return str(self.value)

    @classmethod
    def from_string(cls, s: str) -> "StreamPartitionMsgOffset":
        return cls(int(s))


@dataclasses.dataclass
class StreamMessage:
    offset: StreamPartitionMsgOffset
    payload: bytes
    key: Optional[bytes] = None
    timestamp_ms: Optional[int] = None


@dataclasses.dataclass
class MessageBatch:
    """One fetch result (MessageBatch.java): messages plus the offset to
    resume from (next fetch's start)."""

    messages: Sequence[StreamMessage]
    next_offset: StreamPartitionMsgOffset

    def __len__(self):
        return len(self.messages)


class PartitionGroupConsumer(abc.ABC):
    """Consumer pinned to one stream partition (PartitionGroupConsumer.java)."""

    @abc.abstractmethod
    def fetch_messages(self, start_offset: StreamPartitionMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        ...

    def close(self) -> None:
        pass


class StreamConsumerFactory(abc.ABC):
    """Per-table stream access (StreamConsumerFactory.java)."""

    def __init__(self, config: StreamConfig):
        self.config = config

    @abc.abstractmethod
    def partition_count(self) -> int:
        ...

    @abc.abstractmethod
    def create_partition_consumer(self, partition: int) -> PartitionGroupConsumer:
        ...

    def earliest_offset(self, partition: int) -> StreamPartitionMsgOffset:
        return StreamPartitionMsgOffset(0)


# ---------------------------------------------------------------------------
# decoders (input-format plugins: pinot-plugins/pinot-input-format/*)
# ---------------------------------------------------------------------------


def json_decoder(payload: bytes) -> dict:
    return json.loads(payload.decode("utf-8"))


def json_batch_decoder(payloads) -> list:
    """Decode MANY json payloads in one parser call by joining them into a
    single JSON array — the C scanner loops instead of paying the python
    ``loads`` entry cost per message (~4x on small events; the columnar
    ingest path's decode basis, realtime/chunklet.py). Falls back to the
    per-payload decoder on any malformed message (caller isolates it)."""
    return json.loads(b"[" + b",".join(payloads) + b"]")


def get_batch_decoder(name: str, stream_config: StreamConfig) -> Optional[Callable]:
    """Batch decoder (payloads list → rows list) for decoders that have a
    vectorized form, else None (callers loop the row decoder)."""
    if name == "json":
        return json_batch_decoder
    return None


def csv_decoder_for(columns: Sequence[str], delimiter: str = ",") -> Callable:
    def decode(payload: bytes) -> dict:
        parts = payload.decode("utf-8").rstrip("\n").split(delimiter)
        return dict(zip(columns, parts))

    return decode


_DECODERS: dict[str, Callable] = {"json": json_decoder}


def get_decoder(name: str, stream_config: StreamConfig) -> Callable:
    if name == "csv":
        cols = stream_config.properties.get("csv.columns", "")
        return csv_decoder_for(cols.split(","),
                               stream_config.properties.get("csv.delimiter", ","))
    if name == "avro":
        # schemaful binary records (SimpleAvroMessageDecoder analog): the
        # writer schema rides in stream properties, one binary record per
        # message, no container framing
        from pinot_tpu.ingestion.avro_io import binary_decoder_for

        schema_json = stream_config.properties.get("avro.schema", "")
        if not schema_json:
            raise KeyError(
                "avro decoder needs the writer schema in stream "
                "properties['avro.schema']")
        return binary_decoder_for(schema_json)
    if name == "thrift":
        # TBinaryProtocol struct records (ThriftRecordReader role); the
        # field-id → column map plays the generated class's part
        from pinot_tpu.ingestion.thrift_io import binary_decoder_for as thrift_for

        fmap = stream_config.properties.get("thrift.field.map", "")
        return thrift_for(fmap)
    if name == "confluent-avro":
        # magic byte + schema-registry id framing
        # (KafkaConfluentSchemaRegistryAvroMessageDecoder role)
        from pinot_tpu.ingestion.confluent_avro import ConfluentAvroDecoder

        inline = {
            k[len("schema.registry.schemas."):]: v
            for k, v in stream_config.properties.items()
            if k.startswith("schema.registry.schemas.")
        }
        return ConfluentAvroDecoder(
            registry_url=stream_config.properties.get(
                "schema.registry.url", ""),
            inline_schemas=inline or None)
    if name == "protobuf":
        # one serialized message per payload (ProtoBufMessageDecoder)
        from pinot_tpu.ingestion.protobuf_io import binary_decoder_for

        desc = stream_config.properties.get("protobuf.descriptor_file", "")
        msg = stream_config.properties.get("protobuf.message_name", "")
        if not desc or not msg:
            raise KeyError(
                "protobuf decoder needs stream properties "
                "'protobuf.descriptor_file' + 'protobuf.message_name'")
        return binary_decoder_for(desc, msg)
    try:
        return _DECODERS[name]
    except KeyError:
        raise KeyError(f"unknown decoder {name!r}") from None


def register_decoder(name: str, fn: Callable) -> None:
    _DECODERS[name] = fn


# ---------------------------------------------------------------------------
# factory registry (StreamConsumerFactoryProvider analog)
# ---------------------------------------------------------------------------

_FACTORIES: dict[str, type] = {}


def register_stream_type(name: str, factory_cls: type) -> None:
    _FACTORIES[name] = factory_cls


def create_consumer_factory(config: StreamConfig) -> StreamConsumerFactory:
    # built-ins register lazily so importing the SPI stays dependency-free
    if config.stream_type == "memory" and "memory" not in _FACTORIES:
        from pinot_tpu.stream import memory_stream  # noqa: F401  (registers)
    if config.stream_type == "kafka" and "kafka" not in _FACTORIES:
        from pinot_tpu.stream import kafka_stream  # noqa: F401  (registers)
    if config.stream_type == "kinesis" and "kinesis" not in _FACTORIES:
        from pinot_tpu.stream import kinesis_stream  # noqa: F401  (registers)
    if config.stream_type == "pulsar" and "pulsar" not in _FACTORIES:
        from pinot_tpu.stream import pulsar_stream  # noqa: F401  (registers)
    try:
        cls = _FACTORIES[config.stream_type]
    except KeyError:
        raise KeyError(
            f"unknown stream type {config.stream_type!r}; registered: "
            f"{sorted(_FACTORIES)}"
        ) from None
    return cls(config)
