"""Kinesis stream plugin (pinot-plugins/pinot-stream-ingestion/pinot-kinesis
analog), gated on ``boto3``.

Shape-match to the reference's KinesisConsumerFactory / KinesisConsumer /
KinesisStreamMetadataProvider:

- a Kinesis SHARD is the partition-group unit; shards are mapped to dense
  partition ids ordinally (sorted by shardId), like the reference's
  partition-group metadata derived from ListShards;
- checkpoints are SEQUENCE NUMBERS. Kinesis sequence numbers are decimal
  strings of monotonically increasing integers, so they ride the SPI's
  integer offsets directly: offset 0 = TRIM_HORIZON (earliest), offset
  v > 0 = "resume AFTER sequence number v-1" — next_offset after a record
  with sequence s is int(s)+1, mirroring the kafka plugin's last+1;
- fetches map to GetShardIterator + GetRecords with the SPI timeout.

StreamConfig.properties pass through:

    stream_type: kinesis
    topic: my-stream           # Kinesis stream name
    properties:
      aws.region: us-west-2
      aws.endpoint: http://localhost:4566   # localstack/dev override
      # any further boto3 client kwarg as kinesis.client.<name>

The build image carries no boto3; the module registers lazily and raises a
clear gating error at factory construction (plugin isolation, PluginManager
analog) — tests fake the boto3 module.
"""

from __future__ import annotations

from pinot_tpu.common.table_config import StreamConfig
from pinot_tpu.stream.spi import (
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamPartitionMsgOffset,
    register_stream_type,
)


def _boto3():
    try:
        import boto3  # type: ignore

        return boto3
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "stream_type 'kinesis' needs the boto3 package; install it or "
            "use the 'memory'/'kafka' streams") from e


def _client(config: StreamConfig, timeout_ms: int = 10_000):
    props = config.properties or {}
    kwargs = {}
    if props.get("aws.region"):
        kwargs["region_name"] = props["aws.region"]
    if props.get("aws.endpoint"):
        kwargs["endpoint_url"] = props["aws.endpoint"]
    for key, val in props.items():
        if key.startswith("kinesis.client."):
            kwargs[key[len("kinesis.client."):]] = val
    boto3 = _boto3()
    # bound the SDK so fetch_messages honors the SPI timeout: boto3's
    # defaults (60s read timeout x retries) would stall the ingest thread
    # far past the consume loop's deadline during a partition
    try:
        from botocore.config import Config  # type: ignore

        timeout_s = max(1.0, timeout_ms / 1000.0)
        kwargs.setdefault("config", Config(
            connect_timeout=timeout_s, read_timeout=timeout_s,
            retries={"max_attempts": 2}))
    except ImportError:  # pragma: no cover — faked boto3 in tests
        pass
    return boto3.client("kinesis", **kwargs)


def _shard_ids(client, stream: str) -> list:
    """Dense ordinal shard mapping (sorted by shardId for stability)."""
    shards = []
    token = None
    while True:
        if token:
            resp = client.list_shards(NextToken=token)
        else:
            resp = client.list_shards(StreamName=stream)
        shards.extend(s["ShardId"] for s in resp.get("Shards", []))
        token = resp.get("NextToken")
        if not token:
            return sorted(shards)


class KinesisPartitionConsumer(PartitionGroupConsumer):
    def __init__(self, config: StreamConfig, partition: int):
        self.config = config
        # client-level SDK bound approximating the per-fetch SPI timeout
        # (boto3 configures timeouts per client, not per call); the stream
        # property overrides the 10s default
        props = config.properties or {}
        self._client = _client(
            config,
            timeout_ms=int(props.get("kinesis.fetch.timeout.ms", 10_000)))
        self._stream = config.topic
        ids = _shard_ids(self._client, self._stream)
        if partition >= len(ids):
            raise ValueError(
                f"stream {self._stream!r} has {len(ids)} shards; "
                f"partition {partition} out of range")
        self._shard_id = ids[partition]
        self._iterator = None
        self._positioned_at = None

    def _seek(self, offset_value: int) -> None:
        if offset_value <= 0:
            resp = self._client.get_shard_iterator(
                StreamName=self._stream, ShardId=self._shard_id,
                ShardIteratorType="TRIM_HORIZON")
        else:
            resp = self._client.get_shard_iterator(
                StreamName=self._stream, ShardId=self._shard_id,
                ShardIteratorType="AFTER_SEQUENCE_NUMBER",
                StartingSequenceNumber=str(offset_value - 1))
        self._iterator = resp["ShardIterator"]
        self._positioned_at = offset_value

    def fetch_messages(self, start_offset: StreamPartitionMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        if self._iterator is None or self._positioned_at != start_offset.value:
            self._seek(start_offset.value)
        resp = self._client.get_records(ShardIterator=self._iterator)
        self._iterator = resp.get("NextShardIterator")
        messages = []
        next_off = start_offset.value
        for r in resp.get("Records", []):
            seq = int(r["SequenceNumber"])
            ts = r.get("ApproximateArrivalTimestamp")
            messages.append(StreamMessage(
                offset=StreamPartitionMsgOffset(seq + 1),
                payload=r["Data"],
                key=r.get("PartitionKey"),
                timestamp_ms=int(ts.timestamp() * 1000)
                if hasattr(ts, "timestamp") else ts,
            ))
            next_off = seq + 1
        self._positioned_at = next_off
        return MessageBatch(messages=messages,
                            next_offset=StreamPartitionMsgOffset(next_off))

    def close(self) -> None:
        close = getattr(self._client, "close", None)
        if close is not None:
            close()


class KinesisConsumerFactory(StreamConsumerFactory):
    def __init__(self, config: StreamConfig):
        super().__init__(config)
        _boto3()  # fail fast with the clear gating error

    def partition_count(self) -> int:
        client = _client(self.config)
        try:
            return len(_shard_ids(client, self.config.topic))
        finally:
            close = getattr(client, "close", None)
            if close is not None:
                close()

    def create_partition_consumer(self, partition: int) -> PartitionGroupConsumer:
        return KinesisPartitionConsumer(self.config, partition)

    def earliest_offset(self, partition: int) -> StreamPartitionMsgOffset:
        return StreamPartitionMsgOffset(0)  # TRIM_HORIZON


register_stream_type("kinesis", KinesisConsumerFactory)
