"""Kafka stream plugin (pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0
analog), gated on the ``kafka-python`` client library.

Maps the SPI onto KafkaConsumer primitives the way KafkaPartitionLevelConsumer
does: one consumer per partition pinned with ``assign``, offsets are Kafka
offsets (long, so StreamPartitionMsgOffset wraps them directly), fetches are
``poll`` with the SPI timeout, and partition count comes from
``partitions_for_topic``. StreamConfig.properties pass through:

    stream_type: kafka
    topic: my-events
    properties:
      bootstrap.servers: broker1:9092,broker2:9092
      # any further kafka-python kwarg as kafka.consumer.<name>

The image this framework is developed in carries no Kafka client, so the
module registers lazily and raises a clear error at factory-construction
time when ``kafka`` is not importable — the SPI registry itself never
breaks (plugin isolation, PluginManager analog).
"""

from __future__ import annotations

from pinot_tpu.common.table_config import StreamConfig
from pinot_tpu.stream.spi import (
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamPartitionMsgOffset,
    register_stream_type,
)


def _kafka():
    try:
        import kafka  # type: ignore

        return kafka
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "stream_type 'kafka' needs the kafka-python package; install it "
            "or use the 'memory'/'file' streams") from e


def _coerce(val):
    """StreamConfig.properties is dict[str, str]; kafka-python does no
    config coercion, so numeric/bool kwargs must be typed here."""
    if not isinstance(val, str):
        return val
    low = val.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(val)
    except ValueError:
        pass
    try:
        return float(val)
    except ValueError:
        return val


def _consumer_kwargs(config: StreamConfig) -> dict:
    props = config.properties or {}
    kwargs = {
        "bootstrap_servers": props.get("bootstrap.servers", "localhost:9092"),
        "enable_auto_commit": False,  # offsets live in the checkpoint store
        "group_id": None,
    }
    for key, val in props.items():
        if key.startswith("kafka.consumer."):
            name = key[len("kafka.consumer."):]
            if name == "enable_auto_commit":
                # broker-side auto-commit would fight the checkpoint store's
                # exactly-once resume; refuse rather than silently re-enable
                raise ValueError(
                    "kafka.consumer.enable_auto_commit is not overridable: "
                    "offsets are managed by the checkpoint store")
            kwargs[name] = _coerce(val)
    return kwargs


class KafkaPartitionConsumer(PartitionGroupConsumer):
    def __init__(self, config: StreamConfig, partition: int):
        k = _kafka()
        self._tp = k.TopicPartition(config.topic, partition)
        self._consumer = k.KafkaConsumer(**_consumer_kwargs(config))
        self._consumer.assign([self._tp])
        self._positioned_at = None

    def fetch_messages(self, start_offset: StreamPartitionMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        if self._positioned_at != start_offset.value:
            self._consumer.seek(self._tp, start_offset.value)
        polled = self._consumer.poll(timeout_ms=timeout_ms)
        records = polled.get(self._tp, [])
        messages = [
            StreamMessage(
                offset=StreamPartitionMsgOffset(r.offset),
                payload=r.value,
                key=r.key,
                timestamp_ms=getattr(r, "timestamp", None),
            )
            for r in records
        ]
        next_off = (records[-1].offset + 1) if records else start_offset.value
        self._positioned_at = next_off
        return MessageBatch(messages=messages,
                            next_offset=StreamPartitionMsgOffset(next_off))

    def close(self) -> None:
        self._consumer.close()


class KafkaConsumerFactory(StreamConsumerFactory):
    def __init__(self, config: StreamConfig):
        super().__init__(config)
        _kafka()  # fail fast with the clear gating error
        self._earliest: dict = {}  # partition -> offset, one probe for all

    def _probe_metadata(self) -> int:
        """ONE probe consumer answers partition count AND every partition's
        beginning offset — a 64-partition table start is one broker
        round-trip, not 65."""
        k = _kafka()
        probe = k.KafkaConsumer(**_consumer_kwargs(self.config))
        try:
            parts = probe.partitions_for_topic(self.config.topic)
            if not parts:
                raise RuntimeError(
                    f"kafka topic {self.config.topic!r} has no partitions "
                    f"(missing topic?)")
            tps = [k.TopicPartition(self.config.topic, p) for p in parts]
            begins = probe.beginning_offsets(tps)
            self._earliest = {tp.partition: off for tp, off in begins.items()}
            return len(parts)
        finally:
            probe.close()

    def partition_count(self) -> int:
        return self._probe_metadata()

    def create_partition_consumer(self, partition: int) -> PartitionGroupConsumer:
        return KafkaPartitionConsumer(self.config, partition)

    def earliest_offset(self, partition: int) -> StreamPartitionMsgOffset:
        if partition not in self._earliest:
            self._probe_metadata()
        return StreamPartitionMsgOffset(self._earliest.get(partition, 0))


register_stream_type("kafka", KafkaConsumerFactory)
