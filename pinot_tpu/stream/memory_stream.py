"""In-memory stream: the embedded-Kafka analog for tests and quickstarts.

The reference's integration tests start an embedded Kafka broker
(BaseClusterIntegrationTest.startKafka); here an in-process, thread-safe
topic registry plays that role. Producers publish bytes per partition;
consumers fetch by offset, exactly like a log.
"""

from __future__ import annotations

import threading
from typing import Optional

from pinot_tpu.common.table_config import StreamConfig
from pinot_tpu.stream.spi import (
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamPartitionMsgOffset,
    register_stream_type,
)


class InMemoryTopic:
    def __init__(self, name: str, num_partitions: int = 1):
        self.name = name
        self._partitions: list[list[bytes]] = [[] for _ in range(num_partitions)]
        self._lock = threading.Lock()

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    def publish(self, payload: bytes, partition: int = 0, key: Optional[bytes] = None):
        with self._lock:
            self._partitions[partition].append(payload)

    def publish_json(self, obj: dict, partition: int = 0) -> None:
        import json

        self.publish(json.dumps(obj).encode("utf-8"), partition)

    def log_size(self, partition: int) -> int:
        with self._lock:
            return len(self._partitions[partition])

    def read(self, partition: int, start: int, max_count: int) -> list:
        with self._lock:
            return self._partitions[partition][start : start + max_count]


class TopicRegistry:
    """Process-wide topic namespace (the 'broker')."""

    _topics: dict[str, InMemoryTopic] = {}
    _lock = threading.Lock()

    @classmethod
    def create(cls, name: str, num_partitions: int = 1) -> InMemoryTopic:
        with cls._lock:
            if name not in cls._topics:
                cls._topics[name] = InMemoryTopic(name, num_partitions)
            return cls._topics[name]

    @classmethod
    def get(cls, name: str) -> InMemoryTopic:
        with cls._lock:
            try:
                return cls._topics[name]
            except KeyError:
                raise KeyError(f"topic {name!r} does not exist") from None

    @classmethod
    def delete(cls, name: str) -> None:
        with cls._lock:
            cls._topics.pop(name, None)


class MemoryPartitionConsumer(PartitionGroupConsumer):
    def __init__(self, topic: InMemoryTopic, partition: int, max_batch: int = 1000):
        self._topic = topic
        self._partition = partition
        self._max_batch = max_batch

    def fetch_messages(self, start_offset: StreamPartitionMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        start = start_offset.value
        payloads = self._topic.read(self._partition, start, self._max_batch)
        messages = [
            StreamMessage(StreamPartitionMsgOffset(start + i), p)
            for i, p in enumerate(payloads)
        ]
        return MessageBatch(messages, StreamPartitionMsgOffset(start + len(payloads)))

    def fetch_payload_batch(self, start_offset: StreamPartitionMsgOffset,
                            max_count: int):
        """Columnar-ingest fast path (realtime/chunklet.py): raw payloads +
        next offset, skipping per-message StreamMessage/offset object
        construction (~2.5us/message — above the whole columnar index cost
        per row). Optional SPI surface: consumers without it fall back to
        fetch_messages."""
        start = start_offset.value
        payloads = self._topic.read(self._partition, start, max_count)
        return payloads, StreamPartitionMsgOffset(start + len(payloads))


class MemoryStreamConsumerFactory(StreamConsumerFactory):
    def partition_count(self) -> int:
        return TopicRegistry.get(self.config.topic).num_partitions

    def create_partition_consumer(self, partition: int) -> PartitionGroupConsumer:
        return MemoryPartitionConsumer(TopicRegistry.get(self.config.topic), partition)


register_stream_type("memory", MemoryStreamConsumerFactory)
