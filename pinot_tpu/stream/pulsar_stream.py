"""Pulsar stream plugin (pinot-plugins/pinot-stream-ingestion/pinot-pulsar
analog), gated on ``pulsar-client``.

Shape-match to the reference's PulsarConsumerFactory /
PulsarPartitionLevelConsumer / MessageIdStreamOffset:

- a partitioned topic's partition N maps to the ``<topic>-partition-N``
  sub-topic, read with the Reader API (no subscription state — the
  engine's registry checkpoints are the source of truth, exactly like the
  reference bypasses Pulsar subscriptions);
- offsets are MessageIds. The SPI wraps orderable integers, so MessageIds
  PACK into one int: (ledger_id << 28) | (entry_id << 8) | (batch_index
  + 1), with offset 0 = earliest. Ledger ids grow monotonically and entry
  ids reset per ledger, so packed values order exactly like the
  reference's MessageIdStreamOffset comparison (documented bounds:
  entry_id < 2^20 per ledger, batch < 255 — far above broker defaults of
  50k entries/ledger);
- next_offset after a message is its packed id + 1 ("resume after").

StreamConfig.properties pass through:

    stream_type: pulsar
    topic: persistent://tenant/ns/events
    properties:
      pulsar.service.url: pulsar://localhost:6650
      # further pulsar.Client kwargs as pulsar.client.<name>

The build image carries no pulsar-client; the module registers lazily and
raises a clear gating error at factory construction — tests fake the
``pulsar`` module.
"""

from __future__ import annotations

from pinot_tpu.common.table_config import StreamConfig
from pinot_tpu.stream.spi import (
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamPartitionMsgOffset,
    register_stream_type,
)

_ENTRY_BITS = 20
_BATCH_BITS = 8
# broker-side managedLedgerMaxEntriesPerLedger, declared by the operator so
# the packing bound is checked at CONSTRUCTION (fail fast, before any
# checkpoint advances) instead of mid-consume
_MAX_ENTRIES_PROP = "pulsar.max.entries.per.ledger"


def _validate_entry_bound(config: StreamConfig) -> None:
    """Packed offsets bound entry_id below 2^20 per ledger. Brokers default
    to 50k entries/ledger (far under the bound), but an operator who raised
    managedLedgerMaxEntriesPerLedger past 2^20 would only find out via a
    mid-consume ValueError with the consumer making no ingest progress —
    so the factory/consumer checks the DECLARED broker bound (the
    ``pulsar.max.entries.per.ledger`` stream property) up front and rejects
    the config with the same remediation message. pack_message_id keeps
    its per-message guard as the backstop for undeclared configs."""
    props = config.properties or {}
    declared = props.get(_MAX_ENTRIES_PROP)
    if declared is None:
        return
    try:
        bound = int(declared)
    except (TypeError, ValueError):
        raise ValueError(
            f"{_MAX_ENTRIES_PROP}={declared!r} is not an integer — set it "
            f"to the broker's managedLedgerMaxEntriesPerLedger value")
    if bound > (1 << _ENTRY_BITS):
        raise ValueError(
            f"{_MAX_ENTRIES_PROP}={declared} exceeds the packed-offset "
            f"entry_id bound 2^{_ENTRY_BITS} — lower the broker's "
            f"managedLedgerMaxEntriesPerLedger below it or widen the "
            f"packing (_ENTRY_BITS)")


def _pulsar():
    try:
        import pulsar  # type: ignore

        return pulsar
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "stream_type 'pulsar' needs the pulsar-client package; install "
            "it or use the 'memory'/'kafka' streams") from e


def pack_message_id(ledger_id: int, entry_id: int, batch_index: int) -> int:
    """MessageId → orderable int (MessageIdStreamOffset role). batch_index
    -1 (non-batched) packs as 0; batched entries 0.. pack as 1.. so a
    non-batched message sorts before its (impossible) batch siblings."""
    if entry_id >= (1 << _ENTRY_BITS):
        raise ValueError(
            f"entry_id {entry_id} exceeds the packed-offset bound "
            f"2^{_ENTRY_BITS} — raise managedLedgerMaxEntriesPerLedger "
            f"below it or widen the packing")
    b = batch_index + 1 if batch_index is not None and batch_index >= 0 else 0
    if b >= (1 << _BATCH_BITS):
        raise ValueError(f"batch_index {batch_index} exceeds packing bound")
    return (ledger_id << (_ENTRY_BITS + _BATCH_BITS)) \
        | (entry_id << _BATCH_BITS) | b


def unpack_message_id(packed: int):
    """(ledger_id, entry_id, batch_index) from a packed offset."""
    b = packed & ((1 << _BATCH_BITS) - 1)
    entry = (packed >> _BATCH_BITS) & ((1 << _ENTRY_BITS) - 1)
    ledger = packed >> (_ENTRY_BITS + _BATCH_BITS)
    return ledger, entry, b - 1


def _client(config: StreamConfig):
    props = config.properties or {}
    url = props.get("pulsar.service.url", "pulsar://localhost:6650")
    kwargs = {}
    for key, val in props.items():
        if key.startswith("pulsar.client."):
            kwargs[key[len("pulsar.client."):]] = val
    return _pulsar().Client(url, **kwargs)


def _partition_topic(topic: str, partition: int, n_partitions: int) -> str:
    return topic if n_partitions <= 1 else f"{topic}-partition-{partition}"


class PulsarPartitionConsumer(PartitionGroupConsumer):
    def __init__(self, config: StreamConfig, partition: int,
                 n_partitions: int):
        _validate_entry_bound(config)
        self.config = config
        self._pulsar = _pulsar()
        self._client = _client(config)
        self._topic = _partition_topic(config.topic, partition, n_partitions)
        self._reader = None
        self._positioned_at = None

    def _seek(self, offset_value: int) -> None:
        if self._reader is not None:
            self._reader.close()
        if offset_value <= 0:
            start = self._pulsar.MessageId.earliest
        else:
            # resume AFTER the packed id − 1 (exclusive start): position AT
            # the previous message and skip it via the reader contract
            ledger, entry, batch = unpack_message_id(offset_value - 1)
            start = self._pulsar.MessageId(-1, ledger, entry, batch)
        self._reader = self._client.create_reader(
            self._topic, start,
            start_message_id_inclusive=(offset_value <= 0))
        self._positioned_at = offset_value

    def fetch_messages(self, start_offset: StreamPartitionMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        if self._reader is None or self._positioned_at != start_offset.value:
            self._seek(start_offset.value)
        messages = []
        next_off = start_offset.value
        deadline_ms = max(1, int(timeout_ms))
        # only TIMEOUT ends a fetch quietly; transport/auth errors must
        # surface (a swallowed ConnectError would read as caught-up and
        # stall ingestion silently)
        timeout_excs = tuple(
            e for e in (getattr(self._pulsar, "Timeout", None), TimeoutError)
            if e is not None)
        while True:
            try:
                msg = self._reader.read_next(timeout_millis=deadline_ms)
            except timeout_excs:
                break
            mid = msg.message_id()
            packed = pack_message_id(
                mid.ledger_id(), mid.entry_id(),
                getattr(mid, "batch_index", lambda: -1)())
            next_off = packed + 1
            messages.append(StreamMessage(
                offset=StreamPartitionMsgOffset(packed),
                payload=msg.data(),
                key=(msg.partition_key() or "").encode("utf-8") or None,
                timestamp_ms=msg.publish_timestamp(),
            ))
            deadline_ms = 1  # drain whatever is already buffered
            if len(messages) >= 10_000:
                break
        self._positioned_at = next_off
        return MessageBatch(messages=messages,
                            next_offset=StreamPartitionMsgOffset(next_off))

    def close(self) -> None:
        if self._reader is not None:
            self._reader.close()
        self._client.close()


class PulsarConsumerFactory(StreamConsumerFactory):
    def __init__(self, config: StreamConfig):
        super().__init__(config)
        _validate_entry_bound(config)
        self._n_partitions: int | None = None

    def partition_count(self) -> int:
        # cached: a 32-partition table would otherwise open one throwaway
        # client + metadata round trip PER consumer construction
        if self._n_partitions is None:
            client = _client(self.config)
            try:
                parts = client.get_topic_partitions(self.config.topic)
                self._n_partitions = max(1, len(parts))
            finally:
                client.close()
        return self._n_partitions

    def create_partition_consumer(self, partition: int) -> PulsarPartitionConsumer:
        return PulsarPartitionConsumer(self.config, partition,
                                       self.partition_count())


register_stream_type("pulsar", PulsarConsumerFactory)
