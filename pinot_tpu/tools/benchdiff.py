"""Bench round differ: ``python -m pinot_tpu.tools.benchdiff OLD NEW``.

Compares two recorded bench rounds (``BENCH_r*.json``) and exits non-zero
when the new round regresses past a threshold — the CI face of the bench
artifacts the driver records every PR.

Input tolerance (both files): a round may be

- the bench's own stdout JSON (``{"metric": ..., "detail": {...}}``),
- the driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` where
  ``parsed`` is the full doc **or None** — then the known detail
  sections are brace-matched out of the truncated ``tail`` string, the
  same recovery bench.py's ``_load_micro_reference`` performs,
- partially populated (early rounds lack later phases): only metrics
  present in BOTH rounds are compared; everything else is reported as
  added/removed, never as a regression.

Compared metric families (direction-aware):

- per-suite query latencies (``ssb100m``/``taxi12m``/``subrtt`` entries'
  ``p50_ms`` — lower is better),
- micro kernel throughput (``micro.*.mrows_per_s`` — higher is better),
- concurrency throughput (``concurrency.n*.qps`` — higher is better),
- cluster-tier scaling (``cluster.servers.n*.qps`` /
  ``cluster.scaling_efficiency_2`` — higher is better — and
  ``cluster.result_cache.hit_p50_ms`` — lower is better), compared only
  when BOTH rounds carry a ``detail.cluster`` section,
- the phase waterfall (``observability.phase_p50_ms.*`` — lower is
  better; informational by default since queue/link phases are noisy,
  gated only under ``--gate-phases``),
- the per-kernel roofline (``roofline.kernels.*.gbps`` — higher is
  better — ISSUE 11's achieved-GB/s-vs-HBM-peak accounting), compared
  when both rounds carry a ``detail.roofline`` section (or the copy
  nested under ``observability``),
- the tiered-lifecycle phase (``tiering.per_tier.{hot,warm}.p50_ms`` +
  ``tiering.cold.hydrate_ms`` — lower is better — and
  ``tiering.peak_rss_delta_mb`` — lower is better — ISSUE 12), compared
  only when BOTH rounds carry a ``detail.tiering`` section,
- the overload-survival phase (``overload.knee_qps`` — higher is
  better — ``overload.p99_at_2x_knee_ms`` and
  ``overload.tenant_b.spike_p99_ms`` — lower is better — ISSUE 14),
  compared only when BOTH rounds carry a ``detail.overload`` section,
- the join phase (``join.join_p50_ms`` — lower is better — and the
  distributed stage-2 exchange trend keys ``join.stage2_qps`` — higher
  is better — ``join.exchange_bytes`` / ``join.spill_count`` —
  informational wire-volume and warm-tier-spill trackers, never gated:
  both move legitimately with partition count and buffer sizing —
  ISSUE 16), compared only when BOTH rounds carry the keys,
- the adaptive phase (``adaptive.*.converged_p50_ms`` — lower is
  better — the advisor's post-convergence latency on each deliberately
  mis-tuned scenario, plus ``adaptive.*.queries_to_converge`` —
  informational, never gated: it moves with min-samples/reprobe tuning —
  ISSUE 17), compared only when BOTH rounds carry a ``detail.adaptive``
  section,
- the frontdoor phase (``frontdoor.qps2_over_qps1`` — higher is better,
  the 2-broker scaling ratio — and ``frontdoor.stream_rss_delta_mb`` —
  lower is better, the streaming SELECT's broker RSS growth; ISSUE 18),
  compared only when BOTH rounds carry a ``detail.frontdoor`` section.
"""

from __future__ import annotations

import argparse
import json
import sys

# sections brace-matched out of a truncated driver-wrapper tail
_TAIL_SECTIONS = ("ssb100m", "taxi12m", "subrtt", "micro", "concurrency",
                  "observability", "blockskip", "narrow", "join", "faults",
                  "cluster", "breakdown", "roofline", "tiering", "overload",
                  "adaptive", "frontdoor")


def _brace_match(text: str, key: str):
    """json.loads the ``{...}`` object following ``"key":`` in ``text``,
    or None (absent / truncated mid-object). String-aware: braces inside
    JSON string values (a note containing '}' etc.) don't move the depth
    counter."""
    i = text.find(f'"{key}":')
    if i < 0:
        return None
    j = text.find("{", i)
    if j < 0:
        return None
    depth, k = 0, j
    in_string = escape = False
    while k < len(text):
        ch = text[k]
        if in_string:
            if escape:
                escape = False
            elif ch == "\\":
                escape = True
            elif ch == '"':
                in_string = False
        elif ch == '"':
            in_string = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                break
        k += 1
    try:
        return json.loads(text[j:k + 1])
    except ValueError:
        return None


def load_round(path: str) -> dict:
    """Round file → detail dict (best effort, never raises on partial
    rounds — an unreadable file IS an error).

    A round whose JSON parses to ``None``/empty (driver recorded a
    crashed run: ``parsed: null`` with no recoverable tail, or a bare
    ``null`` document) is SKIPPED with a warning instead of a traceback —
    every metric then reports as added/removed, never as a regression."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not doc:
        print(f"benchdiff: warning: round {path!r} parsed to "
              f"{'empty' if doc == {} else type(doc).__name__}; "
              f"treating as an empty round", file=sys.stderr)
        return {}
    # driver wrapper?
    if "tail" in doc and "metric" not in doc:
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            doc = parsed
        else:
            tail = doc.get("tail") or ""
            detail = {}
            for sec in _TAIL_SECTIONS:
                got = _brace_match(tail, sec)
                if got is not None:
                    detail[sec] = got
            if not detail:
                print(f"benchdiff: warning: round {path!r} has no parsed "
                      f"doc and no recoverable tail sections",
                      file=sys.stderr)
            return detail
    if isinstance(doc.get("detail"), dict):
        return doc["detail"]
    return doc


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def extract_metrics(detail: dict) -> dict:
    """detail → {metric_name: (value, direction)} where direction is
    "lower" (latency) or "higher" (throughput)."""
    out: dict = {}
    for suite in ("ssb100m", "taxi12m", "subrtt"):
        sec = detail.get(suite)
        if not isinstance(sec, dict):
            continue
        for qname, entry in sec.items():
            if isinstance(entry, dict):
                p50 = _num(entry.get("p50_ms"))
                if p50 is not None:
                    out[f"{suite}.{qname}.p50_ms"] = (p50, "lower")
    micro = detail.get("micro")
    if isinstance(micro, dict):
        for kname, entry in micro.items():
            if isinstance(entry, dict):
                rate = _num(entry.get("mrows_per_s"))
                if rate is not None:
                    out[f"micro.{kname}.mrows_per_s"] = (rate, "higher")
                # achieved bandwidth rides next to the row rate so the
                # Pallas scatter-tier micros (ISSUE 15) diff on their
                # GB/s-vs-HBM-peak axis too
                g = _num(entry.get("gbps"))
                if g is not None:
                    out[f"micro.{kname}.gbps"] = (g, "higher")
    conc = detail.get("concurrency")
    if isinstance(conc, dict):
        for lname, entry in conc.items():
            if isinstance(entry, dict):
                qps = _num(entry.get("qps"))
                if qps is not None:
                    out[f"concurrency.{lname}.qps"] = (qps, "higher")
    obs = detail.get("observability")
    if isinstance(obs, dict):
        phases = obs.get("phase_p50_ms")
        if isinstance(phases, dict):
            for pname, v in phases.items():
                v = _num(v)
                if v is not None:
                    out[f"phase.{pname}.p50_ms"] = (v, "lower")
    # per-kernel roofline (ISSUE 11): achieved GB/s per pipeline label —
    # higher is better; compared only when BOTH rounds carry the section
    # (falls back to the copy nested under observability for rounds that
    # predate the top-level promotion)
    roof = detail.get("roofline")
    if not isinstance(roof, dict):
        obs_sec = detail.get("observability")
        roof = obs_sec.get("roofline") if isinstance(obs_sec, dict) else None
    if isinstance(roof, dict):
        for kname, entry in (roof.get("kernels") or {}).items():
            if isinstance(entry, dict):
                g = _num(entry.get("gbps"))
                if g is not None:
                    out[f"roofline.{kname}.gbps"] = (g, "higher")
    clu = detail.get("cluster")
    if isinstance(clu, dict):
        servers = clu.get("servers")
        if isinstance(servers, dict):
            for lname, entry in servers.items():
                if isinstance(entry, dict):
                    qps = _num(entry.get("qps"))
                    if qps is not None:
                        out[f"cluster.{lname}.qps"] = (qps, "higher")
        eff = _num(clu.get("scaling_efficiency_2"))
        if eff is not None:
            out["cluster.scaling_efficiency_2"] = (eff, "higher")
        rc = clu.get("result_cache")
        if isinstance(rc, dict):
            p50 = _num(rc.get("hit_p50_ms"))
            if p50 is not None:
                out["cluster.result_cache.hit_p50_ms"] = (p50, "lower")
    # tiered lifecycle (ISSUE 12): per-tier p50s, hydration latency, and
    # the peak-RSS backstop — compared only when both rounds ran the phase
    tier = detail.get("tiering")
    if isinstance(tier, dict):
        per_tier = tier.get("per_tier")
        if isinstance(per_tier, dict):
            for tname in ("hot", "warm"):
                entry = per_tier.get(tname)
                if isinstance(entry, dict):
                    v = _num(entry.get("p50_ms"))
                    if v is not None:
                        out[f"tiering.{tname}.p50_ms"] = (v, "lower")
            cold = per_tier.get("cold")
            if isinstance(cold, dict):
                v = _num(cold.get("hydrate_ms"))
                if v is not None:
                    out["tiering.cold.hydrate_ms"] = (v, "lower")
        v = _num(tier.get("peak_rss_delta_mb"))
        if v is not None:
            out["tiering.peak_rss_delta_mb"] = (v, "lower")
    # overload-survival phase (ISSUE 14): the knee of the arrival-rate
    # ladder (higher is better), the p99 the cluster holds at 2x that
    # knee and the isolated tenant's p99 delta under the 10x spike
    # (lower is better), compared only when both rounds ran the phase;
    # shed/stale counts are load-dependent and stay informational
    ov = detail.get("overload")
    if isinstance(ov, dict):
        v = _num(ov.get("knee_qps"))
        if v is not None:
            out["overload.knee_qps"] = (v, "higher")
        v = _num(ov.get("p99_at_2x_knee_ms"))
        if v is not None:
            out["overload.p99_at_2x_knee_ms"] = (v, "lower")
        tb = ov.get("tenant_b")
        if isinstance(tb, dict):
            v = _num(tb.get("spike_p99_ms"))
            if v is not None:
                out["overload.tenant_b.spike_p99_ms"] = (v, "lower")
    # join phase (ISSUE 16): star-join p50 plus the distributed
    # stage-2 exchange trend line — QPS gates, wire volume and spill
    # count ride along informationally (see diff_rounds: info metrics
    # are reported but never regress)
    joi = detail.get("join")
    if isinstance(joi, dict):
        v = _num(joi.get("join_p50_ms"))
        if v is not None:
            out["join.join_p50_ms"] = (v, "lower")
        v = _num(joi.get("stage2_qps"))
        if v is not None:
            out["join.stage2_qps"] = (v, "higher")
        for k in ("exchange_bytes", "spill_count"):
            v = _num(joi.get(k))
            if v is not None:
                out[f"join.{k}"] = (v, "info")
    # adaptive phase (ISSUE 17): post-convergence p50 per mis-tuned
    # scenario gates (the advisor must keep rescuing the bad default);
    # queries-to-converge rides along informationally — it moves with
    # min_samples/reprobe tuning, both legitimate knobs
    ada = detail.get("adaptive")
    if isinstance(ada, dict):
        for sname, entry in ada.items():
            if isinstance(entry, dict):
                v = _num(entry.get("converged_p50_ms"))
                if v is not None:
                    out[f"adaptive.{sname}.converged_p50_ms"] = (v, "lower")
                v = _num(entry.get("queries_to_converge"))
                if v is not None:
                    out[f"adaptive.{sname}.queries_to_converge"] = (v, "info")
    # frontdoor phase (ISSUE 18): broker-tier scaling efficiency gates
    # (2-broker QPS over 1-broker, ceiling-normalized upstream in bench);
    # the streaming path's broker RSS delta is lower-is-better — a
    # regression means the front door started materializing again
    fd = detail.get("frontdoor")
    if isinstance(fd, dict):
        v = _num(fd.get("qps2_over_qps1"))
        if v is not None:
            out["frontdoor.qps2_over_qps1"] = (v, "higher")
        v = _num(fd.get("stream_rss_delta_mb"))
        if v is not None:
            out["frontdoor.stream_rss_delta_mb"] = (v, "lower")
    sub = detail.get("subrtt")
    if isinstance(sub, dict):
        # link_floor_ms is deliberately NOT compared: it is a property of
        # the box/tunnel, not the code (the served_p50 gate already
        # normalizes by it), same noise class as the ungated phases
        for k in ("served_p50_ms", "qps8"):
            v = _num(sub.get(k))
            if v is not None:
                direction = "higher" if k == "qps8" else "lower"
                out[f"subrtt.{k}"] = (v, direction)
    return out


def diff_rounds(old: dict, new: dict, threshold: float,
                gate_phases: bool = False) -> dict:
    """{regressions, improvements, unchanged, added, removed} over the
    shared metric set. A metric regresses when it moves past
    ``threshold`` (fraction) in its bad direction."""
    mo, mn = extract_metrics(old), extract_metrics(new)
    report = {"regressions": {}, "improvements": {}, "unchanged": {},
              "added": sorted(set(mn) - set(mo)),
              "removed": sorted(set(mo) - set(mn))}
    for name in sorted(set(mo) & set(mn)):
        vo, direction = mo[name]
        vn, _ = mn[name]
        if vo == 0:
            report["unchanged"][name] = {"old": vo, "new": vn}
            continue
        ratio = vn / vo
        entry = {"old": vo, "new": vn, "ratio": round(ratio, 3)}
        if direction == "info":
            # trend-only metric (exchange wire volume, spill count):
            # reported, never a regression or an improvement
            report["unchanged"][name] = entry
            continue
        worse = ratio > 1 + threshold if direction == "lower" \
            else ratio < 1 - threshold
        better = ratio < 1 - threshold if direction == "lower" \
            else ratio > 1 + threshold
        gated = gate_phases or not name.startswith("phase.")
        if worse and gated:
            report["regressions"][name] = entry
        elif better:
            report["improvements"][name] = entry
        else:
            report["unchanged"][name] = entry
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.benchdiff",
        description="compare two recorded bench rounds; non-zero exit on "
                    "regression past --threshold")
    ap.add_argument("old", help="reference round (BENCH_rNN.json)")
    ap.add_argument("new", help="candidate round")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="regression tolerance as a fraction (default 0.25)")
    ap.add_argument("--gate-phases", action="store_true",
                    help="also gate the per-phase waterfall (noisy: queue/"
                         "link phases swing with load; informational "
                         "otherwise)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    try:
        old = load_round(args.old)
        new = load_round(args.new)
    except (OSError, ValueError) as e:
        print(f"benchdiff: cannot read rounds: {e}", file=sys.stderr)
        return 2
    report = diff_rounds(old, new, args.threshold, args.gate_phases)
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        for bucket in ("regressions", "improvements"):
            rows = report[bucket]
            if rows:
                print(f"{bucket} (threshold {args.threshold:.0%}):")
                for name, e in rows.items():
                    print(f"  {name}: {e['old']} -> {e['new']} "
                          f"(x{e['ratio']})")
        print(f"{len(report['unchanged'])} within threshold, "
              f"{len(report['added'])} added, "
              f"{len(report['removed'])} removed")
        if not report["regressions"]:
            print("no regressions")
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
