"""Cluster temperature CLI: ``python -m pinot_tpu.tools.clusterstat URL``.

Renders the controller's segment-temperature aggregation (ISSUE 11 —
``GET /tables/{t}/heat``, fed by the servers' heartbeat-piggybacked
heat snapshots): per table, the hottest segments with their decayed
access/bytes rates, lifetime totals, and reporting-instance counts —
the operator's view of what ROADMAP 3's tier lifecycle would promote
or demote next.

Options:
    --table T      one table (default: every table the controller lists)
    --top N        segments to print per table (default 10)
    --tiers        also fetch /tables/{t}/tiers and print each segment's
                   tier (hot/warm/cold, ISSUE 12) next to its heat
    --user u:p     basic auth for an ACL'd controller
    --json         machine-readable output (one dict)
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import time
import urllib.error
import urllib.request


def _get(base_url: str, path: str, user: str = None) -> dict:
    req = urllib.request.Request(base_url.rstrip("/") + path)
    if user:
        token = base64.b64encode(user.encode()).decode()
        req.add_header("Authorization", f"Basic {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def gather(base_url: str, table: str = None, user: str = None,
           tiers: bool = False) -> dict:
    """{table: heat dict} from the controller REST; with ``tiers=True``
    each heat dict also carries a ``tiers`` section
    (``/tables/{t}/tiers``, ISSUE 12)."""
    if table:
        tables = [table]
    else:
        tables = _get(base_url, "/tables", user).get("tables", [])
    out = {}
    for t in tables:
        doc = _get(base_url, f"/tables/{t}/heat", user)
        if tiers:
            doc["tiers"] = _get(base_url, f"/tables/{t}/tiers", user)
        out[t] = doc
    return out


def render(heat_by_table: dict, top: int = 10, now: float = None,
           tiers: bool = False) -> str:
    now = time.time() if now is None else now
    lines = []
    for table, heat in sorted(heat_by_table.items()):
        segs = heat.get("segments") or {}
        tier_segs = (heat.get("tiers") or {}).get("segments") or {}
        lines.append(
            f"table {table}: {len(segs)} segment(s) reporting heat "
            f"across {heat.get('instancesReporting', 0)} instance(s)")
        names = list(segs)[:max(1, top)]
        if tiers:
            # tiered-but-cold segments fall out of the heat top-N by
            # construction; list them too so the operator sees the
            # lifecycle's other end
            names += [n for n in tier_segs if n not in segs][:max(1, top)]
        for name in names:
            rec = segs.get(name, {})
            last = rec.get("lastAccessTs") or 0
            ago = f"{max(0.0, now - last):.0f}s ago" if last else "never"
            tier_txt = ""
            if tiers:
                tier_txt = f"tier={tier_segs.get(name, {}).get('tier', '?')} "
            lines.append(
                f"  {name}: {tier_txt}rate={rec.get('rate')} "
                f"bytesRate={rec.get('bytesRate')} "
                f"accesses={rec.get('accesses')} bytes={rec.get('bytes')} "
                f"replicas={rec.get('instances')} last={ago}")
        if not segs:
            lines.append("  (no heat reported yet — servers heartbeat "
                         "their snapshots every few seconds)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.clusterstat",
        description="segment-temperature view from a pinot-tpu controller")
    ap.add_argument("controller", help="controller base URL "
                                       "(e.g. http://127.0.0.1:9000)")
    ap.add_argument("--table", default=None)
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--tiers", action="store_true",
                    help="show each segment's hot/warm/cold tier next to "
                         "its heat (ISSUE 12 lifecycle view)")
    ap.add_argument("--user", default=None, help="basic auth user:pass")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    try:
        heat = gather(args.controller, table=args.table, user=args.user,
                      tiers=args.tiers)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"cannot reach controller {args.controller}: {e}",
              file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(heat, indent=2))
    else:
        print(render(heat, top=args.top, tiers=args.tiers))
    return 0


if __name__ == "__main__":
    sys.exit(main())
