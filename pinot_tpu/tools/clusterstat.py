"""Cluster temperature CLI: ``python -m pinot_tpu.tools.clusterstat URL``.

Renders the controller's segment-temperature aggregation (ISSUE 11 —
``GET /tables/{t}/heat``, fed by the servers' heartbeat-piggybacked
heat snapshots): per table, the hottest segments with their decayed
access/bytes rates, lifetime totals, and reporting-instance counts —
the operator's view of what ROADMAP 3's tier lifecycle would promote
or demote next.

Options:
    --table T      one table (default: every table the controller lists)
    --top N        segments to print per table (default 10)
    --tiers        also fetch /tables/{t}/tiers and print each segment's
                   tier (hot/warm/cold, ISSUE 12) next to its heat
    --load         fetch /cluster/load instead: per-instance scheduler
                   pressure, heartbeat age/liveness, and the controller
                   autoscaler's state (watermarks, sustain counters,
                   last scale action — ISSUE 14)
    --brokers      fetch /brokers instead: the broker fleet with
                   live/draining state and per-broker QPS + cache hit
                   rate from heartbeat-piggybacked counters (ISSUE 18)
    --user u:p     basic auth for an ACL'd controller
    --json         machine-readable output (one dict)
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import time
import urllib.error
import urllib.request


def _get(base_url: str, path: str, user: str = None) -> dict:
    req = urllib.request.Request(base_url.rstrip("/") + path)
    if user:
        token = base64.b64encode(user.encode()).decode()
        req.add_header("Authorization", f"Basic {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def gather(base_url: str, table: str = None, user: str = None,
           tiers: bool = False) -> dict:
    """{table: heat dict} from the controller REST; with ``tiers=True``
    each heat dict also carries a ``tiers`` section
    (``/tables/{t}/tiers``, ISSUE 12)."""
    if table:
        tables = [table]
    else:
        tables = _get(base_url, "/tables", user).get("tables", [])
    out = {}
    for t in tables:
        doc = _get(base_url, f"/tables/{t}/heat", user)
        if tiers:
            doc["tiers"] = _get(base_url, f"/tables/{t}/tiers", user)
        out[t] = doc
    return out


def gather_load(base_url: str, user: str = None) -> dict:
    """The controller's /cluster/load doc (ISSUE 14): per-instance
    pressure + heartbeat ages + autoscaler state."""
    return _get(base_url, "/cluster/load", user)


def gather_brokers(base_url: str, user: str = None) -> dict:
    """The controller's /brokers doc (ISSUE 18): the fleet with
    liveness, drain state, and heartbeat-piggybacked QPS / cache-hit
    counters."""
    return _get(base_url, "/brokers", user)


def render_brokers(doc: dict) -> str:
    brokers = doc.get("brokers") or {}
    lines = [f"{len(brokers)} broker(s):"]
    for name in sorted(brokers):
        rec = brokers[name]
        state = "DRAINING" if rec.get("draining") \
            else ("live" if rec.get("live") else "STALE")
        lines.append(
            f"  {name}: [{state}] url={rec.get('url')} "
            f"qps={rec.get('qps')} queries={rec.get('queries')} "
            f"cacheHitRate={rec.get('cacheHitRate', 0.0):.1%} "
            f"hb={rec.get('heartbeatAgeMs')}ms")
    if not brokers:
        lines.append("  (no brokers registered — start one with "
                     "admin start-broker)")
    return "\n".join(lines)


def render_load(doc: dict) -> str:
    lines = []
    insts = doc.get("instances") or {}
    lines.append(f"{len(insts)} server instance(s):")
    for name in sorted(insts):
        rec = insts[name]
        live = "live" if rec.get("live") else "STALE"
        lines.append(
            f"  {name}: pressure={rec.get('pressure')} "
            f"hb={rec.get('heartbeatAgeMs')}ms [{live}] "
            f"endpoint={rec.get('endpoint')}")
    a = doc.get("autoscaler") or {}
    if not a:
        lines.append("autoscaler: not attached")
        return "\n".join(lines)
    lines.append(
        f"autoscaler: {a.get('servers')} server(s) "
        f"[{a.get('min')}..{a.get('max')}] "
        f"meanPressure={a.get('meanPressure')} "
        f"water={a.get('lowWater')}/{a.get('highWater')} "
        f"sustain(above={a.get('aboveTicks')}, below={a.get('belowTicks')}, "
        f"cooldown={a.get('cooldownTicks')}) "
        f"scaleOuts={a.get('scaleOuts')} scaleIns={a.get('scaleIns')}")
    last = a.get("lastAction")
    if last:
        lines.append(f"  last action: {last.get('action')} "
                     f"{last.get('instance')} -> "
                     f"{last.get('servers_after')} servers "
                     f"(pressure {last.get('mean_pressure')})")
    return "\n".join(lines)


def render(heat_by_table: dict, top: int = 10, now: float = None,
           tiers: bool = False) -> str:
    now = time.time() if now is None else now
    lines = []
    for table, heat in sorted(heat_by_table.items()):
        segs = heat.get("segments") or {}
        tier_segs = (heat.get("tiers") or {}).get("segments") or {}
        lines.append(
            f"table {table}: {len(segs)} segment(s) reporting heat "
            f"across {heat.get('instancesReporting', 0)} instance(s)")
        names = list(segs)[:max(1, top)]
        if tiers:
            # tiered-but-cold segments fall out of the heat top-N by
            # construction; list them too so the operator sees the
            # lifecycle's other end
            names += [n for n in tier_segs if n not in segs][:max(1, top)]
        for name in names:
            rec = segs.get(name, {})
            last = rec.get("lastAccessTs") or 0
            ago = f"{max(0.0, now - last):.0f}s ago" if last else "never"
            tier_txt = ""
            if tiers:
                tier_txt = f"tier={tier_segs.get(name, {}).get('tier', '?')} "
            lines.append(
                f"  {name}: {tier_txt}rate={rec.get('rate')} "
                f"bytesRate={rec.get('bytesRate')} "
                f"accesses={rec.get('accesses')} bytes={rec.get('bytes')} "
                f"replicas={rec.get('instances')} last={ago}")
        if not segs:
            lines.append("  (no heat reported yet — servers heartbeat "
                         "their snapshots every few seconds)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.clusterstat",
        description="segment-temperature view from a pinot-tpu controller")
    ap.add_argument("controller", help="controller base URL "
                                       "(e.g. http://127.0.0.1:9000)")
    ap.add_argument("--table", default=None)
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--tiers", action="store_true",
                    help="show each segment's hot/warm/cold tier next to "
                         "its heat (ISSUE 12 lifecycle view)")
    ap.add_argument("--load", action="store_true", dest="load",
                    help="show per-instance pressure, heartbeat "
                         "liveness, and autoscaler state instead of "
                         "segment heat (ISSUE 14 overload view)")
    ap.add_argument("--brokers", action="store_true", dest="brokers",
                    help="show the broker fleet: live/draining state "
                         "and per-broker QPS + cache hit rate from "
                         "heartbeat-piggybacked counters (ISSUE 18)")
    ap.add_argument("--user", default=None, help="basic auth user:pass")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    try:
        if args.brokers:
            doc = gather_brokers(args.controller, user=args.user)
        elif args.load:
            doc = gather_load(args.controller, user=args.user)
        else:
            heat = gather(args.controller, table=args.table,
                          user=args.user, tiers=args.tiers)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"cannot reach controller {args.controller}: {e}",
              file=sys.stderr)
        return 2
    if args.brokers:
        print(json.dumps(doc, indent=2) if args.as_json
              else render_brokers(doc))
        return 0
    if args.load:
        print(json.dumps(doc, indent=2) if args.as_json
              else render_load(doc))
        return 0
    if args.as_json:
        print(json.dumps(heat, indent=2))
    else:
        print(render(heat, top=args.top, tiers=args.tiers))
    return 0


if __name__ == "__main__":
    sys.exit(main())
