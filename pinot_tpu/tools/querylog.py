"""Query-log summarizer: ``python -m pinot_tpu.tools.querylog <log.jsonl>...``.

Reads the broker's structured JSONL query log (broker/querylog.py) and
prints the operator's five-minute view: volume + error/timeout/partial
counts, latency percentiles overall and per table/template, the
per-phase p50 breakdown reconstructed from the attached traces (queue /
compile / gather / kernel / link / reduce — the waterfall that tells
kernel-ms from link-ms from queue-ms), and the top-N slowest queries.

Accepts MULTIPLE log paths (ISSUE 18): a broker fleet writes one JSONL
per broker, each entry stamped with its ``brokerId`` — passing them all
merges the entries into one fleet-wide summary (per-template stats
aggregate across brokers) plus a per-broker volume/latency breakdown.

Options:
    --top N        how many slow queries to list (default 5)
    --per-template aggregate by literal-free template key too
    --json         machine-readable output (one summary dict)
"""

from __future__ import annotations

import argparse
import json
import sys


# phase buckets for the waterfall, matched on the span name's LAST dotted
# segment (nesting depth varies: "gather" from an embedded engine,
# "server.execute.gather" from a cluster server) — full-name buckets
# first. Matching a raw suffix substring would misbucket e.g.
# "broker.scatter_gather" as the gather phase.
PHASE_FULL_NAMES = {
    "server.queue": "queue",
    "server.compile": "compile",
    "server.trim": "reduce",
    "broker.reduce": "reduce",
    # the broker's scatter wall (the span behind the broker.scatterMs
    # timer) — previously missing, so the waterfall under-reported the
    # broker's share of every distributed query (ISSUE 11 satellite)
    "broker.scatter_gather": "scatter",
    "broker.route": "route",
    # embedded multistage execution (query2/runner.py run_local): the
    # broker-local join/window stage
    "stage2": "stage2",
}
PHASE_LAST_SEGMENTS = {
    "gather": "gather",
    "kernel": "kernel",
    "link": "link",
    "host_scan": "host_scan",
    "host_fallback": "host_fallback",
    "merge": "reduce",
}


def _phase_bucket(name: str):
    bucket = PHASE_FULL_NAMES.get(name)
    if bucket is not None:
        return bucket
    return PHASE_LAST_SEGMENTS.get(name.rsplit(".", 1)[-1])


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[idx])


def phase_breakdown(entry: dict) -> dict:
    """Per-phase ms for one log entry, summed across its servers.

    traceInfo values are span lists for single-stage queries, but the
    multistage path nests a whole per-leaf traceInfo DICT under each
    ``leaf:<alias>`` key ({instance: [spans], "broker": [spans]}) —
    recurse through dicts so join/window entries (and EXPLAIN ANALYZE on
    them) sum the same waterfall instead of crashing on string keys."""
    out: dict = {}

    def _walk(spans_or_nested):
        if isinstance(spans_or_nested, dict):
            for v in spans_or_nested.values():
                _walk(v)
            return
        for s in spans_or_nested or ():
            if not isinstance(s, dict):
                continue
            bucket = _phase_bucket(s.get("phase", ""))
            if bucket is not None:
                out[bucket] = out.get(bucket, 0.0) + s["durationMs"]

    _walk(entry.get("traceInfo") or {})
    return out


def _advisor_state(kind_sets: list) -> str:
    """Convergence label for one template's advisor override history
    (entry-ordered decision-kind sets). "cold" = no execution ever
    stamped an override; "converged" = the trailing executions all ran
    with the same override set (the memo stopped changing its mind);
    "adapting" = the override set is still moving."""
    if not any(kind_sets):
        return "cold"
    tail = kind_sets[-min(3, len(kind_sets)):]
    return "converged" if len(set(tail)) == 1 else "adapting"


def summarize(entries: list, top: int = 5,
              per_template: bool = False) -> dict:
    lats = sorted(e.get("timeUsedMs", 0.0) for e in entries)
    summary = {
        "queries": len(entries),
        "errors": sum(1 for e in entries if e.get("exceptions")),
        "partials": sum(1 for e in entries if e.get("partialResult")),
        "timeouts": sum(
            1 for e in entries
            if any(x.get("errorCode") == 250
                   for x in e.get("exceptions") or ())),
        "latencyMs": {
            "p50": round(_percentile(lats, 0.50), 2),
            "p90": round(_percentile(lats, 0.90), 2),
            "p99": round(_percentile(lats, 0.99), 2),
        },
    }
    phases: dict = {}
    for e in entries:
        for k, v in phase_breakdown(e).items():
            phases.setdefault(k, []).append(v)
    summary["phaseP50Ms"] = {
        k: round(_percentile(sorted(v), 0.5), 3)
        for k, v in sorted(phases.items())
    }
    by_table: dict = {}
    for e in entries:
        by_table.setdefault(e.get("table") or "?", []).append(
            e.get("timeUsedMs", 0.0))
    summary["tables"] = {
        t: {"queries": len(v),
            "p50Ms": round(_percentile(sorted(v), 0.5), 2),
            "p90Ms": round(_percentile(sorted(v), 0.9), 2)}
        for t, v in sorted(by_table.items())
    }
    if per_template:
        by_tpl: dict = {}
        for e in entries:
            counters = e.get("counters") or {}
            # the decisions a plan advisor override stamped on this
            # execution — e.g. "ADVISOR(candBound=1/32: ...)" — keyed on
            # the decision name left of '=' so per-template aggregation
            # sees "the advisor overrides candBound here", not one row
            # per measured value (ISSUE 17 satellite)
            stamps = counters.get("advisorDecisions") or ()
            kinds = frozenset(
                s.split("(", 1)[-1].split("=", 1)[0] for s in stamps)
            by_tpl.setdefault(e.get("template") or "?", []).append(
                (e.get("timeUsedMs", 0.0),
                 bool(counters.get("partialsCacheHit")),
                 bool(counters.get("resultCacheHit")),
                 kinds))
        summary["templates"] = {
            t: {"queries": len(v),
                "p50Ms": round(
                    _percentile(sorted(x for x, _, _, _ in v), 0.5), 2),
                # device partials-cache hit rate for this literal-free
                # template — the repeat-dashboard-query signal the cache
                # exists to serve
                "cacheHitRate": round(
                    sum(1 for _, h, _, _ in v if h) / len(v), 3),
                # broker result-cache hit rate (PR 10's resultCacheHit):
                # hits answer with NO scatter at all, so a template whose
                # latency looks great may simply be cache-hot — the two
                # rates disambiguate (ISSUE 11 satellite)
                "resultCacheHitRate": round(
                    sum(1 for _, _, h, _ in v if h) / len(v), 3),
                # plan advisor (ISSUE 17): how often the memo overrode a
                # static default for this template, which knobs it turned,
                # and whether the decision set has settled — "converged"
                # once the latest executions all stamp the same override
                # set (possibly empty after warm-up confirmed the
                # defaults), "adapting" while it still changes, "cold"
                # before any query ran with advisor overrides recorded
                "advisorOverrides": sum(len(k) for _, _, _, k in v),
                "advisorOverrideRate": round(
                    sum(1 for _, _, _, k in v if k) / len(v), 3),
                "advisorDecisions": sorted(
                    set().union(*(k for _, _, _, k in v))),
                "advisorState": _advisor_state([k for _, _, _, k in v])}
            for t, v in sorted(by_tpl.items())
        }
    # fleet breakdown (ISSUE 18): when entries carry brokerId stamps
    # (broker/querylog.py), break volume/error/latency down per broker —
    # the merged-fleet view's answer to "is one broker the slow one?"
    by_broker: dict = {}
    for e in entries:
        bid = e.get("brokerId")
        if bid:
            by_broker.setdefault(bid, []).append(e)
    if by_broker:
        summary["brokers"] = {
            b: {"queries": len(v),
                "errors": sum(1 for e in v if e.get("exceptions")),
                "p50Ms": round(_percentile(
                    sorted(e.get("timeUsedMs", 0.0) for e in v), 0.5), 2),
                "p90Ms": round(_percentile(
                    sorted(e.get("timeUsedMs", 0.0) for e in v), 0.9), 2)}
            for b, v in sorted(by_broker.items())
        }
    slowest = sorted(entries, key=lambda e: e.get("timeUsedMs", 0.0),
                     reverse=True)[:top]
    summary["slowest"] = [
        {"timeUsedMs": e.get("timeUsedMs"), "table": e.get("table"),
         "requestId": e.get("requestId"), "traceId": e.get("traceId"),
         "sql": (e.get("sql") or "")[:120],
         "phases": {k: round(v, 2)
                    for k, v in sorted(phase_breakdown(e).items())}}
        for e in slowest
    ]
    return summary


def load(path: str) -> list:
    entries = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from rotation/crash
    return entries


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.querylog",
        description="summarize a pinot-tpu broker query log (JSONL)")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="query log file(s) — pass one per broker to "
                         "merge a fleet's logs (ISSUE 18)")
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--per-template", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    entries = []
    for path in args.paths:
        try:
            entries.extend(load(path))
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
    if not entries:
        print("no entries", file=sys.stderr)
        return 1
    summary = summarize(entries, top=args.top,
                        per_template=args.per_template)
    if args.as_json:
        print(json.dumps(summary, indent=2))
        return 0
    lat = summary["latencyMs"]
    print(f"{summary['queries']} logged queries | "
          f"{summary['errors']} errors ({summary['timeouts']} timeouts), "
          f"{summary['partials']} partial")
    print(f"latency p50/p90/p99: {lat['p50']} / {lat['p90']} / "
          f"{lat['p99']} ms")
    if summary["phaseP50Ms"]:
        print("phase p50s (ms): " + ", ".join(
            f"{k}={v}" for k, v in summary["phaseP50Ms"].items()))
    for b, row in (summary.get("brokers") or {}).items():
        print(f"  broker {b}: n={row['queries']} errors={row['errors']} "
              f"p50={row['p50Ms']}ms p90={row['p90Ms']}ms")
    for t, row in summary["tables"].items():
        print(f"  table {t}: n={row['queries']} p50={row['p50Ms']}ms "
              f"p90={row['p90Ms']}ms")
    if "templates" in summary:
        for t, row in summary["templates"].items():
            adv = ""
            if row["advisorState"] != "cold":
                kinds = ",".join(row["advisorDecisions"]) or "-"
                adv = (f" advisor={row['advisorState']} "
                       f"overrides={row['advisorOverrides']} "
                       f"({kinds})")
            print(f"  template {t}: n={row['queries']} p50={row['p50Ms']}ms "
                  f"partialsCache={row['cacheHitRate']:.1%} "
                  f"resultCache={row['resultCacheHitRate']:.1%}{adv}")
    print(f"top {len(summary['slowest'])} slowest:")
    for e in summary["slowest"]:
        phases = " ".join(f"{k}={v}" for k, v in (e["phases"] or {}).items())
        print(f"  {e['timeUsedMs']}ms [{e.get('table')}] "
              f"req={e.get('requestId')} {e['sql']!r} {phases}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
