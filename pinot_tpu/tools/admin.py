"""Admin CLI: the ``pinot-admin.sh`` analog.

Equivalent surface to the reference's command-line tools
(pinot-tools/.../admin/PinotAdministrator.java and its StartController/
StartServer/StartBroker/LaunchDataIngestionJob/PostQuery/AddTable
commands). Multi-process clusters share a FileRegistry JSON file the way
the reference's roles share ZooKeeper; each ``start-*`` command blocks
until interrupted.

Usage examples::

    python -m pinot_tpu.tools.admin quickstart
    python -m pinot_tpu.tools.admin start-controller --registry /tmp/c.json
    python -m pinot_tpu.tools.admin start-server   --registry /tmp/c.json --id server_1
    python -m pinot_tpu.tools.admin start-broker   --registry /tmp/c.json --port 8099
    python -m pinot_tpu.tools.admin add-table --registry /tmp/c.json \
        --schema schema.json --config table.json
    python -m pinot_tpu.tools.admin ingest --registry /tmp/c.json --spec job.json
    python -m pinot_tpu.tools.admin query --broker-url http://127.0.0.1:8099 \
        --sql "SELECT COUNT(*) FROM t"
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _registry(path: str):
    from pinot_tpu.cluster.registry import FileRegistry

    return FileRegistry(path)


def _block():
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


def cmd_quickstart(args) -> int:
    from pinot_tpu.tools.quickstart import run_quickstart

    handle = run_quickstart()
    print("cluster running; Ctrl-C to stop")
    _block()
    handle.stop()
    return 0


def cmd_start_controller(args) -> int:
    from pinot_tpu.controller.controller import Controller

    controller = Controller(_registry(args.registry), args.deep_store,
                            controller_id=args.id)
    controller.start_periodic_tasks(interval_s=args.period_s)
    print(f"controller {args.id} running (registry={args.registry}, "
          f"deep store={args.deep_store})")
    _block()
    controller.stop_periodic_tasks()
    return 0


def cmd_start_server(args) -> int:
    from pinot_tpu.server.server import ServerInstance

    server = ServerInstance(args.id, _registry(args.registry), args.data_dir,
                            host=args.host, port=args.port,
                            max_concurrent_queries=args.max_concurrent,
                            device_executor=None if args.no_device
                            else "auto")
    server.start()
    print(f"server {args.id} running on gRPC port {server.transport.port}")
    _block()
    server.stop()
    return 0


def cmd_start_broker(args) -> int:
    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.broker.fleet import BrokerFleetMember
    from pinot_tpu.broker.http_api import BrokerHttpServer

    # generous default: the first aggregate on a fresh server pays XLA
    # compile (~20-40s) before the template cache warms up
    registry = _registry(args.registry)
    broker = Broker(registry, broker_id=args.id, timeout_s=args.timeout_s)
    users = None
    if args.auth:
        users = {}
        for a in args.auth:
            if ":" not in a:
                print(f"--auth expects user:password, got {a!r}",
                      file=sys.stderr)
                return 2
            u, _, p = a.partition(":")
            users[u] = p
    http = BrokerHttpServer(broker, host=args.host, port=args.port,
                            users=users)
    http.start()
    # fleet membership (ISSUE 18): register under Role.BROKER with the
    # serving URL so clients discover/rotate and peers gossip admission
    # spend — the BrokerStarter's Helix broker-resource registration
    fleet = BrokerFleetMember(registry, broker, http_url=http.url,
                              host=http.host, port=http.port)
    fleet.start()
    print(f"broker {args.id} serving {http.url}/query/sql")
    _block()
    fleet.stop()
    http.stop()
    broker.close()
    return 0


def cmd_start_minion(args) -> int:
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.minion.worker import MinionWorker

    registry = _registry(args.registry)
    controller = Controller(registry, args.deep_store,
                            controller_id=f"{args.id}_ctl")
    minion = MinionWorker(registry, controller, args.work_dir,
                          instance_id=args.id)
    minion.start()
    print(f"minion {args.id} polling the task queue")
    _block()
    minion.stop()
    return 0


def cmd_add_table(args) -> int:
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller

    schema = Schema.load(args.schema)
    with open(args.config) as f:
        config = TableConfig.from_json(json.load(f))
    controller = Controller(_registry(args.registry), args.deep_store)
    controller.add_table(config, schema)
    print(f"table {config.table_name_with_type} created")
    return 0


def cmd_ingest(args) -> int:
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.ingestion.job import IngestionJobSpec, run_ingestion_job

    spec = IngestionJobSpec.load(args.spec)
    controller = Controller(_registry(args.registry), args.deep_store)
    built = run_ingestion_job(spec, controller)
    print(f"built+pushed {len(built)} segments:")
    for d in built:
        print(f"  {d}")
    return 0


def cmd_query(args) -> int:
    if args.broker_url:
        import urllib.request

        req = urllib.request.Request(
            args.broker_url.rstrip("/") + "/query/sql",
            data=json.dumps({"sql": args.sql}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=args.timeout_s) as resp:
            out = json.loads(resp.read())
    else:
        from pinot_tpu.broker.broker import Broker

        broker = Broker(_registry(args.registry), timeout_s=args.timeout_s)
        try:
            out = broker.execute(args.sql)
        finally:
            broker.close()
    json.dump(out, sys.stdout, indent=2, default=str)
    print()
    return 1 if out.get("exceptions") else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pinot_tpu.tools.admin",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("quickstart", help="in-process demo cluster with sample data") \
        .set_defaults(fn=cmd_quickstart)

    sp = sub.add_parser("start-controller")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--deep-store", default="./deepstore")
    sp.add_argument("--id", default="controller_0")
    sp.add_argument("--period-s", type=float, default=60.0)
    sp.set_defaults(fn=cmd_start_controller)

    sp = sub.add_parser("start-server")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--data-dir", default="./serverdata")
    sp.add_argument("--id", default="server_0")
    sp.add_argument("--host", default="127.0.0.1",
                    help="bind + advertised gRPC host (container/pod "
                         "hostname or IP in multi-host deployments)")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--no-device", action="store_true",
                    help="host-only executor (skip jax/XLA entirely: "
                         "fast startup for CPU-bound cluster tiers and "
                         "the bench's multi-process scaling phase)")
    sp.add_argument("--max-concurrent", type=int, default=8,
                    help="scheduler admission width (concurrent queries "
                         "per server; excess queues). Size to the cores "
                         "this process may actually use — past that, "
                         "concurrent queries thrash instead of queueing")
    sp.set_defaults(fn=cmd_start_server)

    sp = sub.add_parser("start-broker")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--id", default="broker_0")
    sp.add_argument("--host", default="127.0.0.1",
                    help="HTTP bind host (0.0.0.0 in containers)")
    sp.add_argument("--port", type=int, default=8099)
    sp.add_argument("--auth", action="append",
                    help="user:password (repeatable); enables HTTP basic "
                         "auth on the query endpoints")
    sp.add_argument("--timeout-s", type=float, default=60.0)
    sp.set_defaults(fn=cmd_start_broker)

    sp = sub.add_parser("start-minion")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--deep-store", default="./deepstore")
    sp.add_argument("--work-dir", default="./minionwork")
    sp.add_argument("--id", default="minion_0")
    sp.set_defaults(fn=cmd_start_minion)

    sp = sub.add_parser("add-table")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--schema", required=True)
    sp.add_argument("--config", required=True)
    sp.add_argument("--deep-store", default="./deepstore")
    sp.set_defaults(fn=cmd_add_table)

    sp = sub.add_parser("ingest")
    sp.add_argument("--registry", required=True)
    sp.add_argument("--spec", required=True)
    sp.add_argument("--deep-store", default="./deepstore")
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser("query")
    sp.add_argument("--sql", required=True)
    sp.add_argument("--registry")
    sp.add_argument("--broker-url")
    sp.add_argument("--timeout-s", type=float, default=30.0)
    sp.set_defaults(fn=cmd_query)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "query" and not (args.registry or args.broker_url):
        print("query needs --registry or --broker-url", file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
