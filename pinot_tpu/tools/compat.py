"""Compatibility verifier: yaml-defined op suites against a live cluster.

Equivalent of the reference's compatibility verifier
(pinot-compatibility-verifier/.../compat/CompatibilityOpsRunner.java driven
by ``compatibility-verifier/compCheck.sh``): a suite file lists ops —
``tableOp`` (create/delete), ``segmentOp`` (upload/delete), ``queryOp``
(run SQL, compare rows), ``streamOp`` (produce events, await counts) —
executed in order against a cluster, so the same suite can gate behavior
across versions/upgrades. Here the cluster is the in-process quickstart
topology (or any supplied handle with controller/broker/registry).

Suite format (yaml or json)::

    operations:
      - type: tableOp
        op: CREATE
        schema: {name: t, dimensions: [[city, STRING]], metrics: [[v, LONG]]}
        tableConfig: {table_name: t}
      - type: segmentOp
        op: UPLOAD
        table: t
        segmentName: s0
        rows: [{city: sf, v: 3}, {city: nyc, v: 4}]
      - type: queryOp
        sql: SELECT city, SUM(v) FROM t GROUP BY city ORDER BY city
        expectedRows: [[nyc, 4], [sf, 3]]
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time


class CompatError(Exception):
    pass


def load_suite(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    try:
        import yaml
    except ImportError as e:  # pragma: no cover — pyyaml is a declared dep
        raise CompatError(
            "yaml suite files need pyyaml installed; use a .json suite "
            "or install pyyaml") from e
    return yaml.safe_load(text)


def _wait(cond, timeout_s: float, what: str) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if cond():
            return
        time.sleep(0.05)
    raise CompatError(f"timed out waiting for {what}")


class CompatRunner:
    """Executes one suite against a cluster handle (registry + controller +
    broker). Collects per-op pass/fail like CompatibilityOpsRunner."""

    def __init__(self, registry, controller, broker, timeout_s: float = 20.0):
        self.registry = registry
        self.controller = controller
        self.broker = broker
        self.timeout_s = timeout_s
        self.results: list = []

    def run(self, suite: dict) -> bool:
        ops = suite.get("operations") or []
        ok = True
        for i, op in enumerate(ops):
            op_type = op.get("type", "?")
            try:
                getattr(self, f"_op_{op_type}", self._op_unknown)(op)
                self.results.append((i, op_type, "PASS", ""))
            except Exception as e:  # noqa: BLE001 — suite reports, not raises
                self.results.append((i, op_type, "FAIL", f"{e}"))
                ok = False
        return ok

    def _op_unknown(self, op: dict) -> None:
        raise CompatError(f"unknown op type {op.get('type')!r}")

    # ---- ops -------------------------------------------------------------
    def _op_tableOp(self, op: dict) -> None:
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig

        kind = op.get("op", "CREATE").upper()
        if kind == "CREATE":
            from pinot_tpu.common.datatypes import DataType

            def fields(key):
                return [(n, DataType(t)) for n, t in op["schema"].get(key, [])]

            sch = op["schema"]
            schema = Schema.build(
                name=sch["name"],
                dimensions=fields("dimensions"),
                metrics=fields("metrics"),
                datetimes=fields("datetimes"),
                primary_key_columns=sch.get("primaryKeyColumns", []),
            )
            cfg = TableConfig.from_json(op["tableConfig"])
            self.controller.add_table(cfg, schema)
        elif kind == "DELETE":
            self.controller.drop_table(op["table"])
        else:
            raise CompatError(f"tableOp {kind!r} not supported")

    def _op_segmentOp(self, op: dict) -> None:
        kind = op.get("op", "UPLOAD").upper()
        table = op["table"]
        if kind == "DELETE":
            self.controller.delete_segment(table, op["segmentName"])
            return
        if kind != "UPLOAD":
            raise CompatError(f"segmentOp {kind!r} not supported")
        import numpy as np

        from pinot_tpu.storage.creator import build_segment

        key = self.controller.resolve(table)
        schema = self.registry.table_schema(key)
        cfg = self.registry.table_config(key)
        if schema is None or cfg is None:
            raise CompatError(f"table {table!r} not found")
        rows = op["rows"]
        cols = {
            name: np.asarray([r.get(name) for r in rows])
            for name in schema.column_names()
        }
        import shutil

        before = len(self.registry.external_view(key))
        out = tempfile.mkdtemp(prefix="compat_seg_")
        try:
            build_segment(schema, cols, out, cfg, op["segmentName"])
            self.controller.upload_segment(table, out)
        finally:
            # upload copies into the deep store; the build dir is garbage
            shutil.rmtree(out, ignore_errors=True)
        _wait(lambda: len(self.registry.external_view(key)) > before
              or op["segmentName"] in {
                  s for segs in self.registry.external_view(key).values()
                  for s in segs},
              self.timeout_s, f"segment {op['segmentName']} serving")

    def _op_queryOp(self, op: dict) -> None:
        sql = op["sql"]
        expected = op.get("expectedRows")
        deadline = time.time() + self.timeout_s
        last = None
        while True:
            resp = self.broker.execute(sql)
            if not resp.get("exceptions"):
                got = resp["resultTable"]["rows"]
                if expected is None or got == expected:
                    return
                last = got
            else:
                last = resp["exceptions"]
            if time.time() > deadline:
                raise CompatError(f"query {sql!r}: got {last}, "
                                  f"expected {expected}")
            time.sleep(0.1)

    def _op_streamOp(self, op: dict) -> None:
        from pinot_tpu.stream.memory_stream import TopicRegistry

        kind = op.get("op", "PRODUCE").upper()
        if kind == "CREATE_TOPIC":
            TopicRegistry.delete(op["topic"])
            TopicRegistry.create(op["topic"], int(op.get("partitions", 1)))
            return
        if kind != "PRODUCE":
            raise CompatError(f"streamOp {kind!r} not supported")
        topic = TopicRegistry.get(op["topic"])
        for row in op["rows"]:
            topic.publish_json(row, partition=int(row.pop("__partition", 0)))


def run_suite_file(path: str, timeout_s: float = 20.0,
                   keep_cluster=None) -> list:
    """Spin up a quickstart-topology cluster (or use ``keep_cluster``:
    a (registry, controller, broker) triple), run the suite, return
    results. The compCheck.sh entry point."""
    suite = load_suite(path)
    if keep_cluster is not None:
        registry, controller, broker = keep_cluster
        runner = CompatRunner(registry, controller, broker, timeout_s)
        runner.run(suite)
        return runner.results
    import shutil

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import ClusterRegistry
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance

    work = tempfile.mkdtemp(prefix="compat_cluster_")
    registry = ClusterRegistry()
    controller = Controller(registry, f"{work}/ds")
    servers = [ServerInstance(f"server_{i}", registry, f"{work}/s{i}",
                              device_executor=None) for i in range(2)]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=max(10.0, timeout_s))
    try:
        runner = CompatRunner(registry, controller, broker, timeout_s)
        runner.run(suite)
        return runner.results
    finally:
        broker.close()
        for s in servers:
            s.stop()
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pinot-compat", description="run a compatibility op suite")
    ap.add_argument("--suite", required=True, help="yaml/json suite file")
    ap.add_argument("--timeout", type=float, default=20.0)
    args = ap.parse_args(argv)
    results = run_suite_file(args.suite, args.timeout)
    failed = 0
    for i, op_type, status, msg in results:
        line = f"[{i}] {op_type}: {status}"
        if msg:
            line += f" — {msg}"
        print(line)
        failed += status != "PASS"
    print(f"{len(results) - failed}/{len(results)} ops passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
