"""One-command quickstart: full in-process cluster + sample data + queries.

Equivalent of the reference's ``Quickstart``
(pinot-tools/.../Quickstart.java:43 — controller + broker + server + the
baseballStats sample, then example queries), using the in-memory registry,
real gRPC scatter/gather, the batch ingestion job runner, and the broker
HTTP endpoint. ``python -m pinot_tpu.tools.quickstart`` keeps serving until
interrupted; tests call :func:`run_quickstart` and stop the handle.
"""

from __future__ import annotations

import csv
import os
import tempfile

import numpy as np

from pinot_tpu.broker.broker import Broker
from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.ingestion.job import IngestionJobSpec, run_ingestion_job
from pinot_tpu.minion.worker import MinionWorker
from pinot_tpu.server.server import ServerInstance

EXAMPLE_QUERIES = [
    "SELECT COUNT(*) FROM baseballStats",
    "SELECT SUM(homeRuns) FROM baseballStats",
    "SELECT teamID, SUM(runs) FROM baseballStats "
    "GROUP BY teamID ORDER BY SUM(runs) DESC LIMIT 5",
    "SELECT playerName, SUM(homeRuns) FROM baseballStats "
    "WHERE yearID >= 2000 GROUP BY playerName "
    "ORDER BY SUM(homeRuns) DESC LIMIT 5",
]

_TEAMS = ["ATL", "BOS", "CHC", "NYY", "OAK", "SEA", "SFG", "TEX"]
_NAMES = ["Aaron", "Bonds", "Cobb", "DiMaggio", "Gehrig", "Mays",
          "Ripken", "Ruth", "Trout", "Williams"]


def write_sample_csvs(data_dir: str, files: int = 2, rows: int = 500,
                      seed: int = 7) -> None:
    """Synthetic baseballStats-shaped sample (the repo carries no data
    files; the reference ships a CSV with the same columns)."""
    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)
    for i in range(files):
        with open(os.path.join(data_dir, f"baseballStats_{i}.csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["playerName", "teamID", "yearID", "runs", "homeRuns"])
            for _ in range(rows):
                w.writerow([
                    _NAMES[rng.integers(len(_NAMES))],
                    _TEAMS[rng.integers(len(_TEAMS))],
                    int(rng.integers(1990, 2024)),
                    int(rng.integers(0, 130)),
                    int(rng.integers(0, 50)),
                ])


class QuickstartHandle:
    def __init__(self, registry, controller, servers, broker, http, minion):
        self.registry = registry
        self.controller = controller
        self.servers = servers
        self.broker = broker
        self.http = http
        self.minion = minion

    def execute(self, sql: str) -> dict:
        return self.broker.execute(sql)

    def stop(self) -> None:
        self.minion.stop()
        self.http.stop()
        self.broker.close()
        for s in self.servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass


def _format_result(resp: dict) -> str:
    if resp.get("exceptions"):
        return f"  ERROR: {resp['exceptions']}"
    rt = resp.get("resultTable", {})
    cols = rt.get("dataSchema", {}).get("columnNames", [])
    lines = ["  " + " | ".join(str(c) for c in cols)]
    for row in rt.get("rows", []):
        lines.append("  " + " | ".join(str(v) for v in row))
    lines.append(f"  ({resp.get('timeUsedMs')} ms, "
                 f"{resp.get('numDocsScanned')} docs scanned)")
    return "\n".join(lines)


def run_quickstart(work_dir=None, n_servers: int = 2,
                   run_examples: bool = True, out=print,
                   device_executor="auto") -> QuickstartHandle:
    work_dir = work_dir or tempfile.mkdtemp(prefix="pinot_tpu_quickstart_")
    out(f"quickstart working dir: {work_dir}")

    registry = ClusterRegistry()
    controller = Controller(registry, os.path.join(work_dir, "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry,
                       os.path.join(work_dir, f"server_{i}"),
                       device_executor=device_executor)
        for i in range(n_servers)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry)
    http = BrokerHttpServer(broker)
    http.start()
    minion = MinionWorker(registry, controller, os.path.join(work_dir, "minion"))
    minion.start()

    schema = Schema.build(
        name="baseballStats",
        dimensions=[("playerName", DataType.STRING), ("teamID", DataType.STRING)],
        metrics=[("runs", DataType.INT), ("homeRuns", DataType.INT)],
        datetimes=[("yearID", DataType.INT)],
    )
    config = TableConfig(
        table_name="baseballStats",
        replication=min(2, n_servers),
        indexing=IndexingConfig(inverted_index_columns=["teamID"]),
    )
    controller.add_table(config, schema)

    data_dir = os.path.join(work_dir, "rawdata")
    write_sample_csvs(data_dir)
    built = run_ingestion_job(
        IngestionJobSpec(table_name="baseballStats", input_dir=data_dir,
                         include_pattern="*.csv", format="csv"),
        controller,
    )
    out(f"ingested {len(built)} segments from {data_dir}")

    # wait until servers actually serve every pushed segment
    import time

    deadline = time.time() + 30
    want = len(built)
    while time.time() < deadline:
        if len(registry.external_view("baseballStats_OFFLINE")) >= want:
            break
        time.sleep(0.05)

    if run_examples:
        for sql in EXAMPLE_QUERIES:
            out(f"\n> {sql}")
            out(_format_result(broker.execute(sql)))
    out(f"\nbroker HTTP endpoint: {http.url}/query/sql "
        f'(POST {{"sql": "..."}})')
    return QuickstartHandle(registry, controller, servers, broker, http, minion)


def main() -> None:
    handle = run_quickstart()
    print("cluster running; Ctrl-C to stop")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.stop()


if __name__ == "__main__":
    main()
