"""SQL AST → QueryContext compiler.

Role-equivalent of the reference's QueryContextConverterUtils +
RequestContextUtils (pinot-common/.../common/request/context/
RequestContextUtils.java: expression → FilterContext lowering) plus the
rewriter chain (sql/parsers/rewriter/: alias + ordinal resolution).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pinot_tpu.query.context import (
    Expression,
    ExpressionType,
    FilterNode,
    FilterNodeType,
    OrderByExpression,
    Predicate,
    PredicateType,
    QueryContext,
)
from pinot_tpu.sql.parser import SqlParseError, SqlSelect, parse_sql

DEFAULT_LIMIT = 10  # reference: CalciteSqlParser DEFAULT_LIMIT


def compile_query(sql: str) -> QueryContext:
    return compile_select(parse_sql(sql))


def contains_window(e: Expression) -> bool:
    """True when a ``__window__`` marker (OVER clause) appears anywhere in
    the expression tree."""
    if not e.is_function:
        return False
    if e.name == "__window__":
        return True
    return any(contains_window(a) for a in e.args)


def is_multistage(stmt: SqlSelect) -> bool:
    """Joins or window functions route through the multi-stage engine
    (query2/); everything else stays on the single-stage path untouched."""
    if stmt.joins:
        return True
    exprs = [e for e, _ in stmt.select]
    exprs.extend(e for e, _ in stmt.order_by)
    if stmt.having is not None:
        exprs.append(stmt.having)
    if stmt.where is not None:
        exprs.append(stmt.where)
    exprs.extend(stmt.group_by)
    return any(contains_window(e) for e in exprs)


def _strip_alias(e: Expression, alias: str) -> Expression:
    """``alias.col`` → ``col`` for a single-table query's own alias, so
    FROM t x / SELECT x.c rides the single-stage path unchanged."""
    if e.is_identifier and e.name.startswith(alias + "."):
        return Expression.identifier(e.name[len(alias) + 1:])
    if e.is_function:
        return Expression(
            ExpressionType.FUNCTION, name=e.name,
            args=tuple(_strip_alias(a, alias) for a in e.args))
    return e


def compile_select(stmt: SqlSelect) -> QueryContext:
    if is_multistage(stmt):
        # the planner (query2/logical.py) owns joins and windows; reaching
        # this single-stage entry with one is a routing bug or a direct
        # server submit of a query only the broker/engine can decompose
        raise SqlParseError(
            "join/window queries compile through the multi-stage engine "
            "(query2), not the single-stage compiler")
    # de-qualify single-table references: the explicit alias when one was
    # written (SELECT x.c FROM t x), else the table name itself
    # (SELECT t.c FROM t)
    a = stmt.table_alias or stmt.table
    if a:
        stmt = dataclasses.replace(
            stmt,
            select=[(_strip_alias(e, a), al) for e, al in stmt.select],
            where=None if stmt.where is None else _strip_alias(stmt.where, a),
            group_by=[_strip_alias(e, a) for e in stmt.group_by],
            having=None if stmt.having is None
            else _strip_alias(stmt.having, a),
            order_by=[(_strip_alias(e, a), asc)
                      for e, asc in stmt.order_by],
        )
    select_exprs = tuple(e for e, _ in stmt.select)
    aliases = tuple(a for _, a in stmt.select)
    alias_map = {a: e for e, a in stmt.select if a}

    group_by = tuple(
        _resolve_ref(e, select_exprs, alias_map) for e in stmt.group_by
    )
    order_by = tuple(
        OrderByExpression(_resolve_ref(e, select_exprs, alias_map), asc)
        for e, asc in stmt.order_by
    )

    filt = _to_filter(stmt.where) if stmt.where is not None else None
    having = None
    if stmt.having is not None:
        having = _to_filter(_substitute_aliases(stmt.having, alias_map))

    return QueryContext(
        table_name=stmt.table,
        select_expressions=select_exprs,
        aliases=aliases,
        distinct=stmt.distinct,
        filter=filt,
        group_by=group_by,
        having=having,
        order_by=order_by,
        limit=stmt.limit if stmt.limit is not None else DEFAULT_LIMIT,
        offset=stmt.offset,
        options=tuple(sorted(stmt.options.items())),
        explain=stmt.explain,
        analyze=stmt.analyze,
    )


# ---------------------------------------------------------------------------
# alias / ordinal resolution (rewriter chain analog)
# ---------------------------------------------------------------------------


def _resolve_ref(e: Expression, select_exprs: tuple, alias_map: dict) -> Expression:
    """GROUP BY 2 / ORDER BY alias → the underlying select expression."""
    if e.is_literal and isinstance(e.value, int) and not isinstance(e.value, bool):
        i = e.value - 1
        if 0 <= i < len(select_exprs):
            return select_exprs[i]
        raise SqlParseError(f"ordinal {e.value} out of range")
    return _substitute_aliases(e, alias_map)


def _substitute_aliases(e: Expression, alias_map: dict) -> Expression:
    if e.is_identifier and e.name in alias_map:
        return alias_map[e.name]
    if e.is_function:
        return Expression(
            ExpressionType.FUNCTION,
            name=e.name,
            args=tuple(_substitute_aliases(a, alias_map) for a in e.args),
        )
    return e


# ---------------------------------------------------------------------------
# boolean expression → filter tree
# ---------------------------------------------------------------------------

_CMP_TO_RANGE = {
    "greater_than": (False, "lower"),
    "greater_than_or_equal": (True, "lower"),
    "less_than": (False, "upper"),
    "less_than_or_equal": (True, "upper"),
}


def _to_filter(e: Expression) -> FilterNode:
    """Lower a boolean expression tree into a FilterNode tree
    (RequestContextUtils.getFilter analog)."""
    if e.is_literal:
        return FilterNode.TRUE if e.value else FilterNode.FALSE
    if not e.is_function:
        raise SqlParseError(f"non-boolean filter expression: {e}")

    name = e.name
    if name in ("and", "or"):
        # flatten left-assoc chains into n-ary nodes at construction
        node_t = FilterNodeType.AND if name == "and" else FilterNodeType.OR
        kids = []
        for a in e.args:
            c = _to_filter(a)
            if c.type is node_t:
                kids.extend(c.children)
            else:
                kids.append(c)
        return FilterNode(node_t, children=tuple(kids))
    if name == "not":
        return FilterNode.not_(_to_filter(e.args[0]))

    if name in ("equals", "not_equals"):
        lhs, rhs = _operand_literal(e.args[0], e.args[1])
        t = PredicateType.EQ if name == "equals" else PredicateType.NOT_EQ
        return FilterNode.pred(Predicate(t, lhs, value=rhs))

    if name in _CMP_TO_RANGE:
        lhs, rhs, flipped = _operand_literal_flippable(e.args[0], e.args[1])
        cname = _flip_cmp(name) if flipped else name
        inclusive, side = _CMP_TO_RANGE[cname]
        kw = (
            dict(lower=rhs, lower_inclusive=inclusive, upper=None)
            if side == "lower"
            else dict(upper=rhs, upper_inclusive=inclusive, lower=None)
        )
        return FilterNode.pred(Predicate(PredicateType.RANGE, lhs, **kw))

    if name == "between":
        lhs = e.args[0]
        lo = _require_literal(e.args[1])
        hi = _require_literal(e.args[2])
        return FilterNode.pred(
            Predicate(PredicateType.RANGE, lhs, lower=lo, upper=hi)
        )

    if name in ("in", "not_in"):
        lhs = e.args[0]
        vals = tuple(_require_literal(a) for a in e.args[1:])
        t = PredicateType.IN if name == "in" else PredicateType.NOT_IN
        return FilterNode.pred(Predicate(t, lhs, values=vals))

    if name == "like":
        lhs = e.args[0]
        pat = _require_literal(e.args[1])
        return FilterNode.pred(Predicate(PredicateType.LIKE, lhs, value=pat))

    if name in ("regexp_like", "text_match", "json_match"):
        lhs = e.args[0]
        pat = _require_literal(e.args[1])
        t = {
            "regexp_like": PredicateType.REGEXP_LIKE,
            "text_match": PredicateType.TEXT_MATCH,
            "json_match": PredicateType.JSON_MATCH,
        }[name]
        return FilterNode.pred(Predicate(t, lhs, value=pat))

    if name == "is_null":
        return FilterNode.pred(Predicate(PredicateType.IS_NULL, e.args[0]))
    if name == "is_not_null":
        return FilterNode.pred(Predicate(PredicateType.IS_NOT_NULL, e.args[0]))

    # boolean-valued transform functions (ST_CONTAINS, STARTSWITH, ...)
    # filter as `expr = true` — the reference wraps these the same way
    # (RequestContextUtils' EQ-true predicate over a boolean transform)
    from pinot_tpu.ops.transform import REGISTRY

    fd = REGISTRY.get(name)
    if fd is not None and fd.returns_bool:
        return FilterNode.pred(Predicate(PredicateType.EQ, e, value=True))

    raise SqlParseError(f"cannot use {name}() as a filter")


def _flip_cmp(name: str) -> str:
    return {
        "greater_than": "less_than",
        "greater_than_or_equal": "less_than_or_equal",
        "less_than": "greater_than",
        "less_than_or_equal": "greater_than_or_equal",
    }[name]


def _operand_literal(a: Expression, b: Expression):
    """Normalize (expr, literal) operand order for symmetric predicates."""
    if b.is_literal:
        return a, b.value
    if a.is_literal:
        return b, a.value
    raise SqlParseError(f"predicate requires a literal operand: {a} vs {b}")


def _operand_literal_flippable(a: Expression, b: Expression):
    if b.is_literal:
        return a, b.value, False
    if a.is_literal:
        return b, a.value, True
    raise SqlParseError(f"predicate requires a literal operand: {a} vs {b}")


def _require_literal(e: Expression):
    if not e.is_literal:
        raise SqlParseError(f"expected literal, got {e}")
    return e.value
