"""SQL front-end: tokenizer + recursive-descent/Pratt parser.

Role-equivalent of the reference's Calcite-based parser
(pinot-common/.../sql/parsers/CalciteSqlParser.java, ``compileToPinotQuery``)
— but hand-rolled, since the TPU build carries no Calcite/sqlglot dependency.
Parses the Pinot query surface:

    [SET key = value;]* [EXPLAIN PLAN FOR]
    SELECT [DISTINCT] expr [AS alias], ... FROM table
    [WHERE bool_expr] [GROUP BY expr, ...] [HAVING bool_expr]
    [ORDER BY expr [ASC|DESC], ...] [LIMIT n [OFFSET m] | LIMIT m, n]

Expressions parse into the engine IR's ``Expression`` trees directly (the
tree doubles as the AST; boolean operators become functions ``and``/``or``/
``not``/comparison names, which the compiler lowers to FilterNodes the same
way the reference's RequestContextUtils.getFilter does).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from pinot_tpu.query.context import Expression

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<number>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|=|<|>|\|\||[+\-*/%(),;.])
    """,
    re.VERBOSE,
)


class SqlParseError(Exception):
    pass


class SqlAnalysisError(SqlParseError):
    """Typed semantic-analysis error (the multi-stage planner's analog of
    Calcite's validator errors): unknown / ambiguous column references
    resolve to this, naming the table alias and the candidate columns,
    instead of surfacing a raw KeyError from the compiler."""

    def __init__(self, message: str, column: Optional[str] = None,
                 candidates: tuple = ()):
        super().__init__(message)
        self.column = column
        self.candidates = tuple(candidates)


@dataclasses.dataclass
class Token:
    kind: str  # number | string | ident | qident | op | eof
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append(Token(kind, m.group(), m.start()))
    tokens.append(Token("eof", "", n))
    return tokens


# ---------------------------------------------------------------------------
# Parsed statement
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class JoinClause:
    """One ``[INNER|LEFT [OUTER]] JOIN table [AS] alias ON expr`` clause
    (multi-stage grammar; the reference snapshot has no join surface)."""

    kind: str  # "INNER" | "LEFT"
    table: str
    alias: Optional[str]
    on: Expression


@dataclasses.dataclass
class SqlSelect:
    table: str
    select: list  # list[tuple[Expression, Optional[str]]] (expr, alias)
    table_alias: Optional[str] = None
    joins: list = dataclasses.field(default_factory=list)
    distinct: bool = False
    where: Optional[Expression] = None
    group_by: list = dataclasses.field(default_factory=list)
    having: Optional[Expression] = None
    order_by: list = dataclasses.field(default_factory=list)  # [(Expression, asc)]
    limit: Optional[int] = None
    offset: int = 0
    options: dict = dataclasses.field(default_factory=dict)
    explain: bool = False
    # EXPLAIN ANALYZE (ISSUE 11): execute the query for real and render
    # the plan annotated with per-node actuals; implies explain
    analyze: bool = False


_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "AS",
    "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE", "IS", "ASC", "DESC",
    "SELECT", "DISTINCT", "BY", "NULL", "TRUE", "FALSE", "CASE", "WHEN",
    "THEN", "ELSE", "END", "CAST",
    # multi-stage grammar (joins + windows)
    "JOIN", "ON", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "OUTER",
    "OVER", "PARTITION",
}

_COMPARISON = {
    "=": "equals",
    "!=": "not_equals",
    "<>": "not_equals",
    ">": "greater_than",
    ">=": "greater_than_or_equal",
    "<": "less_than",
    "<=": "less_than_or_equal",
}

_ADD = {"+": "plus", "-": "minus", "||": "concat"}
_MUL = {"*": "times", "/": "divide", "%": "mod"}


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # ---- token plumbing --------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> bool:
        t = self.peek()
        if t.kind == "ident" and t.upper in kws:
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            t = self.peek()
            raise SqlParseError(f"expected {kw} at {t.pos}, got {t.text!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.text == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            t = self.peek()
            raise SqlParseError(f"expected {op!r} at {t.pos}, got {t.text!r}")

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in kws

    # ---- statement -------------------------------------------------------
    def parse(self) -> SqlSelect:
        options: dict = {}
        # leading SET option = value; statements (Pinot SET syntax)
        while self.at_kw("SET"):
            self.next()
            key_tok = self.next()
            if key_tok.kind not in ("ident", "qident", "string"):
                raise SqlParseError(f"bad SET key at {key_tok.pos}")
            key = _unquote(key_tok)
            self.expect_op("=")
            val_tok = self.next()
            if val_tok.kind == "string":
                val: object = _string_value(val_tok.text)
            elif val_tok.kind == "number":
                val = _number_value(val_tok.text)
            elif val_tok.kind == "ident" and val_tok.upper in ("TRUE", "FALSE"):
                val = val_tok.upper == "TRUE"
            else:
                val = val_tok.text
            options[key] = val
            self.expect_op(";")

        explain = False
        analyze = False
        if self.accept_kw("EXPLAIN"):
            # EXPLAIN PLAN FOR <select> renders the static plan;
            # EXPLAIN ANALYZE <select> executes it and annotates the plan
            # with per-node actuals (ISSUE 11)
            if self.accept_kw("ANALYZE"):
                analyze = True
            else:
                self.expect_kw("PLAN")
                self.expect_kw("FOR")
            explain = True

        stmt = self.parse_select()
        stmt.options = options
        stmt.explain = explain
        stmt.analyze = analyze
        self.accept_op(";")
        t = self.peek()
        if t.kind != "eof":
            raise SqlParseError(f"trailing input at {t.pos}: {t.text!r}")
        return stmt

    def parse_select(self) -> SqlSelect:
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        select: list = [self.parse_select_item()]
        while self.accept_op(","):
            select.append(self.parse_select_item())

        self.expect_kw("FROM")
        table, table_alias = self.parse_table_ref()
        joins: list = []
        while True:
            if self.accept_kw("JOIN"):
                kind = "INNER"
            elif self.at_kw("INNER"):
                self.next()
                self.expect_kw("JOIN")
                kind = "INNER"
            elif self.at_kw("LEFT"):
                self.next()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "LEFT"
            elif self.at_kw("RIGHT", "FULL", "CROSS"):
                t = self.peek()
                raise SqlParseError(
                    f"{t.upper} JOIN is not supported (INNER and LEFT "
                    f"joins only) at {t.pos}")
            else:
                break
            jtable, jalias = self.parse_table_ref()
            self.expect_kw("ON")
            joins.append(JoinClause(kind, jtable, jalias, self.parse_expr()))

        where = None
        if self.accept_kw("WHERE"):
            where = self.parse_expr()

        group_by: list = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())

        having = None
        if self.accept_kw("HAVING"):
            having = self.parse_expr()

        order_by: list = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())

        limit = None
        offset = 0
        if self.accept_kw("LIMIT"):
            first = self.parse_int()
            if self.accept_op(","):  # LIMIT offset, count (MySQL form)
                offset = first
                limit = self.parse_int()
            else:
                limit = first
                if self.accept_kw("OFFSET"):
                    offset = self.parse_int()

        return SqlSelect(
            table=table, select=select, table_alias=table_alias,
            joins=joins, distinct=distinct, where=where,
            group_by=group_by, having=having, order_by=order_by,
            limit=limit, offset=offset,
        )

    def parse_select_item(self):
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = _unquote(self.next())
        elif self.peek().kind in ("ident", "qident") and not self.at_kw(*_RESERVED_STOP):
            alias = _unquote(self.next())
        return (expr, alias)

    def parse_order_item(self):
        expr = self.parse_expr()
        asc = True
        if self.accept_kw("DESC"):
            asc = False
        else:
            self.accept_kw("ASC")
        # NULLS FIRST/LAST accepted and ignored (engine: nulls sort last)
        if self.accept_kw("NULLS"):
            self.next()
        return (expr, asc)

    def parse_table_ref(self):
        """``table [AS] alias`` → (name, alias or None)."""
        name = self.parse_table_name()
        alias = None
        if self.accept_kw("AS"):
            alias = _unquote(self.next())
        elif self.peek().kind in ("ident", "qident") \
                and not self.at_kw(*_RESERVED_STOP):
            alias = _unquote(self.next())
        return name, alias

    def parse_table_name(self) -> str:
        t = self.next()
        if t.kind not in ("ident", "qident"):
            raise SqlParseError(f"expected table name at {t.pos}")
        name = _unquote(t)
        while self.accept_op("."):  # db.table → keep last part
            name = _unquote(self.next())
        return name

    def parse_int(self) -> int:
        t = self.next()
        if t.kind != "number":
            raise SqlParseError(f"expected integer at {t.pos}")
        return int(t.text)

    # ---- expressions (precedence climbing) ------------------------------
    def parse_expr(self) -> Expression:
        return self.parse_or()

    def parse_or(self) -> Expression:
        left = self.parse_and()
        while self.accept_kw("OR"):
            right = self.parse_and()
            left = Expression.function("or", left, right)
        return left

    def parse_and(self) -> Expression:
        left = self.parse_not()
        while self.accept_kw("AND"):
            right = self.parse_not()
            left = Expression.function("and", left, right)
        return left

    def parse_not(self) -> Expression:
        if self.accept_kw("NOT"):
            return Expression.function("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expression:
        left = self.parse_additive()
        t = self.peek()
        if t.kind == "op" and t.text in _COMPARISON:
            self.next()
            right = self.parse_additive()
            return Expression.function(_COMPARISON[t.text], left, right)

        negated = False
        if self.at_kw("NOT"):
            # lookahead: NOT IN / NOT BETWEEN / NOT LIKE
            nxt = self.tokens[self.i + 1]
            if nxt.kind == "ident" and nxt.upper in ("IN", "BETWEEN", "LIKE"):
                self.next()
                negated = True

        if self.accept_kw("IN"):
            self.expect_op("(")
            vals = [self.parse_expr()]
            while self.accept_op(","):
                vals.append(self.parse_expr())
            self.expect_op(")")
            fn = "not_in" if negated else "in"
            return Expression.function(fn, left, *vals)

        if self.accept_kw("BETWEEN"):
            lo = self.parse_additive()
            self.expect_kw("AND")
            hi = self.parse_additive()
            e = Expression.function("between", left, lo, hi)
            return Expression.function("not", e) if negated else e

        if self.accept_kw("LIKE"):
            pat = self.parse_additive()
            e = Expression.function("like", left, pat)
            return Expression.function("not", e) if negated else e

        if self.accept_kw("IS"):
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                return Expression.function("is_not_null", left)
            self.expect_kw("NULL")
            return Expression.function("is_null", left)

        return left

    def parse_additive(self) -> Expression:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in _ADD:
                self.next()
                right = self.parse_multiplicative()
                left = Expression.function(_ADD[t.text], left, right)
            else:
                return left

    def parse_multiplicative(self) -> Expression:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in _MUL:
                self.next()
                right = self.parse_unary()
                left = Expression.function(_MUL[t.text], left, right)
            else:
                return left

    def parse_unary(self) -> Expression:
        if self.accept_op("-"):
            inner = self.parse_unary()
            if inner.is_literal and isinstance(inner.value, (int, float)):
                return Expression.literal(-inner.value)
            return Expression.function("minus", Expression.literal(0), inner)
        self.accept_op("+")
        return self.parse_primary()

    def parse_primary(self) -> Expression:
        t = self.next()
        if t.kind == "number":
            return Expression.literal(_number_value(t.text))
        if t.kind == "string":
            return Expression.literal(_string_value(t.text))
        if t.kind == "op" and t.text == "(":
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.text == "*":
            return Expression.identifier("*")
        if t.kind == "qident":
            return self.parse_maybe_qualified(_unquote(t))
        if t.kind == "ident":
            up = t.upper
            if up == "NULL":
                return Expression.literal(None)
            if up == "TRUE":
                return Expression.literal(True)
            if up == "FALSE":
                return Expression.literal(False)
            if up == "CASE":
                return self.parse_case()
            if up == "CAST":
                return self.parse_cast()
            if self.accept_op("("):
                e = self.parse_function_call(t.text)
                if self.at_kw("OVER"):
                    e = self.parse_over(e)
                return e
            return self.parse_maybe_qualified(t.text)
        raise SqlParseError(f"unexpected token {t.text!r} at {t.pos}")

    def parse_maybe_qualified(self, first: str) -> Expression:
        """``alias.col`` → one identifier named ``alias.col`` (the
        multi-stage planner resolves the qualification; single-table
        queries strip a matching table alias in the compiler)."""
        if not self.accept_op("."):
            return Expression.identifier(first)
        t = self.next()
        if t.kind not in ("ident", "qident"):
            raise SqlParseError(
                f"expected column after {first!r}. at {t.pos}")
        return Expression.identifier(f"{first}.{_unquote(t)}")

    def parse_over(self, fn_expr: Expression) -> Expression:
        """``OVER (PARTITION BY ... ORDER BY ...)`` →
        function('__window__', fn, '__partition__'(keys...),
        '__order__'('__asc__'|'__desc__'(key)...)). The dunder names are
        reserved markers the multi-stage planner unpacks; they can never
        collide with transform registry names."""
        self.expect_kw("OVER")
        self.expect_op("(")
        partition: list[Expression] = []
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        order: list[Expression] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            e, asc = self.parse_order_item()
            order.append(Expression.function(
                "__asc__" if asc else "__desc__", e))
            while self.accept_op(","):
                e, asc = self.parse_order_item()
                order.append(Expression.function(
                    "__asc__" if asc else "__desc__", e))
        if self.at_kw("ROWS", "RANGE", "GROUPS"):
            t = self.peek()
            raise SqlParseError(
                f"explicit window frames ({t.upper} ...) are not "
                f"supported at {t.pos}; the default frame applies")
        self.expect_op(")")
        return Expression.function(
            "__window__", fn_expr,
            Expression.function("__partition__", *partition),
            Expression.function("__order__", *order))

    def parse_function_call(self, name: str) -> Expression:
        # COUNT(*) / COUNT(DISTINCT x) special forms
        fname = name.lower()
        if self.accept_op(")"):
            return Expression.function(fname)
        distinct = self.accept_kw("DISTINCT")
        args = [self.parse_expr()]
        while self.accept_op(","):
            args.append(self.parse_expr())
        self.expect_op(")")
        if distinct:
            if fname == "count":
                return Expression.function("distinctcount", *args)
            raise SqlParseError(f"DISTINCT not supported inside {name}()")
        return Expression.function(fname, *args)

    def parse_case(self) -> Expression:
        """CASE WHEN c1 THEN v1 ... [ELSE e] END →
        function('case', c1, v1, c2, v2, ..., else)."""
        args: list[Expression] = []
        while self.accept_kw("WHEN"):
            args.append(self.parse_expr())
            self.expect_kw("THEN")
            args.append(self.parse_expr())
        if self.accept_kw("ELSE"):
            args.append(self.parse_expr())
        else:
            args.append(Expression.literal(None))
        self.expect_kw("END")
        if len(args) < 3:
            raise SqlParseError("CASE requires at least one WHEN")
        return Expression.function("case", *args)

    def parse_cast(self) -> Expression:
        self.expect_op("(")
        e = self.parse_expr()
        self.expect_kw("AS")
        t = self.next()
        if t.kind != "ident":
            raise SqlParseError(f"expected type name at {t.pos}")
        type_name = t.text.upper()
        self.expect_op(")")
        return Expression.function("cast", e, Expression.literal(type_name))


# ---------------------------------------------------------------------------
# literal helpers
# ---------------------------------------------------------------------------


def _number_value(text: str):
    if re.fullmatch(r"\d+", text):
        return int(text)
    return float(text)


def _string_value(text: str) -> str:
    return text[1:-1].replace("''", "'")


def _unquote(t: Token) -> str:
    if t.kind == "qident":
        return t.text[1:-1].replace('""', '"')
    if t.kind == "string":
        return _string_value(t.text)
    return t.text


def parse_sql(sql: str) -> SqlSelect:
    return Parser(sql).parse()


# EXPLAIN ANALYZE executes the UNDERLYING statement through the normal
# path (broker scatter-gather / multi-stage leaves): the keyword pair is
# stripped from the raw SQL once, preserving any leading SET statements.
_EXPLAIN_ANALYZE_RE = re.compile(r"\bEXPLAIN\s+ANALYZE\s+", re.IGNORECASE)


def strip_explain_analyze(sql: str) -> str:
    """The SQL with its first ``EXPLAIN ANALYZE`` removed (the executable
    form the broker re-runs); unchanged input when the keywords are
    absent — callers use equality as the "did anything strip" guard."""
    return _EXPLAIN_ANALYZE_RE.sub("", sql, count=1)
