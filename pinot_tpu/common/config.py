"""Layered configuration.

Equivalent to the reference's ``PinotConfiguration``
(pinot-spi/.../env/PinotConfiguration.java): resolution order is explicit
overrides > environment variables (``PINOT_TPU_`` prefix, dots as
underscores) > properties/JSON file > defaults.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping


class Configuration:
    ENV_PREFIX = "PINOT_TPU_"

    def __init__(
        self,
        overrides: Mapping[str, Any] | None = None,
        config_file: str | None = None,
        defaults: Mapping[str, Any] | None = None,
        env: Mapping[str, str] | None = None,
    ):
        self._defaults = dict(defaults or {})
        self._file: dict[str, Any] = {}
        if config_file:
            self._file = self._load_file(config_file)
        self._env = dict(env if env is not None else os.environ)
        self._overrides = dict(overrides or {})

    @staticmethod
    def _load_file(path: str) -> dict:
        with open(path) as f:
            text = f.read()
        text_stripped = text.lstrip()
        if text_stripped.startswith("{"):
            return dict(json.loads(text))
        # .properties style: key=value lines
        out = {}
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                out[k.strip()] = v.strip()
        return out

    def _env_key(self, key: str) -> str:
        return self.ENV_PREFIX + key.upper().replace(".", "_").replace("-", "_")

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        ek = self._env_key(key)
        if ek in self._env:
            return self._env[ek]
        if key in self._file:
            return self._file[key]
        return self._defaults.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key, default)
        return int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key, default)
        return float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("true", "1", "yes", "on")

    def set(self, key: str, value: Any) -> None:
        self._overrides[key] = value

    def subset(self, prefix: str) -> dict[str, Any]:
        """All resolved keys under ``prefix.`` (file+defaults+overrides keys)."""
        keys = set(self._defaults) | set(self._file) | set(self._overrides)
        p = prefix.rstrip(".") + "."
        return {k[len(p):]: self.get(k) for k in keys if k.startswith(p)}
