"""Environment provider SPI: failure-domain metadata for instances.

Reference: pinot-plugins/pinot-environment/pinot-azure
(AzureEnvironmentProvider) — resolves the instance's FAILURE DOMAIN from
the cloud metadata service so segment assignment can spread replicas
across fault boundaries. Here the SPI is a registry of providers; the
default provider reads ``PINOT_TPU_FAILURE_DOMAIN`` (or the
``pinot.environment.failure.domain`` config key), and cloud-specific
providers can register the same way the stream/fs plugins do. The
resolved domain rides on InstanceInfo as a ``fd:<domain>`` tag, and the
segment assigner spreads replicas across distinct domains
(controller/controller.py SegmentAssigner).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

FD_TAG_PREFIX = "fd:"

_PROVIDERS: dict[str, Callable[[], Optional[str]]] = {}


def register_environment_provider(name: str,
                                  fn: Callable[[], Optional[str]]) -> None:
    _PROVIDERS[name] = fn


def _default_provider() -> Optional[str]:
    fd = os.environ.get("PINOT_TPU_FAILURE_DOMAIN")
    if fd:
        return fd
    from pinot_tpu.common.config import Configuration

    return Configuration().get("pinot.environment.failure.domain", None)


register_environment_provider("default", _default_provider)


def resolve_failure_domain(provider: str = "default") -> Optional[str]:
    fn = _PROVIDERS.get(provider)
    return fn() if fn is not None else None


def failure_domain_tag(provider: str = "default") -> Optional[str]:
    """``fd:<domain>`` instance tag, or None when no domain is configured."""
    fd = resolve_failure_domain(provider)
    return f"{FD_TAG_PREFIX}{fd}" if fd else None


def domain_of(instance) -> Optional[str]:
    """Failure domain from an InstanceInfo's tags."""
    for t in getattr(instance, "tags", ()) or ():
        if str(t).startswith(FD_TAG_PREFIX):
            return str(t)[len(FD_TAG_PREFIX):]
    return None
