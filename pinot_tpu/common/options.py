"""Uniform parsing for ``SET``-style boolean query options.

Every subsystem that honors a per-query toggle (``useBlockSkip``,
``usePallas``, ``useResultCache``, ``useDeviceReduce``, ``useHedging``,
``usePartialsCache``, ``useSortedProjection``, ``useAdvisor``, ...) used
to parse ``q.options_ci()`` values by hand, and most hand-rolled parses
shared the same latent bug: the SQL layer passes bare ``TRUE``/``FALSE``
through as real booleans but quoted literals (``SET useX = 'false'``)
arrive as *strings*, and ``'false'`` is truthy. PR 10 fixed that once
for the result cache; this helper fixes it once for every current and
future option.

Semantics (the broker result-cache contract, generalized):

- absent / ``None``  -> ``default`` (caller-supplied tri-state allowed)
- real ``bool``      -> itself
- anything else      -> string-folded: ``"true"/"1"/"yes"`` (any case,
  surrounding whitespace ignored) means True, everything else False.
"""

from __future__ import annotations

_TRUTHY = ("true", "1", "yes")


def bool_option(opts, name: str, default=None):
    """Resolve option ``name`` from an ``options_ci()``-style dict.

    ``name`` is matched case-insensitively (``options_ci`` keys are
    already lower-cased; a raw dict is folded here so callers holding
    un-normalized option tuples get the same answer). Returns
    ``default`` when the option is absent — pass ``default=None`` to
    keep the tri-state "unset" visible to the caller."""
    if not opts:
        return default
    key = name.lower()
    val = opts.get(key)
    if val is None and key not in opts:
        # tolerate un-normalized dicts (options straight off q.options)
        for k, v in opts.items():
            if isinstance(k, str) and k.lower() == key:
                val = v
                break
        else:
            return default
    if val is None:
        return default
    if isinstance(val, bool):
        return val
    return str(val).strip().lower() in _TRUTHY


def option_enabled(opts, name: str, default: bool = False) -> bool:
    """``bool_option`` collapsed to a plain bool (absent -> default)."""
    return bool(bool_option(opts, name, default))
