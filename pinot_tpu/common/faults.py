"""Fault-injection harness: named injection points at the failure seams.

The robustness tier (deadline propagation, replica retry + hedging,
device-error recovery) is only trustworthy if every failure mode it claims
to survive can be *produced on demand* — the reference proves its broker
stack with ChaosMonkey-style integration tests
(OfflineClusterIntegrationTest server kills, PeerDownloadLLCRealtime...);
this module is the in-process equivalent. Production code calls
``inject(point, target=...)`` at its seams; with no faults installed the
call never happens (callers gate on the module-level ``ACTIVE`` bool — one
attribute read), so the harness is zero-overhead when disabled.

Points wired in this codebase:

    transport.submit     broker→server RPC, per server instance
                         (drop / delay / blackhole a replica)
    server.crash         server dies mid-query (RPC fails at the
                         transport level, NOT in-band)
    device.launch        XLA dispatch failure (simulated XlaRuntimeError /
                         RESOURCE_EXHAUSTED)
    device.fetch         failure on the blocking device_get
    chunklet.promote     consuming-segment chunklet promotion failure
    peer.fetch           peer segment download failure
    scheduler.admit      admission starvation (ISSUE 14; modes
                         error|delay). Two seams share the point: the
                         broker's tenant admission controller (target =
                         tenant name) — an injected error sheds the
                         query through the typed degrade-or-429 path —
                         and the server's scheduler admission (target =
                         instance id) — an injected error becomes a
                         typed QUERY_SCHEDULING_TIMEOUT, never a hang
                         or a transport fault
    exchange.transfer    distributed stage-2 partition ship (ISSUE 16;
                         target = the RECEIVING instance id): fired in
                         the SENDING server before every mailbox offer
                         — self-sends included — so blackholing one
                         server starves every sender addressing it. The
                         sender converts the fault into a typed
                         EXCHANGE_TRANSFER_FAILED naming the peer; the
                         broker excludes that instance and retries the
                         exchange on replicas, or settles as a typed
                         partialResult inside the deadline

Installation: programmatic (``install(Fault(...))`` — what the chaos
suite uses), or the ``PINOT_TPU_FAULTS`` env var parsed once at first
use: ``point[@target]=mode[:arg][#times]`` entries joined by ``;``, e.g.

    PINOT_TPU_FAULTS="transport.submit@server_1=blackhole;
                      transport.submit@server_2=delay:200"

Modes: ``error`` (raise FaultInjected), ``crash`` (raise — callers place
the seam so the exception escapes in-band handling), ``delay:<ms>``
(sleep, then proceed), ``blackhole[:<ms>]`` (sleep the full window —
default 60s — then raise: the caller's own deadline fires first, like a
dropped-packets replica). ``#N`` fires the fault at most N times then
disarms (e.g. ``device.launch=error#2`` poisons exactly the launch and
its retry).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("pinot_tpu.faults")

# fast-path gate: seams check ``if faults.ACTIVE:`` before calling
# inject() — with no faults installed, production pays one module-attr
# read and a falsy test per seam
ACTIVE = False

_BLACKHOLE_DEFAULT_MS = 60_000.0


class FaultInjected(RuntimeError):
    """An injected failure (transport/server/promotion seams)."""


class InjectedDeviceError(RuntimeError):
    """Injected device-runtime failure. Deliberately NOT a FaultInjected
    subclass: the device recovery path must treat it exactly like an
    XlaRuntimeError it cannot distinguish from a real one."""


@dataclasses.dataclass
class Fault:
    point: str                      # injection point name
    target: Optional[str] = None    # substring match on the seam's target
    mode: str = "error"             # error | crash | delay | blackhole
    delay_ms: float = 0.0
    times: Optional[int] = None     # fire at most N times; None = always
    fired: int = 0                  # observability: how often it fired

    def matches(self, point: str, target) -> bool:
        if self.point != point:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.target is None:
            return True
        return target is not None and self.target in str(target)


_lock = threading.Lock()
_faults: list[Fault] = []
_env_loaded = False


def install(fault: Fault) -> Fault:
    """Arm a fault. Returns it (the caller can read ``fired`` later)."""
    global ACTIVE
    with _lock:
        _faults.append(fault)
        ACTIVE = True
    return fault


def clear(point: Optional[str] = None) -> None:
    """Disarm faults (all, or just one point's)."""
    global ACTIVE
    with _lock:
        if point is None:
            _faults.clear()
        else:
            _faults[:] = [f for f in _faults if f.point != point]
        ACTIVE = bool(_faults)


def active_faults() -> list:
    with _lock:
        return list(_faults)


def parse_spec(spec: str) -> list:
    """``point[@target]=mode[:arg][#times]`` entries joined by ``;``."""
    out = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        lhs, rhs = entry.split("=", 1)
        point, _, target = lhs.partition("@")
        target = target or None
        times = None
        if "#" in rhs:
            rhs, times_s = rhs.rsplit("#", 1)
            times = int(times_s)
        mode, _, arg = rhs.partition(":")
        delay_ms = float(arg) if arg else (
            _BLACKHOLE_DEFAULT_MS if mode == "blackhole" else 0.0)
        out.append(Fault(point=point.strip(),
                         target=target.strip() if target is not None
                         else None,
                         mode=mode.strip(), delay_ms=delay_ms, times=times))
    return out


def install_from_env(env_var: str = "PINOT_TPU_FAULTS") -> int:
    """Parse the env spec once; safe to call repeatedly."""
    global _env_loaded
    with _lock:
        if _env_loaded:
            return 0
        _env_loaded = True
    spec = os.environ.get(env_var, "")
    if not spec:
        return 0
    faults = parse_spec(spec)
    for f in faults:
        install(f)
    if faults:
        log.warning("fault injection ARMED from %s: %s", env_var, faults)
    return len(faults)


# arm env-configured faults at import: the seams' ACTIVE check must see
# them without every process having to call install_from_env explicitly
install_from_env()


def inject(point: str, target=None, bound_ms: float = None) -> None:
    """Fire any armed fault matching (point, target). Called by seams
    only when ``ACTIVE`` is truthy. ``delay`` sleeps then returns;
    ``blackhole`` sleeps its window then raises; ``error``/``crash``
    raise immediately. ``device.*`` points raise InjectedDeviceError so
    the recovery path exercises its real XlaRuntimeError handling.

    ``bound_ms``: the caller's own deadline — a blackhole sleeps at most
    this long before failing (a real blackholed RPC would be cut by the
    transport deadline the same way; without the bound, every blackholed
    call would pin a broker pool thread for the full window)."""
    with _lock:
        hit = next((f for f in _faults if f.matches(point, target)), None)
        if hit is None:
            return
        hit.fired += 1
    msg = f"injected fault at {point}" + \
        (f" (target {target})" if target is not None else "")
    if hit.mode == "delay":
        time.sleep(hit.delay_ms / 1000.0)
        return
    if hit.mode == "blackhole":
        window_ms = hit.delay_ms or _BLACKHOLE_DEFAULT_MS
        if bound_ms is not None:
            window_ms = max(0.0, min(window_ms, bound_ms))
        time.sleep(window_ms / 1000.0)
        raise FaultInjected(f"{msg}: blackhole window elapsed")
    if point.startswith("device."):
        raise InjectedDeviceError(f"{msg}: RESOURCE_EXHAUSTED (simulated)")
    raise FaultInjected(msg)
