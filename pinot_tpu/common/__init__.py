from pinot_tpu.common.datatypes import DataType, FieldRole
from pinot_tpu.common.schema import FieldSpec, Schema
