"""End-to-end query deadlines.

The reference honors ``timeoutMs`` at every tier: the broker stamps a
deadline when the request arrives and ships the *remaining* budget to each
server in the InstanceRequest; servers check it at admission and during
execution, answering with a QUERY_TIMEOUT-coded exception (errorCode 250
family) instead of running to completion after the client gave up. This
module is that budget object: created once per query, decremented by
wall-clock, consulted at every blocking seam (compile semaphore, scheduler
admission, device fetch, host fallback gate, peer fetch, broker gather).

Monotonic-clock based: wall-clock steps (NTP) must not spuriously expire
or extend a query's budget.
"""

from __future__ import annotations

import time

# reference errorCode for a query that ran out of budget
# (QueryException.BROKER_TIMEOUT_ERROR_CODE shape)
QUERY_TIMEOUT_ERROR_CODE = 250


class QueryTimeout(Exception):
    """The query's deadline expired. Carries where the budget ran out so
    the in-band error names the seam (admission vs fetch vs gather)."""

    error_code = QUERY_TIMEOUT_ERROR_CODE


class Deadline:
    """Absolute per-query deadline; cheap to consult."""

    __slots__ = ("at", "budget_s")

    def __init__(self, timeout_s: float):
        self.budget_s = max(0.0, float(timeout_s))
        self.at = time.monotonic() + self.budget_s

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(float(ms) / 1000.0)

    def remaining_s(self) -> float:
        return self.at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def check(self, where: str) -> None:
        """Raise QueryTimeout when the budget is gone."""
        if self.expired():
            raise QueryTimeout(
                f"QUERY_TIMEOUT at {where}: budget "
                f"{self.budget_s * 1000:.0f}ms exhausted")

    def clamp(self, timeout_s: float) -> float:
        """A wait bounded by BOTH its own cap and the remaining budget
        (never negative — an expired deadline yields an immediate-timeout
        wait, and the caller's post-wait check raises)."""
        return max(0.0, min(float(timeout_s), self.remaining_s()))
