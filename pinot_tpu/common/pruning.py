"""Conservative min/max interval pruning — ONE copy of the bound algebra.

Shared by the broker's routing pruner (broker/segment_pruner.py, over
SegmentRecord column stats) and the server/device stats pruner
(engine/engine.py SegmentPruner, over segment metadata): the two tiers must
coerce and compare identically or broker-pruned segments would diverge from
what the server itself would prune.
"""

from __future__ import annotations

from pinot_tpu.query.context import Predicate, PredicateType


def _lt(a, b) -> bool:
    """STRICT comparison: mixed str/number pairs raise TypeError, which
    callers treat as "incomparable → may match". Coercing them to strings
    (lexicographic order) could prune a segment whose scan would REJECT
    the same literal with a type error — a query would silently return
    empty from pruned segments and error from surviving ones."""
    if isinstance(a, str) != isinstance(b, str):
        raise TypeError(
            f"incomparable literal: {type(a).__name__} vs {type(b).__name__}")
    return a < b


def interval_may_match(p: Predicate, mn, mx) -> bool:
    """May any value in [mn, mx] satisfy the predicate? Conservative: only
    EQ/IN/RANGE can prove exclusion, missing bounds and incomparable
    literals always "may match" (ColumnValueSegmentPruner's min/max
    check)."""
    if mn is None or mx is None:
        return True
    try:
        if p.type is PredicateType.EQ:
            return not (_lt(p.value, mn) or _lt(mx, p.value))
        if p.type is PredicateType.IN and p.values:
            return any(not (_lt(v, mn) or _lt(mx, v)) for v in p.values)
        if p.type is PredicateType.RANGE:
            if p.lower is not None:
                if _lt(mx, p.lower) or \
                        (mx == p.lower and not p.lower_inclusive):
                    return False
            if p.upper is not None:
                if _lt(p.upper, mn) or \
                        (mn == p.upper and not p.upper_inclusive):
                    return False
    except TypeError:
        return True  # incomparable literal: cannot prune
    return True


def provably_absent(seg, col: str, values) -> bool:
    """None of ``values`` can occur in the segment: exact dictionary
    membership when the segment reader exposes a (sorted, immutable)
    dictionary, else the bloom bitset. Conservative — any doubt (no
    index, uncastable literal) proves nothing. ONE copy shared by the
    server/device stats pruner (engine.SegmentPruner) and the host scan
    path's EQ/IN predicate short-circuit (engine/host.py)."""
    try:
        d = seg.dictionary(col)
    except Exception:  # noqa: BLE001 — reader without dictionaries
        d = None
    if d is not None:
        try:
            return len(d.ids_of(list(values))) == 0
        except Exception:  # noqa: BLE001 — uncastable literal: no prune
            return False
    bloom_fn = getattr(seg, "bloom", None)
    bits = bloom_fn(col) if bloom_fn is not None else None
    if bits is not None:
        from pinot_tpu.storage.bloom import BloomFilter

        try:
            bf = BloomFilter(bits)
            return not any(bf.might_contain(v) for v in values)
        except Exception:  # noqa: BLE001 — odd literal: no prune
            return False
    return False
