"""Metrics registry: counters, gauges, timers + latency histograms.

Equivalent of the reference's metrics SPI
(pinot-common/.../metrics/AbstractMetrics.java + BrokerMetrics /
ServerMetrics / ControllerMetrics / MinionMetrics over yammer): named
meters/gauges/timers keyed ``component.name[.tag]``, aggregated
in-process and exported as a snapshot dict or Prometheus text. The
yammer backend is replaced by lock-cheap python primitives — emission to
an external system is a reporter's job (register one with
``add_reporter``), matching the SPI split.

Every timer key ALSO maintains a log-bucketed :class:`Histogram` (the
yammer ``Histogram``/``Timer`` percentile role): p50/p90/p99/p999 ride
the snapshot and the Prometheus exposition emits a real ``histogram``
family (``_bucket{le=...}``/``_sum``/``_count`` + ``# HELP``/``# TYPE``)
per key. One update feeds both — there is ONE latency truth; consumers
that need a quantile (the broker's adaptive hedge delay, dashboards)
read it from here instead of keeping private sample windows.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Optional

# ---------------------------------------------------------------------------
# histogram buckets: geometric (log-spaced) bounds shared by every
# Histogram instance — factor 2**0.25 (~19% bucket width) from 10 µs to
# ~2.8 hours, so quantile interpolation error is bounded by one bucket
# (<~19% relative) across the whole range a query path can produce.
# ---------------------------------------------------------------------------
_HIST_FACTOR = 2.0 ** 0.25
_HIST_MIN_MS = 1e-2
_HIST_NBUCKETS = 120  # upper bound of last finite bucket ≈ 1e7 ms
HIST_BOUNDS_MS = tuple(_HIST_MIN_MS * _HIST_FACTOR ** i
                       for i in range(_HIST_NBUCKETS))


class Histogram:
    """Log-bucketed latency histogram (ms). Fixed global bounds keep
    updates O(log B) and merging trivial; quantiles interpolate linearly
    inside the containing bucket and clamp to the observed min/max."""

    __slots__ = ("counts", "count", "total_ms", "min_ms", "max_ms")

    def __init__(self):
        # counts[i] observes (bounds[i-1], bounds[i]]; the last slot is
        # the overflow bucket above the final finite bound
        self.counts = [0] * (_HIST_NBUCKETS + 1)
        self.count = 0
        self.total_ms = 0.0
        self.min_ms = float("inf")
        self.max_ms = 0.0

    def update(self, ms: float) -> None:
        self.counts[bisect.bisect_left(HIST_BOUNDS_MS, ms)] += 1
        self.count += 1
        self.total_ms += ms
        if ms < self.min_ms:
            self.min_ms = ms
        if ms > self.max_ms:
            self.max_ms = ms

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile with in-bucket linear interpolation;
        0.0 when empty (callers that need a default should check
        ``count`` first)."""
        if self.count == 0:
            return 0.0
        import math

        target = max(1, min(self.count, math.ceil(q * self.count)))
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = 0.0 if i == 0 else HIST_BOUNDS_MS[i - 1]
                hi = HIST_BOUNDS_MS[i] if i < _HIST_NBUCKETS else self.max_ms
                frac = (target - cum) / c
                val = lo + frac * (hi - lo)
                return float(min(max(val, self.min_ms), self.max_ms))
            cum += c
        return float(self.max_ms)

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0, "p50Ms": 0.0, "p90Ms": 0.0, "p99Ms": 0.0,
                    "p999Ms": 0.0}
        return {
            "count": self.count,
            "p50Ms": round(self.quantile(0.50), 3),
            "p90Ms": round(self.quantile(0.90), 3),
            "p99Ms": round(self.quantile(0.99), 3),
            "p999Ms": round(self.quantile(0.999), 3),
        }


class Timer:
    """count / total / min / max over observed durations (ms)."""

    __slots__ = ("count", "total_ms", "min_ms", "max_ms")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.min_ms = float("inf")
        self.max_ms = 0.0

    def update(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms < self.min_ms:
            self.min_ms = ms
        if ms > self.max_ms:
            self.max_ms = ms

    def snapshot(self) -> dict:
        avg = self.total_ms / self.count if self.count else 0.0
        return {"count": self.count, "totalMs": round(self.total_ms, 3),
                "avgMs": round(avg, 3),
                "minMs": round(self.min_ms, 3) if self.count else 0.0,
                "maxMs": round(self.max_ms, 3)}


# Prometheus metric names must match [a-zA-Z_:][a-zA-Z0-9_:]* — but
# registry keys carry free-form tags (instance ids, traceInfo-derived
# attempt keys like "inst (retry)", table names with dots). EVERY
# illegal character maps to "_" so the exposition stays parseable by a
# real scraper; the "pinot_tpu_" prefix keeps the first character legal.
_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(k: str) -> str:
    """Registry key → legal Prometheus metric name (ISSUE 11 satellite:
    spaces/parens in instance-keyed names previously emitted an
    exposition prometheus_client refuses to parse)."""
    return "pinot_tpu_" + _PROM_NAME_RE.sub("_", k)


class MetricsRegistry:
    def __init__(self, component: str = ""):
        self.component = component
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, Callable | float] = {}
        self._timers: dict[str, Timer] = {}
        self._hists: dict[str, Histogram] = {}
        self._reporters: list[Callable] = []

    def _key(self, name: str, tag: Optional[str]) -> str:
        parts = [p for p in (self.component, name, tag) if p]
        return ".".join(parts)

    # ---- meters (addMeteredTableValue analog) ---------------------------
    def count(self, name: str, value: float = 1, tag: Optional[str] = None) -> None:
        key = self._key(name, tag)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    # ---- gauges (setOrUpdateGauge analog) -------------------------------
    def gauge(self, name: str, value, tag: Optional[str] = None) -> None:
        """``value``: a number, or a zero-arg callable sampled at snapshot
        time (the reference's Gauge<Long> suppliers)."""
        with self._lock:
            self._gauges[self._key(name, tag)] = value

    def remove_gauge(self, name: str, tag: Optional[str] = None) -> None:
        """Unregister (removeGauge analog) — component teardown MUST call
        this for callable gauges, or their closures pin the dead component
        (and everything it references) in the process-global registry."""
        with self._lock:
            self._gauges.pop(self._key(name, tag), None)

    def gauge_keys(self, tag: str) -> list:
        """Registered gauge keys carrying ``tag`` as their last segment —
        the leak audit surface: after a component's stop(), this must be
        empty for its instance id."""
        suffix = "." + tag
        with self._lock:
            return [k for k in self._gauges if k.endswith(suffix)]

    # ---- timers + histograms (addTimedTableValue analog) ----------------
    def time_ms(self, name: str, ms: float, tag: Optional[str] = None) -> None:
        """One observation feeds BOTH the legacy count/avg/min/max timer
        and the log-bucketed histogram under the same key."""
        key = self._key(name, tag)
        with self._lock:
            t = self._timers.get(key)
            if t is None:
                t = self._timers[key] = Timer()
                self._hists[key] = Histogram()
            t.update(ms)
            self._hists[key].update(ms)

    # observe() is the histogram-forward alias: same storage, same key —
    # call sites that think in distributions rather than timers read better
    observe = time_ms

    def quantile(self, name: str, q: float,
                 tag: Optional[str] = None) -> Optional[float]:
        """Histogram quantile in ms for ``name[.tag]``; None when no
        sample was ever recorded (callers supply their own default)."""
        with self._lock:
            h = self._hists.get(self._key(name, tag))
            if h is None or h.count == 0:
                return None
            return h.quantile(q)

    class _Span:
        __slots__ = ("reg", "name", "tag", "t0")

        def __init__(self, reg, name, tag):
            self.reg, self.name, self.tag = reg, name, tag

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.reg.time_ms(self.name, (time.perf_counter() - self.t0) * 1000,
                             self.tag)
            return False

    def timed(self, name: str, tag: Optional[str] = None) -> "_Span":
        return self._Span(self, name, tag)

    # ---- lifecycle ------------------------------------------------------
    def reset(self) -> None:
        """Drop every counter/gauge/timer/histogram (reporters stay).
        Component teardown in tests calls this so a RESTARTED instance
        can't double-count against the process-global registry."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._hists.clear()

    # ---- export ---------------------------------------------------------
    def add_reporter(self, fn: Callable[[dict], None]) -> None:
        self._reporters.append(fn)

    def report(self) -> None:
        snap = self.snapshot()
        for fn in self._reporters:
            fn(snap)

    def snapshot(self) -> dict:
        with self._lock:
            gauges = {}
            for k, v in self._gauges.items():
                try:
                    gauges[k] = v() if callable(v) else v
                except Exception:  # noqa: BLE001 — sampling must not throw
                    gauges[k] = None
            return {
                "counters": dict(self._counters),
                "gauges": gauges,
                "timers": {k: t.snapshot() for k, t in self._timers.items()},
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (the common reporter target).
        Timers export as real ``histogram`` families: cumulative
        ``_bucket{le=...}`` lines (only buckets where the cumulative
        count advances, plus ``+Inf`` — a sparse but valid exposition),
        ``_sum``/``_count``, and a separate untyped ``_max`` sample."""

        lines = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = []
            for k, v in sorted(self._gauges.items()):
                try:
                    gauges.append((k, v() if callable(v) else v))
                except Exception:  # noqa: BLE001 — sampling must not throw
                    gauges.append((k, None))
            hists = [(k, h.counts[:], h.count, h.total_ms, h.max_ms)
                     for k, h in sorted(self._hists.items())]
        for k, v in counters:
            base = sanitize(k) + "_total"
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base} {v}")
        for k, v in gauges:
            if v is not None:
                base = sanitize(k)
                lines.append(f"# TYPE {base} gauge")
                lines.append(f"{base} {v}")
        for k, counts, count, total_ms, max_ms in hists:
            base = sanitize(k) + "_ms"
            lines.append(f"# HELP {base} latency distribution of {k} "
                         f"in milliseconds")
            lines.append(f"# TYPE {base} histogram")
            cum = 0
            for i, c in enumerate(counts):
                if c == 0 or i >= _HIST_NBUCKETS:
                    continue
                cum += c
                lines.append(
                    f'{base}_bucket{{le="{HIST_BOUNDS_MS[i]:.6g}"}} {cum}')
            lines.append(f'{base}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{base}_sum {round(total_ms, 3)}")
            lines.append(f"{base}_count {count}")
            lines.append(f"{base}_max {round(max_ms, 3)}")
        return "\n".join(lines) + "\n"


# process-wide default registries, one per role (BrokerMetrics.get() style)
_registries: dict[str, MetricsRegistry] = {}
_reg_lock = threading.Lock()


def get_metrics(component: str) -> MetricsRegistry:
    with _reg_lock:
        reg = _registries.get(component)
        if reg is None:
            reg = _registries[component] = MetricsRegistry(component)
        return reg


def reset_metrics(component: Optional[str] = None) -> None:
    """Reset one component's registry (or ALL when None). Registry
    OBJECTS survive — components hold references to them — only their
    contents clear. The test-isolation / restart story: process-global
    registries otherwise accumulate across ServerInstance lifecycles."""
    with _reg_lock:
        regs = ([_registries[component]] if component in _registries
                else [] if component is not None
                else list(_registries.values()))
    for reg in regs:
        reg.reset()


def all_snapshots() -> dict:
    with _reg_lock:
        return {name: reg.snapshot() for name, reg in _registries.items()}


def all_prometheus_text() -> str:
    with _reg_lock:
        regs = list(_registries.values())
    return "".join(reg.prometheus_text() for reg in regs)
