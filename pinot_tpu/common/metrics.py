"""Metrics registry: counters, gauges, timers per component.

Equivalent of the reference's metrics SPI
(pinot-common/.../metrics/AbstractMetrics.java + BrokerMetrics /
ServerMetrics / ControllerMetrics / MinionMetrics over yammer): named
meters/gauges/timers keyed ``component.name[.tag]``, aggregated
in-process and exported as a snapshot dict or Prometheus text. The
yammer backend is replaced by lock-cheap python primitives — emission to
an external system is a reporter's job (register one with
``add_reporter``), matching the SPI split."""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Timer:
    """count / total / min / max over observed durations (ms)."""

    __slots__ = ("count", "total_ms", "min_ms", "max_ms")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.min_ms = float("inf")
        self.max_ms = 0.0

    def update(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms < self.min_ms:
            self.min_ms = ms
        if ms > self.max_ms:
            self.max_ms = ms

    def snapshot(self) -> dict:
        avg = self.total_ms / self.count if self.count else 0.0
        return {"count": self.count, "totalMs": round(self.total_ms, 3),
                "avgMs": round(avg, 3),
                "minMs": round(self.min_ms, 3) if self.count else 0.0,
                "maxMs": round(self.max_ms, 3)}


class MetricsRegistry:
    def __init__(self, component: str = ""):
        self.component = component
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, Callable | float] = {}
        self._timers: dict[str, Timer] = {}
        self._reporters: list[Callable] = []

    def _key(self, name: str, tag: Optional[str]) -> str:
        parts = [p for p in (self.component, name, tag) if p]
        return ".".join(parts)

    # ---- meters (addMeteredTableValue analog) ---------------------------
    def count(self, name: str, value: float = 1, tag: Optional[str] = None) -> None:
        key = self._key(name, tag)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    # ---- gauges (setOrUpdateGauge analog) -------------------------------
    def gauge(self, name: str, value, tag: Optional[str] = None) -> None:
        """``value``: a number, or a zero-arg callable sampled at snapshot
        time (the reference's Gauge<Long> suppliers)."""
        with self._lock:
            self._gauges[self._key(name, tag)] = value

    def remove_gauge(self, name: str, tag: Optional[str] = None) -> None:
        """Unregister (removeGauge analog) — component teardown MUST call
        this for callable gauges, or their closures pin the dead component
        (and everything it references) in the process-global registry."""
        with self._lock:
            self._gauges.pop(self._key(name, tag), None)

    # ---- timers (addTimedTableValue analog) -----------------------------
    def time_ms(self, name: str, ms: float, tag: Optional[str] = None) -> None:
        key = self._key(name, tag)
        with self._lock:
            t = self._timers.get(key)
            if t is None:
                t = self._timers[key] = Timer()
            t.update(ms)

    class _Span:
        __slots__ = ("reg", "name", "tag", "t0")

        def __init__(self, reg, name, tag):
            self.reg, self.name, self.tag = reg, name, tag

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.reg.time_ms(self.name, (time.perf_counter() - self.t0) * 1000,
                             self.tag)
            return False

    def timed(self, name: str, tag: Optional[str] = None) -> "_Span":
        return self._Span(self, name, tag)

    # ---- export ---------------------------------------------------------
    def add_reporter(self, fn: Callable[[dict], None]) -> None:
        self._reporters.append(fn)

    def report(self) -> None:
        snap = self.snapshot()
        for fn in self._reporters:
            fn(snap)

    def snapshot(self) -> dict:
        with self._lock:
            gauges = {}
            for k, v in self._gauges.items():
                try:
                    gauges[k] = v() if callable(v) else v
                except Exception:  # noqa: BLE001 — sampling must not throw
                    gauges[k] = None
            return {
                "counters": dict(self._counters),
                "gauges": gauges,
                "timers": {k: t.snapshot() for k, t in self._timers.items()},
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (the common reporter target)."""

        def sanitize(k: str) -> str:
            return "pinot_tpu_" + k.replace(".", "_").replace("-", "_")

        lines = []
        snap = self.snapshot()
        for k, v in sorted(snap["counters"].items()):
            lines.append(f"{sanitize(k)}_total {v}")
        for k, v in sorted(snap["gauges"].items()):
            if v is not None:
                lines.append(f"{sanitize(k)} {v}")
        for k, t in sorted(snap["timers"].items()):
            base = sanitize(k)
            lines.append(f"{base}_ms_count {t['count']}")
            lines.append(f"{base}_ms_sum {t['totalMs']}")
            lines.append(f"{base}_ms_max {t['maxMs']}")
        return "\n".join(lines) + "\n"


# process-wide default registries, one per role (BrokerMetrics.get() style)
_registries: dict[str, MetricsRegistry] = {}
_reg_lock = threading.Lock()


def get_metrics(component: str) -> MetricsRegistry:
    with _reg_lock:
        reg = _registries.get(component)
        if reg is None:
            reg = _registries[component] = MetricsRegistry(component)
        return reg


def all_snapshots() -> dict:
    with _reg_lock:
        return {name: reg.snapshot() for name, reg in _registries.items()}


def all_prometheus_text() -> str:
    with _reg_lock:
        regs = list(_registries.values())
    return "".join(reg.prometheus_text() for reg in regs)
