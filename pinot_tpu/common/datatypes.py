"""Logical data types and field roles.

Equivalent surface to the reference's ``FieldSpec.DataType`` enum
(pinot-spi/.../data/FieldSpec.java:383-398) and the dimension/metric/datetime
field taxonomy, re-expressed with numpy/JAX storage mappings instead of Java
stored types.
"""

from __future__ import annotations

import enum

import numpy as np


class DataType(enum.Enum):
    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    BIG_DECIMAL = "BIG_DECIMAL"
    BOOLEAN = "BOOLEAN"
    TIMESTAMP = "TIMESTAMP"  # millis since epoch, stored as LONG
    STRING = "STRING"
    JSON = "JSON"
    BYTES = "BYTES"

    # ---- classification -------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_integral(self) -> bool:
        return self in (DataType.INT, DataType.LONG, DataType.BOOLEAN, DataType.TIMESTAMP)

    @property
    def is_floating(self) -> bool:
        return self in (DataType.FLOAT, DataType.DOUBLE, DataType.BIG_DECIMAL)

    @property
    def is_string_like(self) -> bool:
        return self in (DataType.STRING, DataType.JSON, DataType.BYTES)

    # ---- storage mappings ----------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        """Host numpy storage dtype for raw (non-dict-encoded) values."""
        return _NP_DTYPES[self]

    @property
    def device_dtype(self) -> np.dtype:
        """On-device dtype for raw value columns.

        Integral types widen to int64 so block sums stay exact (TPU lowers
        int64 arithmetic to int32 pairs); floats compute in float32 with
        float64-on-host final reduction.
        """
        if self.is_integral:
            return np.dtype(np.int64)
        if self.is_floating:
            return np.dtype(np.float32)
        raise ValueError(f"{self} has no raw device representation (dict-encode it)")

    @property
    def default_null(self):
        """Default null placeholder, mirroring FieldSpec default null values."""
        return _NULL_DEFAULTS[self]

    def convert(self, value):
        """Coerce an ingested python value to this type's canonical python value."""
        if value is None:
            return self.default_null
        if self is DataType.BOOLEAN:
            if isinstance(value, str):
                return 1 if value.strip().lower() in ("true", "1") else 0
            return int(bool(value))
        if self.is_integral:
            return int(value)
        if self.is_floating:
            return float(value)
        if self is DataType.BYTES:
            if isinstance(value, str):
                return bytes.fromhex(value)
            return bytes(value)
        return str(value)


_NUMERIC = frozenset(
    {
        DataType.INT,
        DataType.LONG,
        DataType.FLOAT,
        DataType.DOUBLE,
        DataType.BIG_DECIMAL,
        DataType.BOOLEAN,
        DataType.TIMESTAMP,
    }
)

_NP_DTYPES = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    DataType.BIG_DECIMAL: np.dtype(np.float64),
    DataType.BOOLEAN: np.dtype(np.int32),
    DataType.TIMESTAMP: np.dtype(np.int64),
    DataType.STRING: np.dtype(object),
    DataType.JSON: np.dtype(object),
    DataType.BYTES: np.dtype(object),
}

_NULL_DEFAULTS = {
    DataType.INT: -(2**31),
    DataType.LONG: -(2**63),
    DataType.FLOAT: float("-inf"),
    DataType.DOUBLE: float("-inf"),
    DataType.BIG_DECIMAL: float("-inf"),
    DataType.BOOLEAN: 0,
    DataType.TIMESTAMP: 0,
    DataType.STRING: "null",
    DataType.JSON: "null",
    DataType.BYTES: b"",
}


class FieldRole(enum.Enum):
    """Dimension vs metric vs datetime, as in the reference's FieldSpec subclasses."""

    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    DATE_TIME = "DATE_TIME"
