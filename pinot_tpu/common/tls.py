"""TLS configuration for the data plane (gRPC) and HTTP surfaces.

Reference: pinot-common/.../config/TlsConfig.java:1 + NettyConfig — one
keystore/truststore config shared by every listener and client channel.
Here: PEM file paths resolved from layered configuration
(``pinot.tls.*``), turned into gRPC credentials or an ssl.SSLContext.

Keys (Configuration / PINOT_TPU_ env):
- ``pinot.tls.enabled``      — master switch (default false)
- ``pinot.tls.cert_file``    — server certificate chain (PEM)
- ``pinot.tls.key_file``     — server private key (PEM)
- ``pinot.tls.ca_file``      — trust roots for clients/peers (PEM);
                               defaults to cert_file for self-signed setups
- ``pinot.tls.client_auth``  — require client certificates (mTLS)
- ``pinot.tls.target_name_override`` — expected server cert hostname when
  dialing by IP (test/dev convenience, grpc.ssl_target_name_override)
"""

from __future__ import annotations

import dataclasses
import ssl
from typing import Optional


@dataclasses.dataclass
class TlsConfig:
    cert_file: str
    key_file: str
    ca_file: Optional[str] = None
    client_auth: bool = False
    target_name_override: Optional[str] = None

    @classmethod
    def from_config(cls, cfg=None, prefix: str = "pinot.tls") -> Optional["TlsConfig"]:
        """None when TLS is not enabled in the layered config."""
        if cfg is None:
            from pinot_tpu.common.config import Configuration

            cfg = Configuration()
        if not cfg.get_bool(f"{prefix}.enabled", False):
            return None
        cert = cfg.get(f"{prefix}.cert_file")
        key = cfg.get(f"{prefix}.key_file")
        if not cert or not key:
            raise ValueError(
                f"{prefix}.enabled=true requires {prefix}.cert_file and "
                f"{prefix}.key_file")
        return cls(
            cert_file=cert,
            key_file=key,
            ca_file=cfg.get(f"{prefix}.ca_file") or None,
            client_auth=cfg.get_bool(f"{prefix}.client_auth", False),
            target_name_override=cfg.get(f"{prefix}.target_name_override")
            or None,
        )

    # ---- gRPC ------------------------------------------------------------
    def server_credentials(self):
        import grpc

        with open(self.key_file, "rb") as f:
            key = f.read()
        with open(self.cert_file, "rb") as f:
            chain = f.read()
        roots = None
        if self.client_auth:
            with open(self.ca_file or self.cert_file, "rb") as f:
                roots = f.read()
        return grpc.ssl_server_credentials(
            [(key, chain)],
            root_certificates=roots,
            require_client_auth=self.client_auth,
        )

    def channel_credentials(self):
        import grpc

        with open(self.ca_file or self.cert_file, "rb") as f:
            roots = f.read()
        key = chain = None
        if self.client_auth:
            with open(self.key_file, "rb") as f:
                key = f.read()
            with open(self.cert_file, "rb") as f:
                chain = f.read()
        return grpc.ssl_channel_credentials(
            root_certificates=roots, private_key=key, certificate_chain=chain
        )

    def channel_options(self) -> list:
        if self.target_name_override:
            return [("grpc.ssl_target_name_override",
                     self.target_name_override)]
        return []

    # ---- HTTP ------------------------------------------------------------
    def server_ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.client_auth:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(self.ca_file or self.cert_file)
        return ctx

    def client_ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.create_default_context(cafile=self.ca_file or self.cert_file)
        if self.client_auth:
            ctx.load_cert_chain(self.cert_file, self.key_file)
        return ctx


def generate_self_signed(dir_path: str, common_name: str = "localhost",
                         san_ips=("127.0.0.1",)) -> TlsConfig:
    """Dev/test helper: mint a self-signed cert + key under ``dir_path``
    (the reference ships test keystores; here certs are generated on
    demand so none are checked in)."""
    import datetime
    import os

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    import ipaddress

    san = x509.SubjectAlternativeName(
        [x509.DNSName(common_name)]
        + [x509.IPAddress(ipaddress.ip_address(ip)) for ip in san_ips]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(san, critical=False)
        .sign(key, hashes.SHA256())
    )
    os.makedirs(dir_path, exist_ok=True)
    cert_file = os.path.join(dir_path, "tls.crt")
    key_file = os.path.join(dir_path, "tls.key")
    with open(cert_file, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_file, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ))
    return TlsConfig(cert_file=cert_file, key_file=key_file,
                     target_name_override=common_name)
