"""Plugin registry: one discovery surface for every pluggable kind.

Equivalent of the reference's plugin framework
(pinot-spi/.../plugin/PluginManager.java + the pinot-plugins/* tree):
kind-keyed factories (stream types, message decoders, record readers,
filesystems, minion task executors) behind one ``register``/``load``
surface. The reference isolates plugins with per-plugin classloaders and
discovers them from a plugins dir; here the python import system is the
plugin boundary — ``PINOT_TPU_PLUGINS`` names modules to import at
bootstrap, and importing a plugin module registers its factories (the
side-effect contract the reference's ServiceLoader files play).
"""

from __future__ import annotations

import importlib
import logging
import os
import threading

log = logging.getLogger("pinot_tpu.plugins")

PLUGINS_ENV = "PINOT_TPU_PLUGINS"


class PluginRegistry:
    def __init__(self):
        # RLock: _bootstrap holds it across _register_builtins, whose
        # modules call back into register() on this same thread
        self._lock = threading.RLock()
        self._plugins: dict[tuple, object] = {}
        self._bootstrapped = False

    def register(self, kind: str, name: str, factory) -> None:
        with self._lock:
            self._plugins[(kind, name.lower())] = factory

    def load(self, kind: str, name: str):
        self._bootstrap()
        with self._lock:
            try:
                return self._plugins[(kind, name.lower())]
            except KeyError:
                have = sorted(n for k, n in self._plugins if k == kind)
                raise KeyError(
                    f"no {kind!r} plugin named {name!r}; registered: {have}"
                ) from None

    def available(self, kind: str) -> list:
        self._bootstrap()
        with self._lock:
            return sorted(n for k, n in self._plugins if k == kind)

    def _bootstrap(self) -> None:
        """Register built-ins + import PINOT_TPU_PLUGINS modules, once.
        Runs entirely under the lock so a concurrent load() never observes
        a half-registered state; the done-flag is only set on success, so
        a transient import failure retries instead of poisoning the
        registry for the process lifetime."""
        with self._lock:
            if self._bootstrapped:
                return
            self._register_builtins()
            self.load_env_plugins()
            self._bootstrapped = True

    def load_env_plugins(self) -> list:
        """Import every module named in PINOT_TPU_PLUGINS (idempotent —
        python caches the import; a module's registrations land on the
        GLOBAL registry it imports). Returns the modules loaded."""
        loaded = []
        for mod in filter(None, os.environ.get(PLUGINS_ENV, "").split(",")):
            try:
                loaded.append(importlib.import_module(mod.strip()))
            except Exception:  # noqa: BLE001 — one bad plugin ≠ dead process
                log.exception("failed to load plugin module %s", mod)
        return loaded

    def _register_builtins(self) -> None:
        from pinot_tpu.ingestion import readers as _readers
        from pinot_tpu.storage import fs as _fs
        from pinot_tpu.stream import memory_stream  # noqa: F401 (registers)
        from pinot_tpu.stream import spi as _stream

        self.register("fs", "file", _fs.LocalFS)
        self.register("fs", "", _fs.LocalFS)  # bare paths
        from pinot_tpu.storage import gcsfs as _gcsfs
        from pinot_tpu.storage import s3fs as _s3fs

        self.register("fs", "s3", _s3fs.S3FS)  # gated on boto3 at init
        self.register("fs", "gs", _gcsfs.GcsFS)  # gated on google-cloud
        from pinot_tpu.storage import adlsfs as _adlsfs

        self.register("fs", "abfss", _adlsfs.AdlsFS)  # gated on azure sdk
        from pinot_tpu.storage import hdfsfs as _hdfsfs

        self.register("fs", "hdfs", _hdfsfs.HdfsFS)  # WebHDFS REST (stdlib)
        for name, cls in _stream._FACTORIES.items():
            self.register("stream", name, cls)
        for name, fn in _stream._DECODERS.items():
            self.register("decoder", name, fn)
        for name, cls in _readers._READERS.items():
            self.register("record_reader", name, cls)
        from pinot_tpu.minion import tasks as _tasks

        for name, fn in _tasks.TASK_EXECUTORS.items():
            self.register("minion_task", name, fn)


plugin_registry = PluginRegistry()
