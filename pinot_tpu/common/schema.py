"""Table schema: named, typed, role-tagged columns.

Equivalent surface to the reference's ``Schema`` / ``FieldSpec``
(pinot-spi/.../data/Schema.java, FieldSpec.java): dimension / metric /
datetime fields, single- or multi-value, JSON round-trip compatible with the
reference's schema JSON shape (dimensionFieldSpecs etc.) so existing table
definitions can be reused.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable

from pinot_tpu.common.datatypes import DataType, FieldRole


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    name: str
    data_type: DataType
    role: FieldRole = FieldRole.DIMENSION
    single_value: bool = True
    default_null: object = None
    # DATE_TIME only: format/granularity strings (kept opaque, as in
    # DateTimeFieldSpec).
    format: str | None = None
    granularity: str | None = None

    def null_value(self):
        if self.default_null is not None:
            return self.default_null
        # FieldSpec defaults: metrics null to ZERO (additive identity),
        # dimensions/datetimes to the type's sentinel
        # (DEFAULT_METRIC_NULL_VALUE_OF_* vs DEFAULT_DIMENSION_*)
        if self.role is FieldRole.METRIC and \
                self.data_type.np_dtype is not None and \
                not self.data_type.is_string_like:
            return self.data_type.np_dtype.type(0).item()
        return self.data_type.default_null

    def to_json(self) -> dict:
        d = {"name": self.name, "dataType": self.data_type.value}
        if not self.single_value:
            d["singleValueField"] = False
        if self.default_null is not None:
            d["defaultNullValue"] = self.default_null
        if self.format:
            d["format"] = self.format
        if self.granularity:
            d["granularity"] = self.granularity
        return d


@dataclasses.dataclass
class Schema:
    name: str
    fields: dict[str, FieldSpec]
    primary_key_columns: list[str] = dataclasses.field(default_factory=list)

    @classmethod
    def build(
        cls,
        name: str,
        dimensions: Iterable[tuple[str, DataType]] = (),
        metrics: Iterable[tuple[str, DataType]] = (),
        datetimes: Iterable[tuple[str, DataType]] = (),
        multi_value_dimensions: Iterable[tuple[str, DataType]] = (),
        primary_key_columns: Iterable[str] = (),
    ) -> "Schema":
        fields: dict[str, FieldSpec] = {}
        for n, t in dimensions:
            fields[n] = FieldSpec(n, t, FieldRole.DIMENSION)
        for n, t in multi_value_dimensions:
            fields[n] = FieldSpec(n, t, FieldRole.DIMENSION, single_value=False)
        for n, t in metrics:
            fields[n] = FieldSpec(n, t, FieldRole.METRIC)
        for n, t in datetimes:
            fields[n] = FieldSpec(n, t, FieldRole.DATE_TIME)
        return cls(name=name, fields=fields, primary_key_columns=list(primary_key_columns))

    # ---- accessors ------------------------------------------------------
    def field(self, name: str) -> FieldSpec:
        try:
            return self.fields[name]
        except KeyError:
            raise KeyError(f"column {name!r} not in schema {self.name!r}") from None

    def column_names(self) -> list[str]:
        return list(self.fields)

    @property
    def dimension_names(self) -> list[str]:
        return [f.name for f in self.fields.values() if f.role is FieldRole.DIMENSION]

    @property
    def metric_names(self) -> list[str]:
        return [f.name for f in self.fields.values() if f.role is FieldRole.METRIC]

    @property
    def datetime_names(self) -> list[str]:
        return [f.name for f in self.fields.values() if f.role is FieldRole.DATE_TIME]

    # ---- JSON (reference-compatible shape) ------------------------------
    def to_json(self) -> dict:
        return {
            "schemaName": self.name,
            "dimensionFieldSpecs": [
                f.to_json() for f in self.fields.values() if f.role is FieldRole.DIMENSION
            ],
            "metricFieldSpecs": [
                f.to_json() for f in self.fields.values() if f.role is FieldRole.METRIC
            ],
            "dateTimeFieldSpecs": [
                f.to_json() for f in self.fields.values() if f.role is FieldRole.DATE_TIME
            ],
            "primaryKeyColumns": self.primary_key_columns,
        }

    @classmethod
    def from_json(cls, obj: dict | str) -> "Schema":
        if isinstance(obj, str):
            obj = json.loads(obj)
        fields: dict[str, FieldSpec] = {}
        for key, role in (
            ("dimensionFieldSpecs", FieldRole.DIMENSION),
            ("metricFieldSpecs", FieldRole.METRIC),
            ("dateTimeFieldSpecs", FieldRole.DATE_TIME),
        ):
            for fs in obj.get(key) or []:
                fields[fs["name"]] = FieldSpec(
                    name=fs["name"],
                    data_type=DataType(fs["dataType"]),
                    role=role,
                    single_value=fs.get("singleValueField", True),
                    default_null=fs.get("defaultNullValue"),
                    format=fs.get("format"),
                    granularity=fs.get("granularity"),
                )
        return cls(
            name=obj.get("schemaName", "schema"),
            fields=fields,
            primary_key_columns=list(obj.get("primaryKeyColumns") or []),
        )

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    @classmethod
    def load(cls, path) -> "Schema":
        with open(path) as f:
            return cls.from_json(json.load(f))
