"""Request tracing: per-query phase spans surfaced in the response.

Equivalent of the reference's trace SPI
(pinot-spi/.../trace/Tracing.java:32 + RequestContext /
DefaultRequestContext and the broker's ``trace`` query option): a tracer
records named phase spans (nesting flattened to dotted names); when the
query sets ``SET trace = true`` the spans ride back in the broker
response as ``traceInfo``, the reference's BrokerResponse trace payload.

The tracer is an EXPLICIT, wire-portable object, not thread state: the
broker mints one per request (stamping a ``trace_id`` that ships in every
scatter request, retries and hedges included), the server threads it
through the async launch/fetch split (``InflightLaunch`` and the
``execute_segments_async`` fetch closure carry it by reference), and the
per-server span lists ride home in DataTable metadata. A thread-local
slot remains for call sites that span the CURRENT request without
plumbing (``span(name)`` with no tracer), but a span recorded against an
explicit tracer lands on that tracer no matter which thread runs it —
the PR-2 launch/fetch thread split and coalesced cohort launches record
correctly. Tracing off costs one attribute read per span.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

_local = threading.local()


class Tracer:
    """One query's span collection. Thread-safe: the launch thread, the
    fetch thread, and a cohort leader may all record concurrently.
    Nesting (dotted names) is tracked PER THREAD so concurrent recorders
    can't mangle each other's phase names."""

    __slots__ = ("trace_id", "spans", "_t0", "_lock", "_tls")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id
        self.spans: list = []  # (name, start_ms_rel, duration_ms)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._tls = threading.local()  # per-thread nesting stack

    # ---- recording -------------------------------------------------------
    def span(self, name: str) -> "Tracer._Span":
        return Tracer._Span(self, name)

    def record(self, name: str, t_start: float, t_end: float) -> None:
        """Append one span from perf_counter endpoints (internal)."""
        with self._lock:
            self.spans.append((
                name,
                round((t_start - self._t0) * 1000, 3),
                round((t_end - t_start) * 1000, 3),
            ))

    def add_ms(self, name: str, duration_ms: float) -> None:
        """Record a phase that JUST ENDED and lasted ``duration_ms`` —
        for waits measured by someone else (the scheduler publishes its
        admission wait before the admitted fn runs; the fn back-fills the
        queue span from it)."""
        now = time.perf_counter()
        self.record(name, now - duration_ms / 1000.0, now)

    def elapsed_ms(self) -> float:
        """Wall time since this tracer was created (the request entry)."""
        return (time.perf_counter() - self._t0) * 1000.0

    class _Span:
        __slots__ = ("tracer", "name", "t0")

        def __init__(self, tracer, name):
            self.tracer, self.name = tracer, name

        def __enter__(self):
            t = self.tracer
            if t is not None:
                stack = getattr(t._tls, "stack", None)
                if stack is None:
                    stack = t._tls.stack = []
                stack.append(self.name)
                self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            t = self.tracer
            if t is not None:
                stack = t._tls.stack
                name = ".".join(stack)
                stack.pop()
                t.record(name, self.t0, time.perf_counter())
            return False

    # ---- export ----------------------------------------------------------
    def to_json(self) -> list:
        with self._lock:
            return [{"phase": n, "startMs": s, "durationMs": d}
                    for n, s, d in self.spans]


def start_trace(trace_id: Optional[str] = None) -> Tracer:
    """Install a tracer for this thread (request entry point). The
    returned object should ALSO be carried explicitly across thread
    seams — the thread-local slot only covers same-thread call sites."""
    t = Tracer(trace_id)
    _local.tracer = t
    return t


def end_trace() -> None:
    _local.tracer = None


def active() -> Optional[Tracer]:
    return getattr(_local, "tracer", None)


def span(name: str, tracer: Optional[Tracer] = None) -> "Tracer._Span":
    """Context manager recording a phase on ``tracer`` (explicit — works
    from any thread) or, when omitted, on the calling thread's active
    tracer; a no-op (shared constant-cost object) when tracing is off."""
    return Tracer._Span(tracer if tracer is not None else active(), name)


def top_level_spans(spans: list) -> list:
    """The top-level phases of a span list-of-dicts — what the waterfall
    and the phase-sum/wall reconciliation sum over. Span names are
    ``role.phase`` at the top and gain a dotted segment per nesting level
    (``server.execute.gather``), so top-level == at most one dot. The
    synthetic ``<role>.total`` span is excluded (it IS the wall)."""
    return [s for s in spans
            if s["phase"].count(".") <= 1
            and not s["phase"].endswith(".total")]
