"""Request tracing: per-query phase spans surfaced in the response.

Equivalent of the reference's trace SPI
(pinot-spi/.../trace/Tracing.java:32 + RequestContext /
DefaultRequestContext and the broker's ``trace`` query option): a
thread-local tracer records named phase spans (nesting flattened to
dotted names); when the query sets ``SET trace = true`` the spans ride
back in the broker response as ``traceInfo``, the reference's
BrokerResponse trace payload. Tracing off costs one thread-local read
per span."""

from __future__ import annotations

import threading
import time
from typing import Optional

_local = threading.local()


class Tracer:
    def __init__(self):
        self.spans: list = []  # (name, start_ms_rel, duration_ms)
        self._t0 = time.perf_counter()
        self._stack: list = []

    class _Span:
        __slots__ = ("tracer", "name", "t0")

        def __init__(self, tracer, name):
            self.tracer, self.name = tracer, name

        def __enter__(self):
            if self.tracer is not None:
                self.tracer._stack.append(self.name)
                self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            if self.tracer is not None:
                t = self.tracer
                name = ".".join(t._stack)
                t._stack.pop()
                t.spans.append((
                    name,
                    round((self.t0 - t._t0) * 1000, 3),
                    round((time.perf_counter() - self.t0) * 1000, 3),
                ))
            return False

    def to_json(self) -> list:
        return [{"phase": n, "startMs": s, "durationMs": d}
                for n, s, d in self.spans]


def start_trace() -> Tracer:
    """Install a tracer for this thread (request entry point)."""
    t = Tracer()
    _local.tracer = t
    return t


def end_trace() -> None:
    _local.tracer = None


def active() -> Optional[Tracer]:
    return getattr(_local, "tracer", None)


def span(name: str) -> "Tracer._Span":
    """Context manager recording a phase on the active tracer; a no-op
    (shared constant-cost object) when tracing is off."""
    return Tracer._Span(active(), name)
