"""Table configuration.

Equivalent surface to the reference's ``TableConfig`` + ``IndexingConfig`` +
``RoutingConfig`` + ``SegmentPartitionConfig`` + ``UpsertConfig``
(pinot-spi/.../config/table/*.java), trimmed to the knobs this engine
actually honors. JSON shape loosely follows the reference so configs are
recognizable.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


class TableType:
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"


@dataclasses.dataclass
class StarTreeIndexConfig:
    """Mirrors StarTreeIndexConfig.java: split order + function-column pairs."""

    dimensions_split_order: list[str]
    function_column_pairs: list[str]  # e.g. ["SUM__revenue", "COUNT__*"]
    max_leaf_records: int = 10_000
    skip_star_node_creation: list[str] = dataclasses.field(default_factory=list)
    # PERCENTILETDIGEST__col pairs: digest compression the cube is built
    # with (queries at a different compression fall back to the scan path)
    tdigest_compression: float = 100.0


@dataclasses.dataclass
class IndexingConfig:
    inverted_index_columns: list[str] = dataclasses.field(default_factory=list)
    range_index_columns: list[str] = dataclasses.field(default_factory=list)
    bloom_filter_columns: list[str] = dataclasses.field(default_factory=list)
    json_index_columns: list[str] = dataclasses.field(default_factory=list)
    text_index_columns: list[str] = dataclasses.field(default_factory=list)
    # FST-index analog: trigram posting index accelerating LIKE/REGEXP_LIKE
    # on dictionary columns (storage/fstindex.py)
    fst_index_columns: list[str] = dataclasses.field(default_factory=list)
    # H3-index analog: grid-cell postings accelerating
    # ST_DISTANCE(col, point) < r on WKT POINT columns (storage/geoindex.py)
    h3_index_columns: list[str] = dataclasses.field(default_factory=list)
    sorted_column: Optional[str] = None
    no_dictionary_columns: list[str] = dataclasses.field(default_factory=list)
    star_tree_configs: list[StarTreeIndexConfig] = dataclasses.field(default_factory=list)
    # bit-pack dict-encoded SV forward indexes (FixedBitSVForwardIndex
    # analog, native codec in pinot_tpu/native): 4-32x smaller on disk,
    # decoded to int32 at load time instead of mmap'd
    enable_bit_packing: bool = False
    # chunk-compress RAW (no-dictionary) SV forward indexes with zlib
    # (io/compression analog: per-chunk LZ4/Snappy/zstd in the reference);
    # decoded by the native codec at load time
    compressed_columns: list[str] = dataclasses.field(default_factory=list)
    # per-column chunk codec override (reference ChunkCompressionType):
    # {"col": "zlib" | "zstd" | "lz4"}; listing a column here implies
    # compression even if it is absent from compressed_columns
    compression_codec: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SegmentPartitionConfig:
    """column -> (function_name, num_partitions); see
    pinot-segment-spi/.../partition/."""

    column_partition_map: dict[str, tuple[str, int]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TransformConfig:
    """One ingest-time derived column (ingestion TransformConfig analog):
    ``transform_function`` is a SQL expression over source record fields
    (which need not be schema columns), evaluated by the engine's own
    function registry instead of Groovy."""

    column_name: str
    transform_function: str


@dataclasses.dataclass
class IngestionConfig:
    """Ingestion-time record shaping (spi config/table/ingestion analog):
    transforms run first, then rows where ``filter_function`` evaluates
    true are DROPPED (FilterConfig semantics)."""

    transform_configs: list[TransformConfig] = dataclasses.field(
        default_factory=list)
    filter_function: Optional[str] = None


@dataclasses.dataclass
class QuotaConfig:
    """Per-table query quota (spi/config/table/QuotaConfig analog):
    max queries per second enforced broker-side."""

    max_queries_per_second: Optional[float] = None


@dataclasses.dataclass
class UpsertConfig:
    mode: str = "NONE"  # NONE | FULL | PARTIAL
    comparison_column: Optional[str] = None
    partial_upsert_strategies: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class StreamConfig:
    """Realtime stream settings (pinot-spi/.../stream/StreamConfig.java)."""

    stream_type: str = "memory"  # plugin key: memory | file | kafka
    topic: str = ""
    decoder: str = "json"
    segment_flush_threshold_rows: int = 100_000
    segment_flush_threshold_seconds: int = 3600
    properties: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ChunkletConfig:
    """Consuming-segment chunklet promotion (realtime/chunklet.py): the
    frozen prefix of a consuming segment is sealed into immutable
    device-eligible blocks while only the unfrozen row tail stays on the
    host scan path.

    ``device_min_rows`` is the freshness/latency crossover knob: below it
    the whole consuming segment runs on the host (promotion overhead would
    dominate); above it, sealed chunklets query at device speed and only
    the tail pays host-scan latency. Lower it for query latency on large
    consuming segments, raise it (or disable) for pure-ingest tables."""

    enabled: bool = True
    rows_per_chunklet: int = 65_536
    # frozen rows required before chunklets route to the device path
    device_min_rows: int = 262_144


@dataclasses.dataclass
class TableConfig:
    table_name: str  # raw name, no type suffix
    table_type: str = TableType.OFFLINE
    schema_name: Optional[str] = None
    replication: int = 1
    # dimension table (isDimTable analog): small lookup table replicated to
    # every server so LOOKUP() resolves locally during fact-table execution
    is_dim_table: bool = False
    time_column: Optional[str] = None
    retention_days: Optional[int] = None
    indexing: IndexingConfig = dataclasses.field(default_factory=IndexingConfig)
    partition: SegmentPartitionConfig = dataclasses.field(default_factory=SegmentPartitionConfig)
    upsert: UpsertConfig = dataclasses.field(default_factory=UpsertConfig)
    quota: QuotaConfig = dataclasses.field(default_factory=QuotaConfig)
    ingestion: IngestionConfig = dataclasses.field(
        default_factory=IngestionConfig)
    stream: Optional[StreamConfig] = None
    chunklets: ChunkletConfig = dataclasses.field(
        default_factory=ChunkletConfig)
    # Minion task configs keyed by task type (TableTaskConfig analog), e.g.
    # {"MergeRollupTask": {"max_docs_per_segment": 1_000_000}}
    task_configs: dict = dataclasses.field(default_factory=dict)
    # Tier storage (TierConfig analog): ordered oldest-tier-last; segments
    # whose end-time age exceeds segment_age_ms relocate to servers carrying
    # server_tag, e.g. [{"name": "cold", "segment_age_ms": 86400000,
    # "server_tag": "cold_tier"}]
    tiers: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        # TableConfigUtils analog: star-trees pre-aggregate over all rows at
        # seal time, which an upsert validDocIds mask would silently falsify.
        if self.upsert.mode != "NONE" and self.indexing.star_tree_configs:
            raise ValueError(
                "star_tree_configs are not supported on upsert tables "
                "(pre-aggregated partials ignore validDocIds)"
            )
        mqps = self.quota.max_queries_per_second
        if mqps is not None and mqps <= 0:
            raise ValueError(
                "quota.max_queries_per_second must be positive "
                "(omit it for unlimited)")

    @property
    def table_name_with_type(self) -> str:
        return f"{self.table_name}_{self.table_type}"

    # ---- JSON ----------------------------------------------------------
    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_json(cls, obj: dict | str) -> "TableConfig":
        if isinstance(obj, str):
            obj = json.loads(obj)
        obj = dict(obj)
        if "indexing" in obj and isinstance(obj["indexing"], dict):
            idx = dict(obj["indexing"])
            idx["star_tree_configs"] = [
                StarTreeIndexConfig(**c) for c in idx.get("star_tree_configs", [])
            ]
            obj["indexing"] = IndexingConfig(**idx)
        if "partition" in obj and isinstance(obj["partition"], dict):
            p = dict(obj["partition"])
            p["column_partition_map"] = {
                k: tuple(v) for k, v in p.get("column_partition_map", {}).items()
            }
            obj["partition"] = SegmentPartitionConfig(**p)
        if "upsert" in obj and isinstance(obj["upsert"], dict):
            obj["upsert"] = UpsertConfig(**obj["upsert"])
        if "quota" in obj and isinstance(obj["quota"], dict):
            obj["quota"] = QuotaConfig(**obj["quota"])
        if "ingestion" in obj and isinstance(obj["ingestion"], dict):
            ing = dict(obj["ingestion"])
            ing["transform_configs"] = [
                TransformConfig(**t) for t in ing.get("transform_configs", [])
            ]
            obj["ingestion"] = IngestionConfig(**ing)
        if obj.get("stream") is not None and isinstance(obj["stream"], dict):
            obj["stream"] = StreamConfig(**obj["stream"])
        if "chunklets" in obj and isinstance(obj["chunklets"], dict):
            obj["chunklets"] = ChunkletConfig(**obj["chunklets"])
        return cls(**obj)
