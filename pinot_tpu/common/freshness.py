"""Per-table data-freshness epochs — the broker result cache's staleness
contract (ISSUE 10).

A process-local monotonic counter per LOGICAL table (type suffix
stripped): every mutation that can change a query's answer without
changing the segment SET bumps it — columnar batch/row publishes into a
consuming segment, chunklet promotion, upsert invalidations, and seal
(the same seams PR 9's ``invalidate_cached_partials`` rides). Segment
adds/removes are covered separately by the registry's routing
generation, so (routing generation, epoch view) together bound every
way a cached broker result can go stale.

Servers report their epoch in every DataTable partial
(``ExecutionStats.table_epoch``) and in the sync-loop heartbeat
(``InstanceInfo.table_epochs``); the broker folds both into a per-table
{instance: epoch} view and refuses to serve any cached entry whose
recorded view differs (broker/result_cache.py).

Deliberately dependency-free: ingest worker processes bump epochs
without importing jax or the engine.

Epochs are offset by the process start time in nanoseconds, so a
restarted server can never report a value a broker has already seen
from the previous incarnation (its counter restarts, but its base is
later than any epoch the old process could have reached — one bump per
nanosecond of uptime is unattainable). A stale-by-restart cached entry
therefore invalidates on the restarted process's first mutation instead
of ratcheting forever behind the old, higher count.
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_epochs: dict = {}
_BASE = time.time_ns()


def base_table(table) -> str:
    """Physical registry key → logical table name (``sales_OFFLINE`` and
    ``sales_REALTIME`` share one epoch, like they share one quota)."""
    name = str(table or "")
    for suffix in ("_OFFLINE", "_REALTIME"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def bump(table) -> int:
    """Data under ``table`` changed in place; returns the new epoch."""
    key = base_table(table)
    with _lock:
        _epochs[key] = _epochs.get(key, _BASE) + 1
        return _epochs[key]


def epoch(table) -> int:
    """Current epoch (0 = never mutated in this process)."""
    with _lock:
        return _epochs.get(base_table(table), 0)


def snapshot() -> dict:
    """{logical table: epoch} — the heartbeat payload."""
    with _lock:
        return dict(_epochs)


def reset() -> None:
    """Test hook: forget every epoch (fresh-process semantics)."""
    with _lock:
        _epochs.clear()
