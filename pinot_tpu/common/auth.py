"""Per-principal access control: HTTP Basic credentials + table ACLs.

Equivalent of the reference's ``BasicAuthAccessControlFactory``
(pinot-broker/.../broker/broker/BasicAuthAccessControlFactory.java:44 and
the controller twin): principals configure as

    principals=admin,reader
    principals.admin.password=verysecret
    principals.reader.password=secret
    principals.reader.tables=events,metrics

A principal WITHOUT a ``tables=`` key (or with ``tables=*``) may access
every table; otherwise access is limited to the listed tables. Table names
compare case-insensitively on the RAW name — type suffixes (``_OFFLINE`` /
``_REALTIME``) are stripped first, like the reference's
``BasicAuthPrincipal.hasTable``.

Enforced at both public surfaces: the broker query API
(broker/http_api.py — a denied table answers 403 before any execution)
and the controller admin REST (controller/http_api.py — table metadata is
filtered/denied per principal).
"""

from __future__ import annotations

import base64
import hmac
from typing import Mapping, Optional


def _base_table(table: str) -> str:
    t = table.strip()
    for suffix in ("_OFFLINE", "_REALTIME"):
        if t.upper().endswith(suffix):
            t = t[: -len(suffix)]
    return t.lower()


class BasicAuthAccessControl:
    """users: {name: password}; table_acls: {name: iterable of table names}
    — a principal absent from ``table_acls`` (or mapped to ``None``/"*")
    has access to all tables."""

    def __init__(self, users: Mapping[str, str],
                 table_acls: Optional[Mapping] = None):
        self._users = dict(users)
        self._acls: dict = {}
        for user, tables in (table_acls or {}).items():
            if tables is None:
                continue
            if isinstance(tables, str):
                tables = [t for t in tables.split(",") if t.strip()]
            tables = [t.strip() for t in tables]
            if "*" in tables:
                continue
            self._acls[user] = {_base_table(t) for t in tables}

    @classmethod
    def from_config(cls, conf) -> Optional["BasicAuthAccessControl"]:
        """Build from a Configuration holding ``principals*`` keys
        (``None`` when no principals are configured = auth disabled)."""
        names = [n.strip() for n in str(conf.get("principals", "")).split(",")
                 if n.strip()]
        if not names:
            return None
        users, acls = {}, {}
        for name in names:
            users[name] = str(conf.get(f"principals.{name}.password", ""))
            tables = conf.get(f"principals.{name}.tables")
            if tables is not None:
                acls[name] = tables
        return cls(users, acls)

    # ---- authentication --------------------------------------------------
    def authenticate(self, authorization_header: Optional[str]) -> Optional[str]:
        """Authorization header → principal name, or None when rejected.
        Compares against a dummy for unknown users so timing doesn't
        enumerate usernames."""
        header = authorization_header or ""
        if not header.startswith("Basic "):
            return None
        try:
            raw = base64.b64decode(header[6:]).decode("utf-8")
            user, _, pw = raw.partition(":")
        except Exception:  # noqa: BLE001 — malformed header
            return None
        expected = self._users.get(user)
        known = expected is not None
        ref = (expected if known else "\x00dummy").encode("utf-8")
        ok = hmac.compare_digest(pw.encode("utf-8"), ref) and known
        return user if ok else None

    # ---- authorization ---------------------------------------------------
    @property
    def restricts_tables(self) -> bool:
        """False when no principal has a table list — callers can skip
        table resolution entirely (pure-auth deployments)."""
        return bool(self._acls)

    def is_restricted(self, user: str) -> bool:
        """True when the principal has a table grant list (cross-table
        surfaces like /metrics must deny these principals)."""
        return user in self._acls

    def allows(self, user: str, table: str) -> bool:
        allowed = self._acls.get(user)
        if allowed is None:
            return True  # unrestricted principal
        return _base_table(table) in allowed

    def allowed_tables(self, user: str, tables) -> list:
        """Filter a table listing down to what the principal may see."""
        return [t for t in tables if self.allows(user, t)]
