"""Cluster registry: the Helix/ZooKeeper replacement.

The reference coordinates everything through Helix IdealState/ExternalView in
ZK (SURVEY.md §1: PinotHelixResourceManager writes IdealState, brokers watch
ExternalView, servers run the OFFLINE/CONSUMING/ONLINE state model). The TPU
build replaces that with a small registry of durable maps:

- instances (role, endpoint, heartbeat)       — LiveInstance analog
- tables (config + schema JSON)               — PROPERTYSTORE configs
- segments (metadata + deep-store URI + state)— SegmentZKMetadata
- assignment {table: {segment: [instanceId]}} — IdealState
- external view {table: {segment: [instanceId]}} — what servers actually
  serve (brokers route on this, exactly like the reference's brokers watch
  ExternalView, BrokerRoutingManager.java:87)
- partition assignment for realtime tables    — LLC partition → server

Two implementations share the interface: in-memory (single process, tests)
and file-backed JSON-with-lock (multi-process on a shared filesystem). A
proper multi-host deployment would swap in an etcd-backed impl behind the
same surface — state transitions are polled by servers (sync loop), not
pushed, which replaces Helix messages with level-triggered reconciliation.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fcntl
import json
import os
import threading
import time
from typing import Optional

from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig


class Role:
    SERVER = "SERVER"
    BROKER = "BROKER"
    CONTROLLER = "CONTROLLER"
    MINION = "MINION"


class UnresolvableSegmentLocation(ValueError):
    """``SegmentRecord.location`` names a URI scheme no registered
    PinotFS plugin resolves (ISSUE 12 satellite): raised at
    ``add_segment`` time so a bad deep-store URI fails at registration —
    not at the first cold-tier download, hours later on a different
    host."""


def _validate_location(location: str) -> None:
    """Scheme-resolvability check against the PinotFS plugin registry.
    Bare paths and ``file://`` always resolve (LocalFS is built in);
    anything else must have a registered ``fs`` factory. The registry
    lookup is a lock + dict probe after the one-time plugin bootstrap,
    so this is cheap enough for the ingest-path add_segment callers."""
    if not location:
        return  # consuming segments register location-less
    from urllib.parse import urlparse

    scheme = urlparse(location).scheme
    if scheme in ("", "file") or "://" not in location:
        # absolute/relative paths (a lone drive-letter-style colon parses
        # as a scheme but is still a path) and the built-in file scheme
        return
    from pinot_tpu.common.plugins import plugin_registry

    try:
        plugin_registry.load("fs", scheme)
    except KeyError as e:
        raise UnresolvableSegmentLocation(
            f"segment location {location!r}: no PinotFS plugin registered "
            f"for scheme {scheme!r} ({e})") from None


class SegmentState:
    ONLINE = "ONLINE"
    CONSUMING = "CONSUMING"
    OFFLINE = "OFFLINE"


# heartbeat-staleness rule (ISSUE 14), single-sourced: an instance that
# missed 3 heartbeat intervals (default 2 s cadence) is presumed
# crashed/wedged. The broker's LoadTracker expires its load sample, the
# controller autoscaler counts it as missing capacity, and the
# /cluster/load endpoint renders it STALE — all off THIS constant.
HB_STALE_S = 6.0


@dataclasses.dataclass
class InstanceInfo:
    instance_id: str
    role: str
    host: str = "127.0.0.1"
    grpc_port: int = 0
    last_heartbeat_ms: int = 0
    # instance tags (Helix tag analog): tier placement targets one tag
    tags: list = dataclasses.field(default_factory=list)
    # load signal published with the heartbeat (scheduler pressure():
    # admitted + queued queries) — the broker's load-aware routing reads
    # it when no fresher piggybacked response signal exists
    pressure: float = 0.0
    # {logical table: freshness epoch} (common/freshness.py) — the broker
    # result cache's staleness view when queries aren't flowing
    table_epochs: dict = dataclasses.field(default_factory=dict)
    # per-segment access-temperature snapshot (ISSUE 11,
    # server/heat.py SegmentHeatTracker.snapshot(): {table: {segment:
    # {rate, bytesRate, accesses, bytes, lastAccessTs}}}, hottest-N per
    # table) — the controller aggregates it behind /tables/{t}/heat,
    # the input ROADMAP 3's tier promotion/demotion will consume
    heat: dict = dataclasses.field(default_factory=dict)
    # per-segment tier map (ISSUE 12, server/tiering.py TierManager
    # .snapshot(): {table: {segment: "hot"|"warm"|"cold"}}) — the
    # controller's tier-aware replica-group assignment reads it
    # (controller.py aggregate_tiers / rebalance_tiered)
    tiers: dict = dataclasses.field(default_factory=dict)
    # role-specific heartbeat-piggybacked counters (ISSUE 18): brokers
    # publish {url, draining, qps, cacheHitRate, tenantSpend, ...} here so
    # clients discover query URLs, clusterstat --brokers renders fleet
    # health, and admission gossip shares one logical per-tenant budget
    # across the fleet — all without a second channel
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.grpc_port}"


@dataclasses.dataclass
class SegmentRecord:
    name: str
    table: str
    n_docs: int = 0
    location: str = ""          # deep-store URI (directory path for localfs)
    state: str = SegmentState.ONLINE
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    partition_column: Optional[str] = None
    partition_ids: Optional[list] = None
    partition_function: Optional[str] = None
    num_partitions: Optional[int] = None
    crc: Optional[str] = None
    push_time_ms: int = 0
    # per-column {"min": v, "max": v} from segment metadata (JSON-plain
    # values) — broker-side value pruning (broker/segment_pruner.py)
    column_stats: Optional[dict] = None


def _to_json(state: dict) -> dict:
    return {
        "instances": {k: dataclasses.asdict(v) for k, v in state["instances"].items()},
        "tables": state["tables"],
        "schemas": state["schemas"],
        "segments": {
            t: {n: dataclasses.asdict(r) for n, r in segs.items()}
            for t, segs in state["segments"].items()
        },
        "assignment": state["assignment"],
        "external_view": state["external_view"],
        "partition_assignment": state["partition_assignment"],
        "segment_completion": state.get("segment_completion", {}),
        "tasks": state.get("tasks", {}),
        "task_metadata": state.get("task_metadata", {}),
        "segment_lineage": state.get("segment_lineage", {}),
        "replica_groups": state.get("replica_groups", {}),
        "autoscaler": state.get("autoscaler", {}),
    }


def _from_json(d: dict) -> dict:
    return {
        "instances": {k: InstanceInfo(**v) for k, v in d.get("instances", {}).items()},
        "tables": d.get("tables", {}),
        "schemas": d.get("schemas", {}),
        "segments": {
            t: {n: SegmentRecord(**r) for n, r in segs.items()}
            for t, segs in d.get("segments", {}).items()
        },
        "assignment": d.get("assignment", {}),
        "external_view": d.get("external_view", {}),
        "partition_assignment": d.get("partition_assignment", {}),
        "segment_completion": d.get("segment_completion", {}),
        "tasks": d.get("tasks", {}),
        "task_metadata": d.get("task_metadata", {}),
        "segment_lineage": d.get("segment_lineage", {}),
        "replica_groups": d.get("replica_groups", {}),
        "autoscaler": d.get("autoscaler", {}),
    }


class ClusterRegistry:
    """In-memory registry (single-process clusters and tests)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._state = {
            "instances": {},
            "tables": {},
            "schemas": {},
            "segments": {},
            "assignment": {},
            "external_view": {},
            "partition_assignment": {},
            "replica_groups": {},
            "leases": {},
        }
        # bumped by every mutation that can change what a query routes to
        # or reads (segments, assignment, external view, lineage, replica
        # groups — NOT heartbeats): the broker's routing snapshot cache
        # and result cache key on it (ISSUE 10)
        self._routing_gen = 0
        self._write_ver = 0  # any-write token (state_version)

    def _note_routing_change(self) -> None:
        with self._lock:
            self._routing_gen += 1

    def routing_generation(self) -> int:
        """Cheap monotonic token: while it holds still, a broker may
        reuse its cached routing snapshot and serve fresh-epoch cached
        results (FileRegistry overrides this with per-section version
        counters so the token is cross-process)."""
        with self._lock:
            return self._routing_gen

    def state_version(self) -> int:
        """Change token over the whole state: pollers skip work while it
        holds still. The in-memory form bumps on EVERY write tx (an
        over-approximation — heartbeats count — but in-process polls are
        nanoseconds; FileRegistry narrows it to real section changes)."""
        with self._lock:
            return self._write_ver

    def sections_version(self, sections) -> int:
        """Section-subset change token (FileRegistry refines this to the
        named sections' version counters; in-memory, any write bumps)."""
        with self._lock:
            return self._write_ver

    # ---- tx plumbing (overridden by FileRegistry) ------------------------
    def _read(self) -> dict:
        return self._state

    def _write(self, state: dict) -> None:
        self._state = state

    def _tx(self, fn, write: bool = True):
        with self._lock:
            state = self._read()
            out = fn(state)
            if write:
                self._write(state)
                self._write_ver += 1
            return out

    def _tx_read(self, fn):
        return self._tx(fn, write=False)

    # ---- instances -------------------------------------------------------
    def register_instance(self, info: InstanceInfo) -> None:
        info.last_heartbeat_ms = int(time.time() * 1000)
        self._tx(lambda s: s["instances"].__setitem__(info.instance_id, info))

    def heartbeat(self, instance_id: str, pressure: float = None,
                  table_epochs: dict = None, heat: dict = None,
                  tiers: dict = None, stats: dict = None) -> None:
        """Liveness tick, optionally carrying the instance's current load
        (scheduler pressure), per-table freshness epochs, the per-segment
        heat snapshot (ISSUE 11), the per-segment tier map (ISSUE 12),
        and role-specific counters (ISSUE 18 broker fleet stats) — the
        passive half of the broker's load/staleness view (the active half
        rides piggybacked in every DataTable response) and the
        controller's temperature/tier aggregation input."""

        def fn(s):
            info = s["instances"].get(instance_id)
            if info is not None:
                info.last_heartbeat_ms = int(time.time() * 1000)
                if pressure is not None:
                    info.pressure = float(pressure)
                if table_epochs is not None:
                    info.table_epochs = dict(table_epochs)
                if heat is not None:
                    info.heat = dict(heat)
                if tiers is not None:
                    info.tiers = dict(tiers)
                if stats is not None:
                    info.stats = dict(stats)

        self._tx(fn)

    def expire_heartbeat(self, instance_id: str) -> None:
        """Drop an instance from every liveness window immediately (clean
        quorum exit: peers re-quota without waiting out the TTL)."""

        def fn(s):
            if instance_id in s["instances"]:
                s["instances"][instance_id].last_heartbeat_ms = 0

        self._tx(fn)

    def instances(self, role: Optional[str] = None, live_ttl_ms: Optional[int] = None):
        def fn(s):
            out = list(s["instances"].values())
            if role is not None:
                out = [i for i in out if i.role == role]
            if live_ttl_ms is not None:
                now = int(time.time() * 1000)
                out = [i for i in out if now - i.last_heartbeat_ms <= live_ttl_ms]
            return out

        return self._tx_read(fn)

    # ---- autoscaler state (ISSUE 14) -------------------------------------
    def set_autoscaler_state(self, state: dict) -> None:
        """Publish the controller autoscaler's current view (phase,
        pressure, watermarks, last actions) so operators can read it from
        ANY process — ``tools/clusterstat.py --load`` renders it. One
        shared doc: a single controller leads the autoscale duty."""
        self._tx(lambda s: (s.setdefault("autoscaler", {}).clear(),
                            s["autoscaler"].update(dict(state))))

    def autoscaler_state(self) -> dict:
        return self._tx_read(
            lambda s: dict(s.setdefault("autoscaler", {})))

    # ---- leases (controller HA: Helix leader-election role) --------------
    def try_acquire_lease(self, name: str, holder: str, ttl_ms: int) -> dict:
        """Atomically acquire or renew a named lease: granted when free,
        expired, or already held by ``holder``. Returns the current lease
        ``{"holder", "expires_ms"}`` either way — callers check
        ``lease["holder"] == holder``. This is the whole election
        protocol: the registry tx IS the arbiter (the role ZK ephemeral
        nodes play for Helix leader election,
        pinot-controller/.../LeadControllerManager.java:1)."""
        now = int(time.time() * 1000)

        def fn(s):
            leases = s.setdefault("leases", {})
            cur = leases.get(name)
            if cur is None or cur["holder"] == holder \
                    or now > cur["expires_ms"]:
                leases[name] = {"holder": holder, "expires_ms": now + ttl_ms}
            return dict(leases[name])

        return self._tx(fn)

    def lease_tick(self, holder: str, wanted: list, max_held: int,
                   ttl_ms: int, heartbeat: bool = True) -> set:
        """ONE transaction per HA tick (N separate renewal txs would churn
        the flock + section version once per lease): walk ``wanted`` in
        order, renewing/acquiring until ``max_held`` leases are held, and
        RELEASE any of ``wanted`` held beyond that — the fair-share yield
        that lets live controllers actually split the lead partitions.
        Callers list currently-held names first so renewal is stable.
        Returns the names now held."""
        now = int(time.time() * 1000)

        def fn(s):
            leases = s.setdefault("leases", {})
            held = set()
            for name in wanted:
                cur = leases.get(name)
                mine = cur is not None and cur["holder"] == holder
                if len(held) >= max_held:
                    if mine:
                        leases.pop(name)  # yield the excess
                    continue
                if cur is None or mine or now > cur["expires_ms"]:
                    leases[name] = {"holder": holder,
                                    "expires_ms": now + ttl_ms}
                    held.add(name)
            if heartbeat and holder in s["instances"]:
                s["instances"][holder].last_heartbeat_ms = now
            return held

        return self._tx(fn)

    def release_lease(self, name: str, holder: str) -> None:
        """Voluntary release (clean shutdown hands leadership over without
        waiting out the TTL)."""

        def fn(s):
            cur = s.setdefault("leases", {}).get(name)
            if cur is not None and cur["holder"] == holder:
                s["leases"].pop(name)

        self._tx(fn)

    def lease_holder(self, name: str) -> Optional[str]:
        now = int(time.time() * 1000)

        def fn(s):
            cur = s.setdefault("leases", {}).get(name)
            if cur is None or now > cur["expires_ms"]:
                return None
            return cur["holder"]

        return self._tx_read(fn)

    def drop_instance(self, instance_id: str) -> None:
        def fn(s):
            s["instances"].pop(instance_id, None)
            for table, ev in s["external_view"].items():
                for seg in list(ev):
                    if instance_id in ev[seg]:
                        ev[seg] = [i for i in ev[seg] if i != instance_id]

        self._tx(fn)
        self._note_routing_change()

    # ---- tables ----------------------------------------------------------
    def add_table(self, config: TableConfig, schema: Schema,
                  key: Optional[str] = None) -> None:
        key = key or config.table_name

        def fn(s):
            s["tables"][key] = config.to_json()
            s["schemas"][key] = schema.to_json()
            s["segments"].setdefault(key, {})
            s["assignment"].setdefault(key, {})

        self._tx(fn)
        self._note_routing_change()

    def drop_table(self, table: str) -> None:
        def fn(s):
            for key in ("tables", "schemas", "segments", "assignment",
                        "external_view", "partition_assignment",
                        "replica_groups"):
                s[key].pop(table, None)

        self._tx(fn)
        self._note_routing_change()

    def update_schema(self, table: str, schema: Schema) -> None:
        """Schema evolution: replace a registered table's schema (the
        reference's Schema REST update; validation happens at the
        controller)."""

        def fn(s):
            if table not in s["schemas"]:
                raise KeyError(f"table {table!r} not found")
            s["schemas"][table] = schema.to_json()

        self._tx(fn)

    def table_config(self, table: str) -> Optional[TableConfig]:
        d = self._tx_read(lambda s: s["tables"].get(table))
        return None if d is None else TableConfig.from_json(d)

    def set_table_config(self, table: str, config: TableConfig) -> None:
        """Hot config update (controller REST table-config PUT analog);
        servers pick it up level-triggered on their next sync."""

        def fn(s):
            if table not in s["tables"]:
                raise KeyError(f"table {table!r} not found")
            s["tables"][table] = config.to_json()

        self._tx(fn)
        # config rides the tables section: broker memos keyed on the
        # routing generation (quota rates, table-name sets) must refresh
        self._note_routing_change()

    def table_schema(self, table: str) -> Optional[Schema]:
        d = self._tx_read(lambda s: s["schemas"].get(table))
        return None if d is None else Schema.from_json(d)

    def tables(self) -> list:
        return self._tx_read(lambda s: list(s["tables"]))

    # ---- segments + assignment ------------------------------------------
    def add_segment(self, record: SegmentRecord, instance_ids: list,
                    merge_instances: bool = False) -> None:
        """Register a segment + its replica assignment.

        ``merge_instances=True`` unions ``instance_ids`` into the existing
        assignment instead of replacing it — the multi-replica realtime
        commit path needs this: EVERY replica of a stream partition
        publishes the same committed segment under its own instance id
        (winner via finish, losers via adopt), and replace semantics would
        make the last publisher the only replica, silently dropping
        replication to 1 (the reference instead has the controller write
        the full ideal-state replica set once at commit)."""
        # deep-store URI must resolve NOW (typed error), not at the first
        # cold-tier download (ISSUE 12 satellite)
        _validate_location(record.location)
        record.push_time_ms = record.push_time_ms or int(time.time() * 1000)

        def fn(s):
            s["segments"].setdefault(record.table, {})[record.name] = record
            assign = s["assignment"].setdefault(record.table, {})
            if merge_instances:
                cur = assign.setdefault(record.name, [])
                for i in instance_ids:
                    if i not in cur:
                        cur.append(i)
            else:
                assign[record.name] = list(instance_ids)

        self._tx(fn)
        self._note_routing_change()

    def remove_segment(self, table: str, name: str) -> None:
        def fn(s):
            s["segments"].get(table, {}).pop(name, None)
            s["assignment"].get(table, {}).pop(name, None)

        self._tx(fn)
        self._note_routing_change()

    def segments(self, table: str) -> dict:
        return self._tx_read(lambda s: dict(s["segments"].get(table, {})))

    def assignment(self, table: str) -> dict:
        return self._tx_read(lambda s: {k: list(v) for k, v in s["assignment"].get(table, {}).items()})

    def set_assignment(self, table: str, mapping: dict) -> None:
        self._tx(lambda s: s["assignment"].__setitem__(
            table, {k: list(v) for k, v in mapping.items()}
        ))
        self._note_routing_change()

    # ---- replica groups (ReplicaGroupSegmentAssignment analog) -----------
    def set_replica_groups(self, table: str, groups: dict) -> None:
        """{group name: [instance ids]} — each group holds ONE complete
        replica of the table; the broker routes a whole query to one
        group's instances (InstanceSelector over replica-group instance
        partitions in the reference)."""
        self._tx(lambda s: s["replica_groups"].__setitem__(
            table, {str(k): list(v) for k, v in groups.items()}
        ))
        self._note_routing_change()

    def replica_groups(self, table: str) -> dict:
        return self._tx_read(
            lambda s: {k: list(v) for k, v in
                       s["replica_groups"].get(table, {}).items()}
        )

    def assigned_segments(self, instance_id: str) -> dict:
        """{table: [segment names]} hosted by this instance (server sync)."""

        def fn(s):
            out: dict = {}
            for table, mapping in s["assignment"].items():
                names = [seg for seg, inst in mapping.items() if instance_id in inst]
                if names:
                    out[table] = names
            return out

        return self._tx_read(fn)

    # ---- external view (server-reported serving state) -------------------
    def update_external_view(self, instance_id: str, serving: dict) -> None:
        """``serving``: {table: [segment names]} this instance can answer
        for right now (loaded immutable + live consuming segments)."""

        def fn(s):
            # change-tracked: the steady-state sync tick (same serving set
            # every 200ms) must not churn the routing generation and blow
            # the broker's routing/result caches
            changed = False
            ev_all = s["external_view"]
            for table, ev in ev_all.items():
                keep = set(serving.get(table, ()))
                for seg in list(ev):
                    if instance_id in ev[seg] and seg not in keep:
                        ev[seg] = [i for i in ev[seg] if i != instance_id]
                        changed = True
            for table, names in serving.items():
                ev = ev_all.setdefault(table, {})
                for name in names:
                    lst = ev.setdefault(name, [])
                    if instance_id not in lst:
                        lst.append(instance_id)
                        changed = True
            return changed

        if self._tx(fn):
            self._note_routing_change()

    def scrub_instances(self, instance_ids) -> None:
        """Remove hard-dead instances from every external-view entry in one
        transaction — a killed server can't deregister itself, and stale EV
        entries keep brokers routing at it (the reference gets this from
        Helix dropping the dead participant's ephemeral node). The
        ASSIGNMENT (ideal state) is deliberately untouched: stripping it
        would make a transiently-stalled server delete its local copies on
        return; assignment ghosts are cleaned by the controller's
        rebalance-on-dead repair, which restores replication on live
        servers in the same move."""
        ids = set(instance_ids)
        if not ids:
            return

        def fn(s):
            for table, ev in s["external_view"].items():
                for seg, insts in list(ev.items()):
                    if ids & set(insts):
                        ev[seg] = [i for i in insts if i not in ids]

        self._tx(fn)
        self._note_routing_change()

    def external_view(self, table: str) -> dict:
        return self._tx_read(
            lambda s: {k: list(v) for k, v in s["external_view"].get(table, {}).items() if v}
        )

    # ---- realtime partition assignment ----------------------------------
    def set_partition_assignment(self, table: str, mapping: dict) -> None:
        """{partition(str): [instance_ids]} — every listed replica consumes
        the partition (multi-replica LLC consumption)."""

        def norm(v):
            return [v] if isinstance(v, str) else list(v)

        self._tx(lambda s: s["partition_assignment"].__setitem__(
            table, {str(k): norm(v) for k, v in mapping.items()}
        ))

    def partition_assignment(self, table: str) -> dict:
        out = self._tx_read(
            lambda s: dict(s["partition_assignment"].get(table, {}))
        )
        return {k: ([v] if isinstance(v, str) else list(v)) for k, v in out.items()}

    # ---- segment completion FSM (SegmentCompletionManager analog) --------
    # state: {table: {partition: {sequence: entry}}} where entry =
    # {committer, state: COMMITTING|DONE, segment, location, offset, ts_ms}
    # The first replica to reach its flush threshold CAS-claims the commit;
    # losers HOLD until the entry goes DONE, then adopt the committed
    # segment. A stale COMMITTING entry (committer died mid-build) can be
    # taken over.

    def try_claim_commit(self, table: str, partition: int, sequence: int,
                         instance_id: str, segment_name: str) -> dict:
        """CAS: claim the commit for (partition, sequence). Returns the
        current entry — caller won iff entry['committer'] == instance_id
        and entry['state'] == 'COMMITTING'."""

        def fn(s):
            part = s.setdefault("segment_completion", {}) \
                .setdefault(table, {}).setdefault(str(partition), {})
            entry = part.get(str(sequence))
            if entry is None:
                entry = {
                    "committer": instance_id, "state": "COMMITTING",
                    "segment": segment_name, "location": None, "offset": None,
                    "ts_ms": int(time.time() * 1000),
                }
                part[str(sequence)] = entry
            return dict(entry)

        return self._tx(fn)

    def finish_commit(self, table: str, partition: int, sequence: int,
                      instance_id: str, segment_name: str, location: str,
                      end_offset: str) -> bool:
        """Committer publishes the built segment; False if it lost the claim
        (a takeover happened while it was building). ``segment_name`` is
        re-recorded: after a takeover the new committer's segment replaces
        the dead claimer's."""

        def fn(s):
            part = s.get("segment_completion", {}).get(table, {}) \
                .get(str(partition), {})
            entry = part.get(str(sequence))
            if entry is None or entry["committer"] != instance_id:
                return False
            entry.update(state="DONE", segment=segment_name, location=location,
                         offset=end_offset, ts_ms=int(time.time() * 1000))
            return True

        return self._tx(fn)

    def commit_entry(self, table: str, partition: int, sequence: int):
        def fn(s):
            e = s.get("segment_completion", {}).get(table, {}) \
                .get(str(partition), {}).get(str(sequence))
            return None if e is None else dict(e)

        return self._tx_read(fn)

    def takeover_commit(self, table: str, partition: int, sequence: int,
                        instance_id: str, stale_ms: int) -> dict:
        """If the entry is COMMITTING and untouched for ``stale_ms``, replace
        the (presumed dead) committer. Returns the current entry."""

        def fn(s):
            part = s.setdefault("segment_completion", {}) \
                .setdefault(table, {}).setdefault(str(partition), {})
            entry = part.get(str(sequence))
            now = int(time.time() * 1000)
            if entry is None:
                entry = {
                    "committer": instance_id, "state": "COMMITTING",
                    "segment": None, "location": None, "offset": None,
                    "ts_ms": now,
                }
                part[str(sequence)] = entry
            elif entry["state"] == "COMMITTING" and now - entry["ts_ms"] >= stale_ms:
                entry.update(committer=instance_id, ts_ms=now)
            return dict(entry)

        return self._tx(fn)


    # ---- minion task queue (PinotHelixTaskResourceManager analog) --------
    # tasks: {task_id: {id, type, table, config, state, worker, ts_ms, output}}
    # States: PENDING -> RUNNING -> DONE | FAILED. Minions claim via CAS
    # (the registry tx is the arbiter, replacing Helix's task framework).

    class TaskState:
        PENDING = "PENDING"
        RUNNING = "RUNNING"
        DONE = "DONE"
        FAILED = "FAILED"

    def submit_task(self, task_type: str, table: str, config: dict) -> str:
        def fn(s):
            tasks = s.setdefault("tasks", {})
            task_id = f"task_{task_type}_{len(tasks)}_{int(time.time() * 1000)}"
            tasks[task_id] = {
                "id": task_id, "type": task_type, "table": table,
                "config": dict(config), "state": self.TaskState.PENDING,
                "worker": None, "ts_ms": int(time.time() * 1000), "output": None,
            }
            return task_id

        return self._tx(fn)

    def claim_task(self, instance_id: str,
                   task_types: Optional[list] = None) -> Optional[dict]:
        """CAS-claim the oldest PENDING task (optionally restricted by type)."""

        def fn(s):
            pending = sorted(
                (t for t in s.get("tasks", {}).values()
                 if t["state"] == self.TaskState.PENDING
                 and (task_types is None or t["type"] in task_types)),
                key=lambda t: t["ts_ms"],
            )
            if not pending:
                return None
            t = pending[0]
            t["state"] = self.TaskState.RUNNING
            t["worker"] = instance_id
            t["ts_ms"] = int(time.time() * 1000)
            return dict(t)

        return self._tx(fn)

    def finish_task(self, task_id: str, ok: bool, output: Optional[str] = None) -> None:
        def fn(s):
            t = s.get("tasks", {}).get(task_id)
            if t is not None:
                t["state"] = self.TaskState.DONE if ok else self.TaskState.FAILED
                t["output"] = output
                t["ts_ms"] = int(time.time() * 1000)

        self._tx(fn)

    def touch_task(self, task_id: str) -> None:
        """Executor heartbeat: a healthy long-running task refreshes ts_ms
        so requeue_stale_tasks never requeues live work."""

        def fn(s):
            t = s.get("tasks", {}).get(task_id)
            if t is not None and t["state"] == self.TaskState.RUNNING:
                t["ts_ms"] = int(time.time() * 1000)

        self._tx(fn)

    def prune_terminal_tasks(self, ttl_ms: int = 3_600_000) -> int:
        """GC DONE/FAILED tasks older than ``ttl_ms`` — the tasks map rides
        every FileRegistry transaction, so history must stay bounded."""

        def fn(s):
            tasks = s.get("tasks", {})
            cutoff = int(time.time() * 1000) - ttl_ms
            dead = [tid for tid, t in tasks.items()
                    if t["state"] in (self.TaskState.DONE, self.TaskState.FAILED)
                    and t["ts_ms"] < cutoff]
            for tid in dead:
                del tasks[tid]
            return len(dead)

        return self._tx(fn)

    def requeue_stale_tasks(self, stale_ms: int, max_attempts: int = 3) -> list:
        """Repair path for dead minions (stale-COMMITTING analog of the
        completion FSM): RUNNING tasks untouched for ``stale_ms`` go back to
        PENDING (or FAILED once ``max_attempts`` claims burned)."""

        def fn(s):
            now = int(time.time() * 1000)
            changed = []
            for t in s.get("tasks", {}).values():
                if t["state"] == self.TaskState.RUNNING \
                        and now - t["ts_ms"] >= stale_ms:
                    attempts = t.get("attempts", 1)
                    if attempts >= max_attempts:
                        t["state"] = self.TaskState.FAILED
                        t["output"] = f"abandoned after {attempts} stale claims"
                    else:
                        t["state"] = self.TaskState.PENDING
                        t["worker"] = None
                        t["attempts"] = attempts + 1
                    t["ts_ms"] = now
                    changed.append(dict(t))
            return changed

        return self._tx(fn)

    def tasks(self, table: Optional[str] = None,
              state: Optional[str] = None) -> list:
        def fn(s):
            out = [dict(t) for t in s.get("tasks", {}).values()]
            if table is not None:
                out = [t for t in out if t["table"] == table]
            if state is not None:
                out = [t for t in out if t["state"] == state]
            return sorted(out, key=lambda t: t["ts_ms"])

        return self._tx_read(fn)

    # ---- per-table task metadata (watermarks etc.; ZK minion metadata) ---
    def task_metadata_get(self, table: str, task_type: str) -> dict:
        return self._tx_read(
            lambda s: dict(s.get("task_metadata", {}).get(table, {}).get(task_type, {}))
        )

    def task_metadata_set(self, table: str, task_type: str, meta: dict) -> None:
        self._tx(lambda s: s.setdefault("task_metadata", {})
                 .setdefault(table, {}).__setitem__(task_type, dict(meta)))

    # ---- segment lineage (SegmentLineage analog: atomic replace) ---------
    # {table: {lineage_id: {from: [...], to: [...], state, ts_ms}}}
    # IN_PROGRESS: brokers route the FROM set (TO still loading);
    # COMPLETED:   brokers route the TO set (FROM await deletion).
    # The single-tx flip is what makes a merge swap atomic to queries.

    def start_lineage(self, table: str, from_segments: list, to_segments: list) -> str:
        def fn(s):
            lin = s.setdefault("segment_lineage", {}).setdefault(table, {})
            lid = f"lineage_{len(lin)}_{int(time.time() * 1000)}"
            lin[lid] = {
                "from": list(from_segments), "to": list(to_segments),
                "state": "IN_PROGRESS", "ts_ms": int(time.time() * 1000),
            }
            return lid

        lid = self._tx(fn)
        self._note_routing_change()
        return lid

    def complete_lineage(self, table: str, lineage_id: str) -> bool:
        """CAS flip IN_PROGRESS → COMPLETED. Returns False if the entry was
        concurrently aborted/repaired — the caller MUST then abandon the
        swap (deleting the FROM set after a lost flip loses both copies)."""

        def fn(s):
            e = s.get("segment_lineage", {}).get(table, {}).get(lineage_id)
            if e is None or e["state"] != "IN_PROGRESS":
                return False
            e["state"] = "COMPLETED"
            e["ts_ms"] = int(time.time() * 1000)
            return True

        out = self._tx(fn)
        if out:
            self._note_routing_change()
        return out

    def try_abort_lineage(self, table: str, lineage_id: str) -> bool:
        """CAS IN_PROGRESS → ABORTING (controller repair claims the unwind).
        ABORTING keeps the TO set routing-excluded while its segments are
        deleted; False means the executor already flipped to COMPLETED."""

        def fn(s):
            e = s.get("segment_lineage", {}).get(table, {}).get(lineage_id)
            if e is None or e["state"] == "COMPLETED":
                return False
            e["state"] = "ABORTING"
            e["ts_ms"] = int(time.time() * 1000)
            return True

        out = self._tx(fn)
        if out:
            self._note_routing_change()
        return out

    def revert_lineage(self, table: str, lineage_id: str) -> bool:
        """Drop a non-COMPLETED entry (failed/aborted replace). A COMPLETED
        entry is never dropped here — prune_lineage GCs it once the FROM
        set is fully gone."""

        def fn(s):
            lin = s.get("segment_lineage", {}).get(table, {})
            e = lin.get(lineage_id)
            if e is None or e["state"] == "COMPLETED":
                return False
            del lin[lineage_id]
            return True

        out = self._tx(fn)
        if out:
            self._note_routing_change()
        return out

    def lineage(self, table: str) -> dict:
        return self._tx_read(
            lambda s: {k: dict(v) for k, v in
                       s.get("segment_lineage", {}).get(table, {}).items()}
        )

    def stale_in_progress_lineage(self, table: str, stale_ms: int) -> dict:
        """Non-COMPLETED entries untouched for ``stale_ms`` (the executor —
        or a previous repair — died mid-swap); the controller unwinds them."""
        now = int(time.time() * 1000)
        return {
            lid: e for lid, e in self.lineage(table).items()
            if e["state"] != "COMPLETED" and now - e["ts_ms"] >= stale_ms
        }

    def routing_snapshot(self, table: str) -> tuple:
        """(external_view, segment records, lineage) in ONE read tx — the
        broker's per-query read; a single FileRegistry parse instead of
        three, and no cross-read consistency window."""

        def fn(s):
            view = {k: list(v) for k, v in
                    s["external_view"].get(table, {}).items() if v}
            records = dict(s["segments"].get(table, {}))
            lineage = {k: dict(v) for k, v in
                       s.get("segment_lineage", {}).get(table, {}).items()}
            return view, records, lineage

        return self._tx_read(fn)

    def prune_lineage(self, table: str) -> int:
        """GC COMPLETED entries whose FROM segments are fully deleted."""

        def fn(s):
            lin = s.get("segment_lineage", {}).get(table, {})
            segs = s.get("segments", {}).get(table, {})
            ev = s.get("external_view", {}).get(table, {})
            gone = 0
            for lid in list(lin):
                e = lin[lid]
                if e["state"] == "COMPLETED" and not any(
                    f in segs or ev.get(f) for f in e["from"]
                ):
                    del lin[lid]
                    gone += 1
            return gone

        return self._tx(fn)


_SECTIONS = (
    "instances", "tables", "schemas", "segments", "assignment",
    "external_view", "partition_assignment", "segment_completion",
    "tasks", "task_metadata", "segment_lineage", "replica_groups",
    "leases", "autoscaler",
)

# sections whose change means "what a query routes to (or would read)
# moved" — the FileRegistry's routing generation sums exactly these
# version counters, so heartbeats/leases/tasks never blow broker caches
_ROUTING_SECTIONS = (
    "tables", "segments", "assignment", "external_view",
    "segment_lineage", "replica_groups",
)


def _section_to_json(name: str, data: dict):
    # vars() over dataclasses.asdict: fields are flat scalars/lists and
    # asdict's recursive deep-copy dominates section-write cost at
    # thousands of segments
    if name == "instances":
        return {k: dict(vars(v)) for k, v in data.items()}
    if name == "segments":
        return {t: {n: dict(vars(r)) for n, r in segs.items()}
                for t, segs in data.items()}
    return data


def _section_from_json(name: str, d):
    d = d or {}
    if name == "instances":
        return {k: InstanceInfo(**v) for k, v in d.items()}
    if name == "segments":
        return {t: {n: SegmentRecord(**r) for n, r in segs.items()}
                for t, segs in d.items()}
    return d


class _LazyState:
    """Dict-like view over the registry's section files: sections load on
    first access within a transaction, and only ACCESSED sections are
    written back — a heartbeat touches instances.json alone instead of
    rewriting (and re-parsing) the whole cluster state."""

    def __init__(self, reg: "FileRegistry"):
        self._reg = reg
        self.accessed: set = set()

    def _section(self, key: str) -> dict:
        if key not in _SECTIONS:
            raise KeyError(key)
        self.accessed.add(key)
        return self._reg._load_section(key)

    def __getitem__(self, key: str) -> dict:
        return self._section(key)

    def get(self, key: str, default=None):
        return self._section(key)

    def setdefault(self, key: str, default=None):
        return self._section(key)

    def __contains__(self, key: str) -> bool:
        return key in _SECTIONS


class FileRegistry(ClusterRegistry):
    """File-backed registry with advisory locking: the durable cluster
    state for multi-process single-host clusters (the role ZK plays).

    Layout: ``<path>.d/<section>.json`` — one file per state section plus a
    monotonically-bumped ``version`` stamp. Transactions hold one flock,
    load only the sections they touch, and rewrite only those (atomic
    tmp+rename). A version-validated in-process cache makes the poll paths
    (server sync, broker routing) parse nothing but the tiny version file
    while the cluster is quiescent — the FileRegistry equivalent of ZK
    watches."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self.dir = path + ".d"
        os.makedirs(self.dir, exist_ok=True)
        self._version_path = os.path.join(self.dir, "version")
        self._lock_path = os.path.join(self.dir, ".lock")
        self._cache: dict = {}      # section -> parsed state
        self._raw: dict = {}        # section -> serialized text (dirty check)
        self._sig: dict = {}        # section -> file stat signature
        self._lock_fh = None        # persistent flock fd (see _locked)
        self._migrate_legacy()

    def _migrate_legacy(self) -> None:
        """One-time split of a pre-section single-JSON state file."""
        with self._locked(write=True):
            if os.path.exists(self._version_path):
                return
            legacy = {}
            if os.path.isfile(self.path):
                try:
                    with open(self.path) as f:
                        legacy = _from_json(json.load(f))
                except (json.JSONDecodeError, OSError):
                    legacy = {}
            for name in _SECTIONS:
                self._write_section(name, legacy.get(name, {}))
            self._bump_version()

    # ---- file plumbing ---------------------------------------------------
    @contextlib.contextmanager
    def _locked(self, write: bool):
        with self._lock:
            # the lock fd is opened ONCE and kept: under sandboxed kernels
            # (gVisor-class gofer fs) every open() is an ~ms RPC, and the
            # old open-per-tx pattern made the file lock itself the most
            # expensive part of an otherwise cached read tx. self._lock
            # already serializes threads, so one fd per process is safe.
            lf = self._lock_fh
            if lf is None or lf.closed:
                lf = self._lock_fh = open(self._lock_path, "a+")
            fcntl.flock(lf, fcntl.LOCK_EX if write else fcntl.LOCK_SH)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def _read_versions(self) -> dict:
        """Per-section change counters — one tiny file read per tx; a
        heartbeat bump invalidates peers' cached instances section only,
        not their (large) segments/assignment caches."""
        try:
            with open(self._version_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return {}

    def _bump_version(self, sections=None) -> dict:
        v = self._read_versions()
        for name in (sections if sections is not None else _SECTIONS):
            v[name] = v.get(name, 0) + 1
        tmp = f"{self._version_path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(v, f)
        os.replace(tmp, self._version_path)
        return v

    def _section_path(self, name: str) -> str:
        return os.path.join(self.dir, f"{name}.json")

    def _file_sig(self, name: str):
        try:
            st = os.stat(self._section_path(name))
            return (st.st_ino, st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _load_section(self, name: str) -> dict:
        if name in self._cache:
            return self._cache[name]
        try:
            with open(self._section_path(name)) as f:
                text = f.read()
            data = _section_from_json(name, json.loads(text))
        except (OSError, json.JSONDecodeError):
            text, data = "", _section_from_json(name, {})
        self._cache[name] = data
        self._raw[name] = text
        self._sig[name] = self._file_sig(name)
        return data

    def _stage_section(self, name: str, data: dict):
        """Serialize ONE section to a tmp file; returns (tmp_path, text), or
        None (skipping the disk write) when the content is byte-identical to
        what's on disk — read-shaped write txs (empty claim_task polls, no-op
        heartbeats) must not churn files or invalidate peer caches.

        Staging is separate from publishing (the os.replace in _tx) so a
        multi-section tx hits its slow/fallible part — serialization + data
        writes — before ANY section becomes visible to peers; the publish
        pass is metadata-only renames."""
        # dumps-then-write hits the C encoder; json.dump's streaming
        # iterencode is ~10x slower on large sections
        text = json.dumps(_section_to_json(name, data))
        if text == self._raw.get(name):
            return None
        tmp = f"{self._section_path(name)}.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(text)
        except Exception:
            # a partial tmp (ENOSPC mid-write) must not linger — debris
            # accumulates exactly when the disk is already full
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return tmp, text

    def _publish_staged(self, name: str, tmp: str, text: str) -> None:
        """Atomically swap a staged tmp into place + refresh cache
        bookkeeping (single publication contract for both the one-section
        and multi-section write paths)."""
        os.replace(tmp, self._section_path(name))
        self._raw[name] = text
        self._sig[name] = self._file_sig(name)

    def _write_section(self, name: str, data: dict) -> bool:
        """Stage + publish ONE section (single-section callers like legacy
        migration, where cross-section atomicity doesn't apply)."""
        s = self._stage_section(name, data)
        if s is None:
            return False
        self._publish_staged(name, *s)
        return True

    def _drop_cache(self) -> None:
        self._cache.clear()
        self._raw.clear()
        self._sig.clear()

    # ---- transactions ----------------------------------------------------
    def _tx(self, fn, write: bool = True):
        with self._locked(write):
            # stat-signature validation: survives a peer crashing between
            # its section writes and version bump (the file itself is the
            # truth, not the counter)
            for name in list(self._cache):
                if self._file_sig(name) != self._sig.get(name):
                    del self._cache[name]
                    self._raw.pop(name, None)
                    self._sig.pop(name, None)
            state = _LazyState(self)
            try:
                out = fn(state)
                if write and state.accessed:
                    # two-phase write-back: stage every dirty section fully,
                    # THEN publish with a tight rename-only loop, so a crash
                    # or serialization error mid-tx leaves peers seeing either
                    # none or all of a cross-section transaction (the advisor
                    # case: segments updated but external_view not)
                    staged = []
                    try:
                        for name in state.accessed:
                            s = self._stage_section(name, self._cache[name])
                            if s is not None:
                                staged.append((name, *s))
                        for name, tmp, text in staged:
                            self._publish_staged(name, tmp, text)
                    except Exception:
                        # staging failure → nothing published; publish
                        # failure → torn state is unavoidable (renames are
                        # metadata-only, so this is a pathological fs), but
                        # at least don't leak the unpublished tmps
                        for _, tmp, _ in staged:
                            try:
                                os.unlink(tmp)
                            except OSError:
                                pass
                        raise
                    if staged:
                        self._bump_version([name for name, _, _ in staged])
            except Exception:
                # fn (or a failed write-back) may have left cached sections
                # diverged from disk: never serve them again
                self._drop_cache()
                raise
            return out

    def state_version(self) -> int:
        """Cheap change token: pollers can skip work while it holds still
        (the ZK-watch analog for file-backed clusters). Lock-free like
        routing_generation: the version file is replaced atomically, so a
        torn read is impossible and the flock would only add syscalls to
        the hot polling path."""
        return sum(self._read_versions().values())

    def sections_version(self, sections) -> int:
        """Change token over a CHOSEN section subset — the server sync
        loop polls (tables, schemas, segments, assignment,
        partition_assignment, ...) without being re-triggered by every
        controller lease renewal, peer heartbeat, or external-view
        publish (lock-free, see state_version)."""
        v = self._read_versions()
        return sum(v.get(name, 0) for name in sections)

    def routing_generation(self) -> int:
        """Cross-process routing-change token: the sum of the ROUTING
        section version counters (the version file is written atomically,
        so this reads lock-free). Heartbeats touch only instances.json and
        don't move it — byte-identical section writes are skipped at
        staging, so a steady-state sync tick bumps nothing."""
        v = self._read_versions()
        return sum(v.get(name, 0) for name in _ROUTING_SECTIONS)
