"""gRPC data-plane transport: broker ↔ server query RPC.

Equivalent of the reference's query wire (Netty + thrift-compact
InstanceRequest, InstanceRequestHandler.java:54-76, and the gRPC streaming
server GrpcQueryServer.java:53,117 / server.proto:43-59). One method:

    /pinot.PinotQueryServer/Submit   bytes → bytes

Request: JSON {sql, segments: [...], requestId, brokerId, traceEnabled}
(the InstanceRequest analog — the query ships as SQL text the way the
reference ships the PinotQuery AST). Response: DataTable bytes
(engine/datatable.py). Raw-bytes generic handlers avoid a protoc build
step while keeping a real gRPC wire — HTTP/2 framing, deadlines, and
multiplexed channels all apply.
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Callable, Optional

import grpc

SUBMIT_METHOD = "/pinot.PinotQueryServer/Submit"
SUBMIT_STREAMING_METHOD = "/pinot.PinotQueryServer/SubmitStreaming"
# peer segment download (PeerServerSegmentFinder role): a server streams a
# tar of a segment dir it serves to a replica whose deep-store copy is
# unreachable
FETCH_SEGMENT_METHOD = "/pinot.PinotQueryServer/FetchSegment"
# distributed stage-2 exchange (mailbox leapfrog — the reference snapshot
# has no pinot-query-runtime): ExecuteStage is the broker→server "run your
# slice of stage 2" request; ExchangeTransfer is the server→server
# partition payload (query2/exchange.py wire codec)
EXECUTE_STAGE_METHOD = "/pinot.PinotQueryServer/ExecuteStage"
EXCHANGE_TRANSFER_METHOD = "/pinot.PinotQueryServer/ExchangeTransfer"

# wide-result headroom (ISSUE 18): gRPC's 4 MB default inbound cap turns a
# multi-million-row buffered SELECT into RESOURCE_EXHAUSTED before the
# broker ever sees the DataTable. Mirror the reference's GrpcConfig
# maxInboundMessageSizeBytes default (128 MB) on both ends of the wire;
# the streaming path stays the right answer for results bigger than one
# message, this just keeps the unary path honest up to the same bound.
MAX_INBOUND_MESSAGE_BYTES = 128 * 1024 * 1024
_SIZE_OPTIONS = (
    ("grpc.max_receive_message_length", MAX_INBOUND_MESSAGE_BYTES),
    ("grpc.max_send_message_length", MAX_INBOUND_MESSAGE_BYTES),
)


def make_instance_request(sql: str, segments: list, request_id: int,
                          broker_id: str = "", trace: bool = False,
                          table: str = None, time_filter: dict = None,
                          timeout_ms: float = None, trace_id: str = None,
                          attempt: str = "primary", workload: str = None,
                          priority: str = None) -> bytes:
    """``table``: physical table override (hybrid split sends the same SQL to
    X_OFFLINE and X_REALTIME); ``time_filter``: {column, op le|gt, value}
    AND-ed server-side (the time-boundary predicate); ``timeout_ms``: the
    query's REMAINING deadline budget at send time — the server bounds
    every downstream wait by it and answers QUERY_TIMEOUT instead of
    executing work the broker already abandoned (the reference ships
    timeoutMs in the InstanceRequest the same way).

    ``trace``/``trace_id``/``attempt``: the distributed-tracing stamp
    (the reference's InstanceRequest ``enableTrace`` + requestId): when
    the query runs with SET trace=true the broker sets traceEnabled on
    EVERY attempt — primary, retry, or hedge, ``attempt`` naming which —
    so the per-server span ladders all join one trace id.

    ``workload``/``priority`` (ISSUE 14): the broker-resolved tenant and
    priority class — the server's weighted-fair scheduler groups slots
    by the TENANT (falling back to the table name when absent) so one
    tenant cannot hold every server slot, and the class weight sets the
    group's fair share."""
    return json.dumps(
        {
            "sql": sql,
            "segments": list(segments),
            "requestId": request_id,
            "brokerId": broker_id,
            "traceEnabled": trace,
            "traceId": trace_id,
            "attempt": attempt,
            "table": table,
            "timeFilter": time_filter,
            "timeoutMs": timeout_ms,
            "workload": workload,
            "priority": priority,
        }
    ).encode("utf-8")


def parse_instance_request(data: bytes) -> dict:
    return json.loads(data.decode("utf-8"))


class _BytesHandler(grpc.GenericRpcHandler):
    def __init__(self, submit_fn: Callable[[bytes], bytes],
                 submit_streaming_fn: Optional[Callable] = None,
                 fetch_segment_fn: Optional[Callable] = None,
                 execute_stage_fn: Optional[Callable] = None,
                 exchange_transfer_fn: Optional[Callable] = None):
        self._submit = submit_fn
        self._submit_streaming = submit_streaming_fn
        self._fetch_segment = fetch_segment_fn
        self._execute_stage = execute_stage_fn
        self._exchange_transfer = exchange_transfer_fn

    def service(self, handler_call_details):
        if handler_call_details.method == SUBMIT_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._submit(req),
                request_deserializer=None,
                response_serializer=None,
            )
        if (handler_call_details.method == EXECUTE_STAGE_METHOD
                and self._execute_stage is not None):
            # broker → server: run one worker's slice of distributed
            # stage 2 (scan, partition, ship, join, partial-aggregate)
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._execute_stage(req),
                request_deserializer=None,
                response_serializer=None,
            )
        if (handler_call_details.method == EXCHANGE_TRANSFER_METHOD
                and self._exchange_transfer is not None):
            # server → server: one hash-partition payload for a mailbox
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._exchange_transfer(req),
                request_deserializer=None,
                response_serializer=None,
            )
        if (handler_call_details.method == SUBMIT_STREAMING_METHOD
                and self._submit_streaming is not None):
            # server-streaming: one DataTable block per yield
            # (server.proto:43-47 streaming Submit analog)
            return grpc.unary_stream_rpc_method_handler(
                lambda req, ctx: self._submit_streaming(req),
                request_deserializer=None,
                response_serializer=None,
            )
        if (handler_call_details.method == FETCH_SEGMENT_METHOD
                and self._fetch_segment is not None):
            # server-streaming tar chunks of a hosted segment dir
            return grpc.unary_stream_rpc_method_handler(
                lambda req, ctx: self._fetch_segment(req),
                request_deserializer=None,
                response_serializer=None,
            )
        return None


class QueryServerTransport:
    """Server side: listens and dispatches Submit to the handler."""

    def __init__(self, submit_fn: Callable[[bytes], bytes],
                 host: str = "127.0.0.1", port: int = 0, max_workers: int = 8,
                 submit_streaming_fn: Optional[Callable] = None, tls=None,
                 fetch_segment_fn: Optional[Callable] = None,
                 execute_stage_fn: Optional[Callable] = None,
                 exchange_transfer_fn: Optional[Callable] = None):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            handlers=(_BytesHandler(submit_fn, submit_streaming_fn,
                                    fetch_segment_fn, execute_stage_fn,
                                    exchange_transfer_fn),),
            options=_SIZE_OPTIONS,
        )
        if tls is not None:
            # TlsConfig (common/tls.py) — the reference's Netty/gRPC TLS
            # listener (TlsConfig.java + GrpcQueryServer secure mode)
            self.port = self._server.add_secure_port(
                f"{host}:{port}", tls.server_credentials())
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self.tls_enabled = tls is not None

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"


class QueryRouterChannel:
    """Broker side: one channel per server instance
    (transport/QueryRouter.java + ServerChannels analog)."""

    def __init__(self, endpoint: str, tls=None):
        self.endpoint = endpoint
        if tls is not None:
            self._channel = grpc.secure_channel(
                endpoint, tls.channel_credentials(),
                options=tuple(tls.channel_options()) + _SIZE_OPTIONS)
        else:
            self._channel = grpc.insecure_channel(
                endpoint, options=_SIZE_OPTIONS)
        self._submit = self._channel.unary_unary(
            SUBMIT_METHOD, request_serializer=None, response_deserializer=None
        )
        self._submit_streaming = self._channel.unary_stream(
            SUBMIT_STREAMING_METHOD, request_serializer=None,
            response_deserializer=None,
        )
        self._fetch_segment = self._channel.unary_stream(
            FETCH_SEGMENT_METHOD, request_serializer=None,
            response_deserializer=None,
        )
        self._execute_stage = self._channel.unary_unary(
            EXECUTE_STAGE_METHOD, request_serializer=None,
            response_deserializer=None,
        )
        self._exchange_transfer = self._channel.unary_unary(
            EXCHANGE_TRANSFER_METHOD, request_serializer=None,
            response_deserializer=None,
        )

    def submit(self, request: bytes, timeout_s: float) -> bytes:
        return self._submit(request, timeout=timeout_s)

    def execute_stage(self, request: bytes, timeout_s: float) -> bytes:
        """Distributed stage-2: DataTable of the worker's merged
        partition partials."""
        return self._execute_stage(request, timeout=timeout_s)

    def transfer(self, request: bytes, timeout_s: float) -> bytes:
        """Exchange payload → JSON ack {ok, spilled, softLimit}."""
        return self._exchange_transfer(request, timeout=timeout_s)

    def fetch_segment(self, request: bytes, timeout_s: float):
        """Peer segment download: iterator of tar chunks."""
        return self._fetch_segment(request, timeout=timeout_s)

    def submit_streaming(self, request: bytes, timeout_s: float):
        """Returns the gRPC response iterator (also a Call: the consumer
        may ``.cancel()`` it for early termination once it has enough
        rows — the streaming reduce's short-circuit)."""
        return self._submit_streaming(request, timeout=timeout_s)

    def close(self) -> None:
        self._channel.close()
