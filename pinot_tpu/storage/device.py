"""Device-resident segments: HBM column blocks.

The TPU replacement for the reference's mmap'd ``PinotDataBuffer`` substrate
(pinot-segment-spi/.../memory/PinotDataBuffer.java): instead of byte buffers
read through per-doc virtual calls, a segment's queryable columns are shipped
once to HBM as dense, padded arrays:

- DICT columns  -> int32 dict ids (pad value -1, never matches a predicate)
- RAW columns   -> narrow typed arrays (int32/int64/float32); aggregation
                   kernels widen in-register, so HBM traffic stays narrow
- lengths are padded up to a block multiple (default 1024 = 8 sublanes x 128
  lanes) so every kernel sees static, tile-aligned shapes

``DeviceSegmentBatch`` stacks many segments into one (S, L) launch — the
batched-kernel replacement for BaseCombineOperator's per-segment thread pool
(pinot-core/.../operator/combine/BaseCombineOperator.java:79-145).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.storage.segment import Encoding, ImmutableSegment

PAD_MULTIPLE = 1024

_RAW_DEVICE_DTYPES = {
    DataType.INT: np.int32,
    DataType.LONG: np.int64,
    DataType.FLOAT: np.float32,
    DataType.DOUBLE: np.float32,  # TPU has no native f64; broker reduce re-widens
    DataType.BIG_DECIMAL: np.float32,
    DataType.BOOLEAN: np.int32,
    DataType.TIMESTAMP: np.int64,
}


def padded_len(n: int, multiple: int = PAD_MULTIPLE) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def host_column_block(seg: ImmutableSegment, col: str, pad_to: int) -> np.ndarray:
    """Padded host array for one column (not yet on device)."""
    meta = seg.column_metadata(col)
    if not meta.single_value:
        raise NotImplementedError(
            "multi-value columns execute on the host path for now"
        )
    fwd = np.asarray(seg.forward(col))
    if meta.encoding == Encoding.DICT:
        out = np.full(pad_to, -1, dtype=np.int32)
        out[: len(fwd)] = fwd
        return out
    dt = _RAW_DEVICE_DTYPES[meta.data_type]
    out = np.zeros(pad_to, dtype=dt)
    out[: len(fwd)] = fwd.astype(dt)
    return out


@dataclasses.dataclass
class DeviceColumn:
    name: str
    data: jax.Array  # (padded,) or (S, padded) when batched
    encoding: str
    data_type: DataType


class DeviceSegment:
    """One segment's queryable columns in HBM."""

    def __init__(self, segment: ImmutableSegment, columns: Optional[Sequence[str]] = None,
                 pad_multiple: int = PAD_MULTIPLE, device=None):
        self.segment = segment
        self.n_docs = segment.n_docs
        self.padded = padded_len(self.n_docs, pad_multiple)
        self.columns: dict[str, DeviceColumn] = {}
        self._device = device
        names = list(columns) if columns is not None else [
            c for c in segment.column_names() if segment.column_metadata(c).single_value
        ]
        for c in names:
            self._upload(c)

    def _upload(self, col: str) -> None:
        meta = self.segment.column_metadata(col)
        block = host_column_block(self.segment, col, self.padded)
        arr = jax.device_put(block, self._device)
        self.columns[col] = DeviceColumn(col, arr, meta.encoding, meta.data_type)

    def column(self, name: str) -> DeviceColumn:
        if name not in self.columns:
            self._upload(name)  # lands on the same device as the eager columns
        return self.columns[name]

    @property
    def valid_count(self) -> int:
        return self.n_docs


class DeviceSegmentBatch:
    """Many segments stacked on a leading axis for one batched kernel launch.

    All segments are padded to the batch max length; per-segment doc counts
    ride along as an int32 vector so kernels can mask padding. This axis is
    what gets sharded over the device mesh (parallel/mesh.py).
    """

    def __init__(self, segments: Sequence[ImmutableSegment], columns: Sequence[str],
                 pad_multiple: int = PAD_MULTIPLE):
        self.segments = list(segments)
        if not self.segments:
            raise ValueError("empty batch")
        self.pad_to = max(padded_len(s.n_docs, pad_multiple) for s in self.segments)
        self.n_docs = np.array([s.n_docs for s in self.segments], dtype=np.int32)
        self.columns: dict[str, DeviceColumn] = {}
        for c in columns:
            metas = [s.column_metadata(c) for s in self.segments]
            enc = metas[0].encoding
            if any(m.encoding != enc for m in metas):
                raise ValueError(f"mixed encodings for column {c!r} across batch")
            stacked = np.stack([host_column_block(s, c, self.pad_to) for s in self.segments])
            self.columns[c] = DeviceColumn(c, jnp.asarray(stacked), enc, metas[0].data_type)
        self.n_docs_dev = jnp.asarray(self.n_docs)

    @property
    def num_segments(self) -> int:
        return len(self.segments)

    def column(self, name: str) -> DeviceColumn:
        return self.columns[name]
