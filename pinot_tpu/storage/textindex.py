"""Text index: tokenized posting lists with positions for TEXT_MATCH.

Equivalent of the reference's Lucene-backed text index
(pinot-segment-local/.../readers/text/LuceneTextIndexReader.java, creator
LuceneTextIndexCreator): documents tokenize to lowercase alphanumeric
terms; TEXT_MATCH(col, '<query>') supports the Lucene query subset the
reference's docs exercise — bare terms, AND/OR (AND binds tighter),
"quoted phrases" (consecutive positions), and trailing-wildcard prefix
terms (``plan*``). Bare terms separated by whitespace OR together, the
Lucene default operator.

On disk (``<col>.textidx.npz``): sorted term array with concatenated
(doc, position) postings. Segments without the index tokenize the column
at query time and evaluate the same semantics (scan path).
"""

from __future__ import annotations

import re

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def tokenize_text(s: str) -> list:
    return _TOKEN_RE.findall(str(s).lower())


def _build_postings(values):
    """(terms, off, docs, poss) — shared by the on-disk build and the
    ephemeral scan index so both paths stay byte-identical in layout."""
    postings: dict = {}  # term -> (docs list, positions list)
    for doc_id, s in enumerate(values):
        for pos, tok in enumerate(tokenize_text(s)):
            d, p = postings.setdefault(tok, ([], []))
            d.append(doc_id)
            p.append(pos)
    terms = sorted(postings)
    off = np.zeros(len(terms) + 1, dtype=np.int64)
    total = sum(len(postings[t][0]) for t in terms)
    docs = np.empty(total, dtype=np.int64)
    poss = np.empty(total, dtype=np.int64)
    at = 0
    for i, t in enumerate(terms):
        d, p = postings[t]
        docs[at: at + len(d)] = d
        poss[at: at + len(d)] = p
        at += len(d)
        off[i + 1] = at
    return np.asarray(terms, dtype=np.str_), off, docs, poss


def build_text_index(values, out_path: str) -> None:
    terms, off, docs, poss = _build_postings(values)
    np.savez(out_path, terms=terms, off=off, docs=docs, poss=poss)


class TextIndexReader:
    def __init__(self, npz_path: str):
        z = np.load(npz_path, allow_pickle=False)
        self._terms = z["terms"]
        self._off = z["off"]
        self._docs = z["docs"]
        self._poss = z["poss"]

    def _term_slice(self, term: str):
        i = int(np.searchsorted(self._terms, term))
        if i >= len(self._terms) or str(self._terms[i]) != term:
            return None
        return self._off[i], self._off[i + 1]

    def posting(self, term: str):
        s = self._term_slice(term)
        if s is None:
            return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
        lo, hi = s
        return np.asarray(self._docs[lo:hi]), np.asarray(self._poss[lo:hi])

    def prefix_posting(self, prefix: str):
        lo_i = int(np.searchsorted(self._terms, prefix))
        hi_i = int(np.searchsorted(self._terms, prefix + "￿"))
        if lo_i == hi_i:
            return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
        lo, hi = self._off[lo_i], self._off[hi_i]
        return np.asarray(self._docs[lo:hi]), np.asarray(self._poss[lo:hi])

    def match(self, query: str, n_docs: int) -> np.ndarray:
        docs = _eval_query(parse_text_query(query), self)
        mask = np.zeros(n_docs, dtype=bool)
        valid = docs[docs < n_docs]
        mask[valid] = True
        return mask


class ScanTextIndex(TextIndexReader):
    """Ephemeral in-memory index over raw values (no-index scan path)."""

    def __init__(self, values):
        self._terms, self._off, self._docs, self._poss = _build_postings(values)


# ---------------------------------------------------------------------------
# Query parsing: OR( AND( unit... )... ); unit = term | prefix* | "phrase"
# ---------------------------------------------------------------------------

_QUERY_TOKEN_RE = re.compile(r'"([^"]*)"|\(|\)|[^\s()"]+')


def parse_text_query(query: str):
    """-> nested ('or', [...]) / ('and', [...]) / ('term'|'prefix'|'phrase', s)."""
    tokens = []
    for m in _QUERY_TOKEN_RE.finditer(query):
        if m.group(1) is not None:
            tokens.append(("phrase", m.group(1)))
        else:
            tokens.append(("raw", m.group(0)))
    pos = [0]

    def parse_or():
        parts = [parse_and()]
        while pos[0] < len(tokens):
            kind, text = tokens[pos[0]]
            # operators are case-sensitive, like Lucene's QueryParser:
            # lowercase 'or'/'and' are ordinary search terms
            if kind == "raw" and text == "OR":
                pos[0] += 1
                parts.append(parse_and())
            elif kind == "raw" and text == ")":
                break
            else:
                # bare juxtaposition: Lucene default operator is OR
                parts.append(parse_and())
        return ("or", parts) if len(parts) > 1 else parts[0]

    def parse_and():
        parts = [parse_unit()]
        while pos[0] < len(tokens):
            kind, text = tokens[pos[0]]
            if kind == "raw" and text == "AND":
                pos[0] += 1
                parts.append(parse_unit())
            else:
                break
        return ("and", parts) if len(parts) > 1 else parts[0]

    def parse_unit():
        if pos[0] >= len(tokens):
            raise ValueError(f"bad TEXT_MATCH query: {query!r}")
        kind, text = tokens[pos[0]]
        pos[0] += 1
        if kind == "phrase":
            return ("phrase", text)
        if text == "(":
            node = parse_or()
            if pos[0] < len(tokens) and tokens[pos[0]] == ("raw", ")"):
                pos[0] += 1
            return node
        if text.endswith("*") and len(text) > 1:
            return ("prefix", text[:-1].lower())
        return ("term", text.lower())

    node = parse_or()
    if pos[0] != len(tokens):
        raise ValueError(f"bad TEXT_MATCH query: {query!r}")
    return node


def _eval_query(node, idx: TextIndexReader) -> np.ndarray:
    kind = node[0]
    if kind == "or":
        docs = _eval_query(node[1][0], idx)
        for child in node[1][1:]:
            docs = np.union1d(docs, _eval_query(child, idx))
        return docs
    if kind == "and":
        docs = _eval_query(node[1][0], idx)
        for child in node[1][1:]:
            docs = np.intersect1d(docs, _eval_query(child, idx))
        return docs
    if kind == "term":
        return np.unique(idx.posting(node[1])[0])
    if kind == "prefix":
        return np.unique(idx.prefix_posting(node[1])[0])
    if kind == "phrase":
        return _phrase_docs(node[1], idx)
    raise ValueError(f"bad text query node {node!r}")


def _phrase_docs(phrase: str, idx: TextIndexReader) -> np.ndarray:
    terms = tokenize_text(phrase)
    if not terms:
        return np.empty(0, dtype=np.int64)
    if len(terms) == 1:
        return np.unique(idx.posting(terms[0])[0])
    # offset each term's positions back to the phrase start; a doc matches
    # when some start position appears for every term
    postings = [idx.posting(t) for t in terms]
    docs = np.unique(postings[0][0])
    for d, _ in postings[1:]:
        docs = np.intersect1d(docs, np.unique(d))
    out = []
    for doc in docs:
        starts = None
        for i, (d, p) in enumerate(postings):
            sp = p[d == doc] - i
            starts = sp if starts is None else np.intersect1d(starts, sp)
            if len(starts) == 0:
                break
        if starts is not None and len(starts):
            out.append(doc)
    return np.asarray(out, dtype=np.int64)
