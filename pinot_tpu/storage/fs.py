"""Deep-store filesystem SPI.

Equivalent of the reference's ``PinotFS``
(pinot-spi/.../filesystem/PinotFS.java + LocalPinotFS, with S3/GCS/HDFS
as plugins): scheme-keyed factories resolve a URI to a filesystem
offering the segment-lifecycle operations the controller needs (copy
dir/file, delete, exists, listFiles, mkdir). Only ``file://`` ships
in-tree — object-store impls register through the plugin registry
(common/plugins.py) exactly like the reference's pinot-file-system
plugins.
"""

from __future__ import annotations

import os
import shutil
from urllib.parse import urlparse


class PinotFS:
    """SPI surface (PinotFS.java subset the controller exercises)."""

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> None:
        """File or directory; dst is replaced."""
        raise NotImplementedError

    def list_files(self, path: str) -> list:
        raise NotImplementedError


class LocalFS(PinotFS):
    """LocalPinotFS analog over the host filesystem."""

    @staticmethod
    def _p(path: str) -> str:
        u = urlparse(path)
        return u.path if u.scheme == "file" else path

    def mkdir(self, path: str) -> None:
        os.makedirs(self._p(path), exist_ok=True)

    def delete(self, path: str) -> None:
        p = self._p(path)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def copy(self, src: str, dst: str) -> None:
        s, d = self._p(src), self._p(dst)
        os.makedirs(os.path.dirname(d) or ".", exist_ok=True)
        if os.path.isdir(s):
            if os.path.exists(d):
                shutil.rmtree(d)
            shutil.copytree(s, d)
        else:
            shutil.copy2(s, d)

    def list_files(self, path: str) -> list:
        p = self._p(path)
        return sorted(os.listdir(p)) if os.path.isdir(p) else []


def create_fs(uri: str) -> PinotFS:
    """Scheme → filesystem via the plugin registry (PinotFSFactory.create)."""
    from pinot_tpu.common.plugins import plugin_registry

    scheme = urlparse(uri).scheme or "file"
    factory = plugin_registry.load("fs", scheme)
    return factory()
