"""Deep-store filesystem SPI.

Equivalent of the reference's ``PinotFS``
(pinot-spi/.../filesystem/PinotFS.java + LocalPinotFS, with S3/GCS/HDFS
as plugins): scheme-keyed factories resolve a URI to a filesystem
offering the segment-lifecycle operations the controller needs (copy
dir/file, delete, exists, listFiles, mkdir). Only ``file://`` ships
in-tree — object-store impls register through the plugin registry
(common/plugins.py) exactly like the reference's pinot-file-system
plugins.
"""

from __future__ import annotations

import os
import shutil
from urllib.parse import urlparse


class PinotFS:
    """SPI surface (PinotFS.java subset the controller exercises)."""

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> None:
        """File or directory; dst is replaced."""
        raise NotImplementedError

    def list_files(self, path: str) -> list:
        raise NotImplementedError


class LocalFS(PinotFS):
    """LocalPinotFS analog over the host filesystem."""

    @staticmethod
    def _p(path: str) -> str:
        u = urlparse(path)
        return u.path if u.scheme == "file" else path

    def mkdir(self, path: str) -> None:
        os.makedirs(self._p(path), exist_ok=True)

    def delete(self, path: str) -> None:
        p = self._p(path)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def copy(self, src: str, dst: str) -> None:
        s, d = self._p(src), self._p(dst)
        os.makedirs(os.path.dirname(d) or ".", exist_ok=True)
        if os.path.isdir(s):
            if os.path.exists(d):
                shutil.rmtree(d)
            shutil.copytree(s, d)
        else:
            shutil.copy2(s, d)

    def list_files(self, path: str) -> list:
        p = self._p(path)
        return sorted(os.listdir(p)) if os.path.isdir(p) else []


class PrefixObjectFS(PinotFS):
    """Shared base for object stores that model segment directories as key
    prefixes (S3/GCS/ABFS-shaped). Subclasses set ``scheme`` and implement
    five primitive hooks; the PinotFS surface (delimiter-safe dir
    matching, replace-on-copy, download/upload/remote-copy branching) is
    written once here.

    Hooks:
      _list(bucket, prefix, limit=None) -> [key]
      _put(local_path, bucket, key)
      _get(bucket, key, local_path)
      _delete_objs(bucket, [key])            # batched where the SDK allows
      _copy_obj(src_bucket, src_key, dst_bucket, dst_key)
    """

    scheme = ""

    def _split(self, uri: str):
        u = urlparse(uri)
        if u.scheme != self.scheme or not u.netloc:
            raise ValueError(f"not a {self.scheme} URI: {uri!r}")
        return u.netloc, u.path.lstrip("/")

    def _dir_keys(self, bucket: str, prefix: str, limit=None) -> list:
        """Keys of the 'directory' at prefix: everything under
        prefix + '/' plus an exact-key object — a bare prefix match would
        also hit same-prefix siblings (seg_1 vs seg_10)."""
        p = prefix.rstrip("/")
        keys = self._list(bucket, p + "/", limit=limit)
        if limit is None or len(keys) < limit:
            exact = self._list(bucket, p, limit=1)
            if exact and exact[0] == p and p not in keys:
                keys.append(p)
        return keys

    def mkdir(self, path: str) -> None:
        pass  # prefixes need no creation

    def delete(self, path: str) -> None:
        bucket, prefix = self._split(path)
        keys = self._dir_keys(bucket, prefix)
        if keys:
            self._delete_objs(bucket, keys)

    def exists(self, path: str) -> bool:
        bucket, prefix = self._split(path)
        return bool(self._dir_keys(bucket, prefix, limit=1))

    def copy(self, src: str, dst: str) -> None:
        pfx = f"{self.scheme}://"
        src_obj = src.startswith(pfx)
        dst_obj = dst.startswith(pfx)
        if not src_obj and dst_obj:  # upload (segment push)
            self.delete(dst)  # PinotFS contract: dst is REPLACED
            bucket, prefix = self._split(dst)
            if os.path.isdir(src):
                for root, _, files in os.walk(src):
                    for f in sorted(files):
                        full = os.path.join(root, f)
                        rel = os.path.relpath(full, src).replace(os.sep, "/")
                        self._put(full, bucket, f"{prefix.rstrip('/')}/{rel}")
            else:
                self._put(src, bucket, prefix)
        elif src_obj and not dst_obj:  # download (server sync)
            bucket, prefix = self._split(src)
            p = prefix.rstrip("/")
            keys = self._dir_keys(bucket, p)
            if not keys:
                raise FileNotFoundError(src)
            for key in keys:
                rel = key[len(p):].lstrip("/")
                local = os.path.join(dst, rel) if rel else dst
                os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
                self._get(bucket, key, local)
        elif src_obj and dst_obj:
            self.delete(dst)  # PinotFS contract: dst is REPLACED
            sb, sp = self._split(src)
            sp = sp.rstrip("/")
            db, dp = self._split(dst)
            for key in self._dir_keys(sb, sp):
                rel = key[len(sp):].lstrip("/")
                self._copy_obj(sb, key, db, f"{dp}/{rel}".rstrip("/"))
        else:
            raise ValueError(
                f"{type(self).__name__}.copy needs at least one "
                f"{self.scheme}:// side")

    def list_files(self, path: str) -> list:
        bucket, prefix = self._split(path)
        pfx = prefix.rstrip("/") + "/" if prefix else ""
        names = set()
        for key in self._list(bucket, pfx):
            rest = key[len(pfx):]
            names.add(rest.split("/", 1)[0])
        return sorted(n for n in names if n)

    # ---- hooks -----------------------------------------------------------
    def _list(self, bucket: str, prefix: str, limit=None) -> list:
        raise NotImplementedError

    def _put(self, local_path: str, bucket: str, key: str) -> None:
        raise NotImplementedError

    def _get(self, bucket: str, key: str, local_path: str) -> None:
        raise NotImplementedError

    def _delete_objs(self, bucket: str, keys: list) -> None:
        raise NotImplementedError

    def _copy_obj(self, src_bucket: str, src_key: str,
                  dst_bucket: str, dst_key: str) -> None:
        raise NotImplementedError


def create_fs(uri: str) -> PinotFS:
    """Scheme → filesystem via the plugin registry (PinotFSFactory.create)."""
    from pinot_tpu.common.plugins import plugin_registry

    scheme = urlparse(uri).scheme or "file"
    factory = plugin_registry.load("fs", scheme)
    return factory()
