"""Partition functions for ingest-time column partitioning.

Equivalent of pinot-segment-spi/.../partition/ (Murmur/Modulo/HashCode/
ByteArray partition functions): maps column values -> partition id so the
broker can prune segments for ``col = literal`` queries
(SinglePartitionColumnSegmentPruner.java). Vectorized over numpy arrays.
"""

from __future__ import annotations

import numpy as np


def _to_bytes_rows(values: np.ndarray) -> list[bytes]:
    out = []
    for v in values:
        if isinstance(v, bytes):
            out.append(v)
        else:
            out.append(str(v).encode("utf-8"))
    return out


def murmur2_32(data: bytes, seed: int = 0x9747B28C) -> int:
    """Murmur2 32-bit, matching kafka/pinot's MurmurPartitionFunction behavior
    closely enough for internal consistency (we only require determinism)."""
    m = 0x5BD1E995
    r = 24
    length = len(data)
    h = (seed ^ length) & 0xFFFFFFFF
    i = 0
    while length - i >= 4:
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * m) & 0xFFFFFFFF
        k ^= k >> r
        k = (k * m) & 0xFFFFFFFF
        h = (h * m) & 0xFFFFFFFF
        h ^= k
        i += 4
    rem = length - i
    if rem >= 3:
        h ^= data[i + 2] << 16
    if rem >= 2:
        h ^= data[i + 1] << 8
    if rem >= 1:
        h ^= data[i]
        h = (h * m) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * m) & 0xFFFFFFFF
    h ^= h >> 15
    return h


def partition_ids(values: np.ndarray, function: str, num_partitions: int) -> np.ndarray:
    """Vectorized value -> partition id."""
    fn = function.lower()
    if fn == "modulo":
        return (np.asarray(values).astype(np.int64) % num_partitions).astype(np.int32)
    if fn in ("murmur", "murmur2"):
        return np.array(
            [murmur2_32(b) % num_partitions for b in _to_bytes_rows(values)], dtype=np.int32
        )
    if fn == "hashcode":
        # Java String.hashCode analog on utf-8 text
        out = np.empty(len(values), dtype=np.int64)
        for i, b in enumerate(_to_bytes_rows(values)):
            h = 0
            for c in b.decode("utf-8", "replace"):
                h = (31 * h + ord(c)) & 0xFFFFFFFF
            out[i] = h if h < 2**31 else h - 2**32
        return (np.abs(out) % num_partitions).astype(np.int32)
    raise ValueError(f"unknown partition function {function!r}")


def partition_of_value(value, function: str, num_partitions: int) -> int:
    return int(partition_ids(np.array([value], dtype=object), function, num_partitions)[0])
