"""JSON index: flattened path/value posting lists for JSON_MATCH.

Equivalent of the reference's JSON index
(pinot-segment-local/.../readers/json/ImmutableJsonIndexReader.java and
creator JsonIndexCreator): every doc's JSON flattens into one or more
*flat rows* — one per combination of array elements — each holding
``path → scalar`` entries under both the exact path (``$.arr[0].k``) and
the wildcard form (``$.arr[*].k``). Predicates inside ``JSON_MATCH``
evaluate in flat-row space, so ``"$.a[*].k1" = 'x' AND "$.a[*].k2" = 'y'``
matches only when one array ELEMENT satisfies both — the reference's
same-flattened-doc semantics.

On disk (``<col>.jsonidx.npz``): sorted (path, value) keys with
concatenated flat-row posting lists, plus existence postings per path and
the flat-row → doc map. Query-time the inner expression string parses with
the normal SQL expression parser and evaluates over the postings; segments
without the index take a flatten-per-doc scan with identical semantics.
"""

from __future__ import annotations

import json
import re
from typing import Optional

import numpy as np

from pinot_tpu.query.context import FilterNode, FilterNodeType, Predicate, PredicateType

_IDX_RE = re.compile(r"\[\d+\]")
MAX_FLAT_ROWS_PER_DOC = 1024  # cartesian-blowup guard


def _scalar_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _rec(node, path: str) -> list:
    if isinstance(node, dict):
        rows = [{}]
        for k, v in node.items():
            sub = _rec(v, f"{path}.{k}")
            if len(rows) * len(sub) > MAX_FLAT_ROWS_PER_DOC:
                sub = sub[: max(1, MAX_FLAT_ROWS_PER_DOC // max(1, len(rows)))]
            rows = [dict(a, **b) for a in rows for b in sub]
        return rows
    if isinstance(node, list):
        rows = []
        for i, v in enumerate(node):
            rows.extend(_rec(v, f"{path}[{i}]"))
            if len(rows) >= MAX_FLAT_ROWS_PER_DOC:
                break
        return rows or [{}]
    if node is None:
        return [{}]  # JSON null == absent path (reference semantics)
    return [{path: _scalar_str(node)}]


def flatten_doc(obj) -> list:
    """Flat rows for one parsed JSON value; always >= 1 row per doc."""
    rows = _rec(obj, "$")
    for r in rows:
        for k in list(r):
            w = _IDX_RE.sub("[*]", k)
            if w != k:
                r.setdefault(w, r[k])
    return rows


def _parse_doc(v) -> object:
    if isinstance(v, (dict, list)):
        return v
    try:
        return json.loads(v)
    except (TypeError, ValueError):
        return None  # malformed JSON indexes as empty (no paths)


# ---------------------------------------------------------------------------
# Build
# ---------------------------------------------------------------------------

def build_json_index(values, out_path: str) -> None:
    """values: iterable of JSON strings (or parsed objects), one per doc."""
    postings: dict = {}  # (path, value_or_None) -> list[flat_row_id]
    row_doc: list = []
    for doc_id, v in enumerate(values):
        for flat in flatten_doc(_parse_doc(v)):
            rid = len(row_doc)
            row_doc.append(doc_id)
            seen_paths = set()
            for path, val in flat.items():
                postings.setdefault((path, val), []).append(rid)
                if path not in seen_paths:
                    seen_paths.add(path)
                    postings.setdefault((path, None), []).append(rid)
    keys = sorted(postings, key=lambda k: (k[0], k[1] is not None, k[1] or ""))
    off = np.zeros(len(keys) + 1, dtype=np.int64)
    rows_concat = np.empty(sum(len(postings[k]) for k in keys), dtype=np.int64)
    pos = 0
    for i, k in enumerate(keys):
        rows = postings[k]
        rows_concat[pos: pos + len(rows)] = rows
        pos += len(rows)
        off[i + 1] = pos
    np.savez(
        out_path,
        paths=np.asarray([k[0] for k in keys], dtype=np.str_),
        vals=np.asarray(["" if k[1] is None else k[1] for k in keys], dtype=np.str_),
        kinds=np.asarray([0 if k[1] is None else 1 for k in keys], dtype=np.uint8),
        off=off,
        rows=rows_concat,
        row_doc=np.asarray(row_doc, dtype=np.int64),
    )


# ---------------------------------------------------------------------------
# Read / match
# ---------------------------------------------------------------------------

class JsonIndexReader:
    def __init__(self, npz_path: str):
        z = np.load(npz_path, allow_pickle=False)
        self._paths = z["paths"]
        self._vals = z["vals"]
        self._kinds = z["kinds"]
        self._off = z["off"]
        self._rows = z["rows"]
        self.row_doc = z["row_doc"]
        self.n_rows = len(self.row_doc)
        self._by_key: dict = {}
        for i in range(len(self._paths)):
            key = (str(self._paths[i]),
                   str(self._vals[i]) if self._kinds[i] else None)
            self._by_key[key] = i

    def _posting(self, path: str, value: Optional[str]) -> np.ndarray:
        i = self._by_key.get((path, value))
        if i is None:
            return np.empty(0, dtype=np.int64)
        return np.asarray(self._rows[self._off[i]: self._off[i + 1]])

    def _value_keys(self, path: str):
        """(value_string, posting) pairs under one path (range scans)."""
        for (p, v), i in self._by_key.items():
            if p == path and v is not None:
                yield v, np.asarray(self._rows[self._off[i]: self._off[i + 1]])

    def match(self, f: FilterNode, n_docs: int) -> np.ndarray:
        """Doc mask for a parsed JSON_MATCH inner filter."""
        rows = _eval_filter(f, _IndexRowSpace(self))
        mask = np.zeros(n_docs, dtype=bool)
        if len(rows):
            mask[self.row_doc[rows]] = True
        return mask


class _IndexRowSpace:
    """Flat-row-space evaluation over the on-disk postings."""

    def __init__(self, reader: JsonIndexReader):
        self.r = reader

    def all_rows(self) -> np.ndarray:
        return np.arange(self.r.n_rows, dtype=np.int64)

    def exists(self, path: str) -> np.ndarray:
        return self.r._posting(path, None)

    def eq(self, path: str, value) -> np.ndarray:
        return self.r._posting(path, _literal_str(value))

    def value_entries(self, path: str):
        return self.r._value_keys(path)

    def rows_of_docs(self, docs: np.ndarray) -> np.ndarray:
        return np.nonzero(np.isin(self.r.row_doc, docs))[0]

    def docs_of_rows(self, rows: np.ndarray) -> np.ndarray:
        return np.unique(self.r.row_doc[rows])

    def all_docs(self) -> np.ndarray:
        return np.unique(self.r.row_doc)


class _ScanRowSpace:
    """Same evaluation over flat rows materialized from raw values at query
    time (segments without the index)."""

    def __init__(self, values):
        self.row_doc_list = []
        self.flat = []
        for doc_id, v in enumerate(values):
            for fr in flatten_doc(_parse_doc(v)):
                self.row_doc_list.append(doc_id)
                self.flat.append(fr)
        self.row_doc = np.asarray(self.row_doc_list, dtype=np.int64)

    def all_rows(self) -> np.ndarray:
        return np.arange(len(self.flat), dtype=np.int64)

    def exists(self, path: str) -> np.ndarray:
        return np.asarray(
            [i for i, fr in enumerate(self.flat) if path in fr], dtype=np.int64)

    def eq(self, path: str, value) -> np.ndarray:
        v = _literal_str(value)
        return np.asarray(
            [i for i, fr in enumerate(self.flat) if fr.get(path) == v],
            dtype=np.int64)

    def value_entries(self, path: str):
        by_val: dict = {}
        for i, fr in enumerate(self.flat):
            v = fr.get(path)
            if v is not None:
                by_val.setdefault(v, []).append(i)
        for v, rows in by_val.items():
            yield v, np.asarray(rows, dtype=np.int64)

    def rows_of_docs(self, docs: np.ndarray) -> np.ndarray:
        return np.nonzero(np.isin(self.row_doc, docs))[0]

    def docs_of_rows(self, rows: np.ndarray) -> np.ndarray:
        return np.unique(self.row_doc[rows])

    def all_docs(self) -> np.ndarray:
        return np.unique(self.row_doc)


def match_scan(values, f: FilterNode, n_docs: int) -> np.ndarray:
    space = _ScanRowSpace(values)
    rows = _eval_filter(f, space)
    mask = np.zeros(n_docs, dtype=bool)
    if len(rows):
        mask[space.row_doc[rows]] = True
    return mask


def _literal_str(v) -> str:
    """Query-literal canonicalization — must stay identical to the
    build-time ``_scalar_str`` or EQ lookups go empty."""
    return _scalar_str(v)


def _try_float(s: str) -> Optional[float]:
    try:
        return float(s)
    except ValueError:
        return None


def _eval_filter(f: FilterNode, space) -> np.ndarray:
    """Flat-row ids matching the filter. AND intersects in flat-row space
    (same-element semantics); NOT complements at DOC level, like the
    reference's exclusive flattened-doc handling."""
    t = f.type
    if t is FilterNodeType.AND:
        rows = _eval_filter(f.children[0], space)
        for c in f.children[1:]:
            rows = np.intersect1d(rows, _eval_filter(c, space),
                                  assume_unique=False)
        return rows
    if t is FilterNodeType.OR:
        rows = _eval_filter(f.children[0], space)
        for c in f.children[1:]:
            rows = np.union1d(rows, _eval_filter(c, space))
        return rows
    if t is FilterNodeType.NOT:
        matched_docs = space.docs_of_rows(_eval_filter(f.children[0], space))
        keep = np.setdiff1d(space.all_docs(), matched_docs)
        return space.rows_of_docs(keep)
    if t is FilterNodeType.CONSTANT_TRUE:
        return space.all_rows()
    if t is FilterNodeType.CONSTANT_FALSE:
        return np.empty(0, dtype=np.int64)
    return _eval_predicate(f.predicate, space)


def _eval_predicate(p: Predicate, space) -> np.ndarray:
    if not p.lhs.is_identifier:
        raise ValueError("JSON_MATCH predicates take a \"$.path\" lhs")
    path = p.lhs.name
    t = p.type
    if t is PredicateType.EQ:
        return space.eq(path, p.value)
    if t is PredicateType.IN:
        rows = np.empty(0, dtype=np.int64)
        for v in p.values:
            rows = np.union1d(rows, space.eq(path, v))
        return rows
    if t is PredicateType.NOT_EQ:
        # path exists with a different value (flat-row level, ref semantics)
        return np.setdiff1d(space.exists(path), space.eq(path, p.value))
    if t is PredicateType.NOT_IN:
        rows = space.exists(path)
        for v in p.values:
            rows = np.setdiff1d(rows, space.eq(path, v))
        return rows
    if t is PredicateType.IS_NOT_NULL:
        return space.exists(path)
    if t is PredicateType.IS_NULL:
        have = space.docs_of_rows(space.exists(path))
        return space.rows_of_docs(np.setdiff1d(space.all_docs(), have))
    if t is PredicateType.RANGE:
        # numeric bounds compare numerically over numeric-looking values;
        # string bounds compare lexicographically (the stored form), the
        # reference's string-range behavior
        lo = None if p.lower is None else _try_float(_literal_str(p.lower))
        hi = None if p.upper is None else _try_float(_literal_str(p.upper))
        numeric = (p.lower is None or lo is not None) and \
            (p.upper is None or hi is not None)
        out = []
        for v, rows in space.value_entries(path):
            if numeric:
                cv = _try_float(v)
                if cv is None:
                    continue
                clo, chi = lo, hi
            else:
                cv = v
                clo = None if p.lower is None else _literal_str(p.lower)
                chi = None if p.upper is None else _literal_str(p.upper)
            if clo is not None and (cv < clo or (cv == clo and not p.lower_inclusive)):
                continue
            if chi is not None and (cv > chi or (cv == chi and not p.upper_inclusive)):
                continue
            out.append(rows)
        if not out:
            return np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate(out))
    raise ValueError(f"unsupported predicate {t} inside JSON_MATCH")


def parse_match_expression(expr: str) -> FilterNode:
    """'"$.a" = ''x'' AND ...' -> FilterNode, via the SQL expression parser."""
    from pinot_tpu.sql.compiler import _to_filter
    from pinot_tpu.sql.parser import Parser

    return _to_filter(Parser(expr).parse_expr())
