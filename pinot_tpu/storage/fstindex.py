"""Regex-acceleration index for LIKE / REGEXP_LIKE on dictionary columns.

The reference's FST index (pinot-segment-local/.../readers/
LuceneFSTIndexReader.java:1 + utils/nativefst/) maps regex patterns to
matching dictionary ids so REGEXP_LIKE avoids evaluating the pattern
against every dictionary entry. A Lucene FST is a pointer-chasing
automaton — the wrong shape for this build. The same CAPABILITY here is a
**trigram posting index** over dictionary values (the pg_trgm design):

- build: every value's 3-grams → sorted posting lists of dict ids;
- query: extract the literal substrings a pattern REQUIRES (conservative
  regex analysis — alternation/optional groups contribute nothing),
  intersect their trigrams' posting lists, and regex-verify only the
  surviving candidates.

O(C) regex evaluations become O(|candidates|); correctness never depends
on the analysis because survivors are always re-verified with the real
pattern, and a pattern with no usable literals simply scans all entries
(the pre-index behavior).
"""

from __future__ import annotations

import os
import re

import numpy as np

IDS_FILE = "{col}.fst.ids.npy"
OFFS_FILE = "{col}.fst.off.npy"
GRAMS_FILE = "{col}.fst.grams.npy"

_QUANTS = "*?{"


def _skip_quant(pattern: str, i: int):
    """i points at a quantifier char; return the index PAST it (handles the
    {m,n} body), or None on unbalanced braces."""
    if pattern[i] == "{":
        j = pattern.find("}", i)
        return None if j < 0 else j + 1
    return i + 1


def required_literals(pattern: str) -> list:
    """Literal substrings every match of ``pattern`` must contain.
    Conservative: returns [] whenever the analysis is unsure (top-level
    alternation, unbalanced syntax, ...) — the caller then scans."""
    literals: list[str] = []
    cur: list[str] = []
    # group bookkeeping: (index into `literals` at group start, tainted)
    stack: list = []
    tainted_depth = 0  # >0: inside a group that contains an alternation

    def flush():
        if cur and tainted_depth == 0:
            literals.append("".join(cur))
        cur.clear()

    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "\\":
            if i + 1 >= n:
                return []
            nxt = pattern[i + 1]
            if nxt.isalnum():  # \d \w \b ... character classes/anchors
                flush()
            else:  # escaped metachar is a literal char
                cur.append(nxt)
            i += 2
            # an escaped char followed by a quantifier is optional/repeated
            if i < n and pattern[i] in _QUANTS:
                if cur:
                    cur.pop()
                flush()
                nxt_i = _skip_quant(pattern, i)
                if nxt_i is None:
                    return []
                i = nxt_i
            continue
        if c == "|":
            if not stack:
                return []  # top-level alternation: nothing is required
            # group content is alternated: drop its literals, taint it
            start, _ = stack[-1]
            del literals[start:]
            stack[-1] = (start, True)
            tainted_depth = sum(1 for _, t in stack if t)
            cur.clear()
            i += 1
            continue
        if c == "(":
            flush()
            if i + 1 < n and pattern[i + 1] == "?":
                # (?: / (?= / (?! / (?P<...>: bail conservatively — the
                # verify pass keeps correctness, this only costs narrowing
                return []
            stack.append((len(literals), False))
            i += 1
            continue
        if c == ")":
            flush()
            if not stack:
                return []
            start, was_tainted = stack.pop()
            tainted_depth = sum(1 for _, t in stack if t)
            # a quantified group is optional/repeated: its literals are
            # not required ('{m,n}' bodies must be skipped whole — '(x){2}'
            # once leaked '2}' into a literal and false-negatived queries)
            if i + 1 < n and pattern[i + 1] in _QUANTS:
                del literals[start:]
                nxt_i = _skip_quant(pattern, i + 1)
                if nxt_i is None:
                    return []
                i = nxt_i
                continue
            i += 1
            continue
        if c == "[":
            flush()
            j = i + 1
            if j < n and pattern[j] == "^":
                j += 1
            if j < n and pattern[j] == "]":
                j += 1
            while j < n and pattern[j] != "]":
                j += 2 if pattern[j] == "\\" else 1
            if j >= n:
                return []
            i = j + 1
            if i < n and pattern[i] in _QUANTS:
                i = _skip_quant(pattern, i)  # class optional/repeated
                if i is None:
                    return []
            continue
        if c in ".^$":
            flush()
            i += 1
            continue
        if c == "+":
            # previous unit required at least once, but adjacency to what
            # FOLLOWS breaks (ab+c matches 'abbc'): keep the literal up to
            # and including the char, then start fresh
            flush()
            i += 1
            continue
        if c in _QUANTS:
            # previous char optional ({} treated conservatively)
            if cur:
                cur.pop()
            flush()
            i = _skip_quant(pattern, i)
            if i is None:
                return []
            continue
        cur.append(c)
        i += 1
    if stack:
        return []
    flush()
    return [l for l in literals if len(l) >= 3]


def _grams(s: str):
    return {s[i: i + 3] for i in range(len(s) - 2)}


class TrigramIndex:
    """Sorted posting lists of dict ids per trigram."""

    def __init__(self, grams: np.ndarray, ids: np.ndarray, offs: np.ndarray):
        self.grams = grams  # sorted (G,) U3 array
        self.ids = ids      # concatenated int32 postings
        self.offs = offs    # (G+1,) int64

    @classmethod
    def build(cls, values) -> "TrigramIndex":
        posting: dict = {}
        for i, v in enumerate(np.asarray(values)):
            for g in _grams(str(v)):
                posting.setdefault(g, []).append(i)
        grams = np.asarray(sorted(posting), dtype=np.str_)
        offs = np.zeros(len(grams) + 1, dtype=np.int64)
        chunks = []
        for j, g in enumerate(grams):
            chunks.append(np.asarray(posting[g], dtype=np.int32))
            offs[j + 1] = offs[j] + len(chunks[-1])
        ids = np.concatenate(chunks) if chunks else np.empty(0, dtype=np.int32)
        return cls(grams, ids, offs)

    def save(self, dir_path: str, col: str) -> None:
        np.save(os.path.join(dir_path, GRAMS_FILE.format(col=col)),
                self.grams, allow_pickle=False)
        np.save(os.path.join(dir_path, IDS_FILE.format(col=col)),
                self.ids, allow_pickle=False)
        np.save(os.path.join(dir_path, OFFS_FILE.format(col=col)),
                self.offs, allow_pickle=False)

    @classmethod
    def load(cls, dir_path: str, col: str):
        gp = os.path.join(dir_path, GRAMS_FILE.format(col=col))
        if not os.path.exists(gp):
            return None
        return cls(
            np.load(gp, allow_pickle=False),
            np.load(os.path.join(dir_path, IDS_FILE.format(col=col)),
                    allow_pickle=False, mmap_mode="r"),
            np.load(os.path.join(dir_path, OFFS_FILE.format(col=col)),
                    allow_pickle=False),
        )

    def _postings(self, gram: str):
        j = np.searchsorted(self.grams, gram)
        if j >= len(self.grams) or self.grams[j] != gram:
            return np.empty(0, dtype=np.int32)
        return np.asarray(self.ids[self.offs[j]: self.offs[j + 1]])

    def candidates(self, pattern: str, n_values: int):
        """Sorted candidate dict ids, or None → no narrowing possible."""
        lits = required_literals(pattern)
        if not lits:
            return None
        cand = None
        for lit in lits:
            for g in _grams(lit):
                p = self._postings(g)
                cand = p if cand is None else \
                    cand[np.isin(cand, p, assume_unique=True)]
                if len(cand) == 0:
                    return cand
        return cand
