"""Immutable segment: on-disk format + host-side reader.

Equivalent of the reference's segment directory format + ``ImmutableSegmentImpl``
(pinot-segment-local/.../indexsegment/immutable/ImmutableSegmentImpl.java and
V1Constants.java:25-53), re-designed for a TPU loader:

- ``metadata.json``         segment + per-column metadata (replaces
                            metadata.properties + index_map)
- ``<col>.fwd.npy``         forward index: int32 dict ids (DICT encoding) or
                            raw typed values (RAW encoding); mmap-able dense
                            arrays instead of bit-packed buffers so the device
                            upload is a straight memcpy. (A bit-packed variant
                            ``<col>.fwdpacked.bin`` is produced by the native
                            C++ packer when enabled.)
- ``<col>.mvoff.npy``       multi-value row offsets (n_docs+1) when the column
                            is multi-value; fwd then holds the flattened values
- ``<col>.dict.npy``        sorted dictionary values
- ``<col>.inv.docs.npy`` /
  ``<col>.inv.off.npy``     inverted index: concatenated sorted doc-id lists
                            per dict id + offsets (card+1) — the dense analog
                            of one RoaringBitmap per dict id
                            (BitmapInvertedIndexReader.java)
- ``<col>.bloom.npy``       bloom filter bitset (host-side pruning)
- ``startree/``             star-tree pre-aggregated segment (own metadata)

All arrays load with ``np.load(mmap_mode='r')`` — the host never copies a
column until it is shipped to HBM.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import numpy as np

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.storage.dictionary import Dictionary

SEGMENT_FORMAT_VERSION = 1

METADATA_FILE = "metadata.json"
CREATION_META_FILE = "creation.meta.json"

# Zone-map granularity (rows per zone-map block). A format constant shared by
# the segment creator (``<col>.zmap.npy``), the chunklet sealer, and the
# device batch loader (engine/params.py) — the device block-skip kernel
# (ops/blockskip.py) prunes at exactly this granularity, so the on-disk
# blocks line up 1:1 with the (S, n_blocks) device zone arrays.
ZONE_BLOCK_ROWS = 4096


def build_zone_map(values: np.ndarray, block_rows: int = ZONE_BLOCK_ROWS) -> np.ndarray:
    """(2, n_blocks) per-block [min, max] over ``values`` (dict ids for DICT
    columns, raw values for RAW) — the columnar analog of the reference's
    per-chunk min/max metadata that ColumnValueSegmentPruner consults, kept
    at a granularity the device can gather by."""
    n = len(values)
    if n == 0:
        return np.zeros((2, 0), dtype=np.asarray(values).dtype)
    starts = np.arange(0, n, block_rows, dtype=np.int64)
    lo = np.minimum.reduceat(values, starts)
    hi = np.maximum.reduceat(values, starts)
    return np.stack([lo, hi])


class Encoding:
    DICT = "DICT"
    RAW = "RAW"


@dataclasses.dataclass
class ColumnMetadata:
    name: str
    data_type: DataType
    encoding: str
    cardinality: int
    min_value: object
    max_value: object
    is_sorted: bool
    single_value: bool = True
    max_mv_entries: int = 1
    has_dictionary: bool = False
    has_inverted: bool = False
    has_range: bool = False
    has_bloom: bool = False
    has_json_index: bool = False
    has_text_index: bool = False
    has_fst_index: bool = False
    has_h3_index: bool = False
    has_null_vector: bool = False
    packed_bits: Optional[int] = None  # bit-packed fwd index width, else None
    compression: Optional[str] = None  # raw fwd chunk codec (zlib|zstd|lz4)
    total_number_of_entries: int = 0  # == n_docs for SV, total MV entries for MV
    partition_function: Optional[str] = None
    num_partitions: Optional[int] = None
    partitions: Optional[list[int]] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["data_type"] = self.data_type.value
        for k in ("min_value", "max_value"):
            v = d[k]
            if isinstance(v, (np.generic,)):
                d[k] = v.item()
            if isinstance(v, bytes):
                d[k] = v.hex()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ColumnMetadata":
        d = dict(d)
        d["data_type"] = DataType(d["data_type"])
        return cls(**d)


@dataclasses.dataclass
class SegmentMetadata:
    segment_name: str
    table_name: str
    n_docs: int
    columns: dict[str, ColumnMetadata]
    time_column: Optional[str] = None
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    format_version: int = SEGMENT_FORMAT_VERSION
    crc: Optional[str] = None

    def to_json(self) -> dict:
        return {
            "segment_name": self.segment_name,
            "table_name": self.table_name,
            "n_docs": self.n_docs,
            "time_column": self.time_column,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "format_version": self.format_version,
            "crc": self.crc,
            "columns": {k: v.to_json() for k, v in self.columns.items()},
        }

    @classmethod
    def from_json(cls, d: dict) -> "SegmentMetadata":
        d = dict(d)
        d["columns"] = {k: ColumnMetadata.from_json(v) for k, v in d["columns"].items()}
        return cls(**d)


class ImmutableSegment:
    """Host-side handle on a sealed segment directory (mmap-backed).

    The query path never reads values through this object doc-by-doc; it
    either ships whole columns to the device (``DeviceSegment``) or runs
    vectorized numpy over the mmap for host-only paths (pruning, string
    materialization) — the moral replacement for ForwardIndexReader's
    batch ``readDictIds``/``readValuesSV`` (ForwardIndexReader.java:85,114).
    """

    # upsert: in-memory validDocIds mask managed by the upsert metadata
    # manager (realtime/upsert.py); None for non-upsert tables
    valid_docs_mask = None

    # plane-load observation seam (ISSUE 12): called with the plane file
    # name the FIRST time it is actually mapped/decoded. The warm tier's
    # LazySegmentView (server/tiering.py) counts through it to assert the
    # mapFile contract — a query touching 2 of 20 columns maps only those
    # planes. None (the default) costs one attribute read per cold load.
    plane_load_hook = None

    def __init__(self, segment_dir: str):
        self.dir = segment_dir
        with open(os.path.join(segment_dir, METADATA_FILE)) as f:
            self.metadata = SegmentMetadata.from_json(json.load(f))
        self._dict_cache: dict[str, Optional[Dictionary]] = {}
        self._fwd_cache: dict[str, np.ndarray] = {}
        self._json_cache: dict = {}
        self._text_cache: dict = {}

    # ---- identity -------------------------------------------------------
    @property
    def name(self) -> str:
        return self.metadata.segment_name

    @property
    def n_docs(self) -> int:
        return self.metadata.n_docs

    def column_names(self) -> list[str]:
        return list(self.metadata.columns)

    def column_metadata(self, col: str) -> ColumnMetadata:
        return self.metadata.columns[col]

    def _path(self, fname: str) -> str:
        return os.path.join(self.dir, fname)

    def _note_plane(self, fname: str) -> None:
        h = self.plane_load_hook
        if h is not None:
            h(fname)

    # ---- index readers --------------------------------------------------
    def dictionary(self, col: str) -> Optional[Dictionary]:
        if col not in self._dict_cache:
            meta = self.column_metadata(col)
            if meta.has_dictionary:
                self._note_plane(f"{col}.dict.npy")
                self._dict_cache[col] = Dictionary.load(self._path(f"{col}.dict.npy"))
            else:
                self._dict_cache[col] = None
        return self._dict_cache[col]

    def forward(self, col: str) -> np.ndarray:
        """Dict ids (int32) for DICT columns, raw values for RAW columns.
        Bit-packed columns decode through the native codec
        (FixedBitSVForwardIndexReader analog) into an in-memory int32
        array; plain columns stay mmap'd."""
        if col not in self._fwd_cache:
            meta = self.column_metadata(col)
            if meta.compression is not None:
                from pinot_tpu import native

                self._note_plane(f"{col}.fwdz.bin")
                blob = np.fromfile(self._path(f"{col}.fwdz.bin"),
                                   dtype=np.uint8)
                offs = np.load(self._path(f"{col}.fwdz.off.npy"),
                               allow_pickle=False)
                n = (self.n_docs if meta.single_value
                     else meta.total_number_of_entries)
                dtype = np.dtype(meta.data_type.np_dtype)
                raw = native.decompress_chunks(blob, offs, n * dtype.itemsize,
                                               codec=meta.compression)
                self._fwd_cache[col] = raw.view(dtype)
            elif meta.packed_bits is not None:
                from pinot_tpu import native

                self._note_plane(f"{col}.fwdpacked.bin")
                buf = np.fromfile(self._path(f"{col}.fwdpacked.bin"),
                                  dtype=np.uint8)
                n = (self.n_docs if meta.single_value
                     else meta.total_number_of_entries)
                need = native.packed_size(n, meta.packed_bits)
                if len(buf) < need:
                    # the native decoder trusts its length args — a short
                    # buffer must fail loudly, not read past the heap
                    raise ValueError(
                        f"{col}.fwdpacked.bin truncated: {len(buf)} bytes, "
                        f"need {need} for {n} x {meta.packed_bits} bits"
                    )
                self._fwd_cache[col] = native.unpack(buf, n, meta.packed_bits)
            else:
                self._note_plane(f"{col}.fwd.npy")
                self._fwd_cache[col] = np.load(
                    self._path(f"{col}.fwd.npy"), mmap_mode="r",
                    allow_pickle=False,
                )
        return self._fwd_cache[col]

    def mv_offsets(self, col: str) -> Optional[np.ndarray]:
        if self.column_metadata(col).single_value:
            return None
        self._note_plane(f"{col}.mvoff.npy")
        return np.load(self._path(f"{col}.mvoff.npy"), mmap_mode="r", allow_pickle=False)

    def inverted(self, col: str) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """(concat_sorted_doc_ids, offsets[card+1]) or None."""
        if not self.column_metadata(col).has_inverted:
            return None
        self._note_plane(f"{col}.inv.docs.npy")
        docs = np.load(self._path(f"{col}.inv.docs.npy"), mmap_mode="r", allow_pickle=False)
        off = np.load(self._path(f"{col}.inv.off.npy"), mmap_mode="r", allow_pickle=False)
        return docs, off

    def bloom(self, col: str) -> Optional[np.ndarray]:
        if not self.column_metadata(col).has_bloom:
            return None
        self._note_plane(f"{col}.bloom.npy")
        return np.load(self._path(f"{col}.bloom.npy"), mmap_mode="r", allow_pickle=False)

    def zone_map(self, col: str) -> Optional[np.ndarray]:
        """(2, n_blocks) per-ZONE_BLOCK_ROWS-block [min, max] over the
        forward index (LOCAL dict ids for DICT columns, raw values
        otherwise), or None for segments built before the format carried
        zone maps (the batch loader then recomputes from the column
        block)."""
        path = self._path(f"{col}.zmap.npy")
        if not os.path.isfile(path):
            return None
        self._note_plane(f"{col}.zmap.npy")
        return np.load(path, mmap_mode="r", allow_pickle=False)

    def range_index(self, col: str) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """(doc_ids_in_value_order, sorted_values) for a RAW range-indexed
        column (RangeIndexReaderImpl analog), or None."""
        meta = self.column_metadata(col)
        if not meta.has_range or meta.encoding == Encoding.DICT:
            return None
        docs_path = self._path(f"{col}.range.docs.npy")
        if not os.path.isfile(docs_path):
            return None
        self._note_plane(f"{col}.range.docs.npy")
        docs = np.load(docs_path, mmap_mode="r", allow_pickle=False)
        vals = np.load(self._path(f"{col}.range.vals.npy"), mmap_mode="r",
                       allow_pickle=False)
        return docs, vals

    def json_index(self, col: str):
        """JSON index reader (ImmutableJsonIndexReader analog), or None."""
        if col not in self._json_cache:
            if not self.column_metadata(col).has_json_index:
                self._json_cache[col] = None
            else:
                from pinot_tpu.storage.jsonindex import JsonIndexReader

                self._json_cache[col] = JsonIndexReader(
                    self._path(f"{col}.jsonidx.npz"))
        return self._json_cache[col]

    def text_index(self, col: str):
        """Text index reader (LuceneTextIndexReader analog), or None."""
        if col not in self._text_cache:
            if not self.column_metadata(col).has_text_index:
                self._text_cache[col] = None
            else:
                from pinot_tpu.storage.textindex import TextIndexReader

                self._text_cache[col] = TextIndexReader(
                    self._path(f"{col}.textidx.npz"))
        return self._text_cache[col]

    def fst_index(self, col: str):
        """Trigram regex-acceleration index (LuceneFSTIndexReader role), or
        None."""
        if not hasattr(self, "_fst_cache"):
            self._fst_cache = {}
        if col not in self._fst_cache:
            if not getattr(self.column_metadata(col), "has_fst_index", False):
                self._fst_cache[col] = None
            else:
                from pinot_tpu.storage.fstindex import TrigramIndex

                self._fst_cache[col] = TrigramIndex.load(self.dir, col)
        return self._fst_cache[col]

    def geo_index(self, col: str):
        """Grid-cell geospatial index (ImmutableH3IndexReader role), or
        None."""
        if not hasattr(self, "_geo_cache"):
            self._geo_cache = {}
        if col not in self._geo_cache:
            if not getattr(self.column_metadata(col), "has_h3_index", False):
                self._geo_cache[col] = None
            else:
                from pinot_tpu.storage.geoindex import GeoGridIndex

                self._geo_cache[col] = GeoGridIndex.load(self.dir, col)
        return self._geo_cache[col]

    def null_vector(self, col: str) -> Optional[np.ndarray]:
        """Per-doc null bitmap, or None when the column has no nulls
        (NullValueVectorReader analog; absent file == empty bitmap)."""
        if not self.column_metadata(col).has_null_vector:
            return None
        self._note_plane(f"{col}.nullvec.npy")
        return np.load(self._path(f"{col}.nullvec.npy"), mmap_mode="r",
                       allow_pickle=False)

    # ---- raw value access (host-side materialization) -------------------
    def values(self, col: str) -> np.ndarray:
        """Decoded raw values for the whole column (host path only).
        Multi-value columns return an object array of per-doc value arrays
        (ForwardIndexReader.java:99 getDictIdMV analog)."""
        meta = self.column_metadata(col)
        flat = self.flat_values(col)
        if meta.single_value:
            return flat
        off = np.asarray(self.mv_offsets(col))
        out = np.empty(self.n_docs, dtype=object)
        for i in range(self.n_docs):
            out[i] = flat[off[i]: off[i + 1]]
        return out

    def flat_values(self, col: str) -> np.ndarray:
        """Decoded values in entry order: (n_docs,) for SV, (total_entries,)
        for MV (pair with ``mv_offsets``). The vectorized MV access path —
        ``values()``'s per-doc object array is for row materialization only."""
        meta = self.column_metadata(col)
        fwd = self.forward(col)
        if meta.encoding == Encoding.DICT:
            return self.dictionary(col).take(np.asarray(fwd))
        return np.asarray(fwd)

    def row_value(self, col: str, doc_id: int):
        """One doc's decoded value, or None when the doc is null there —
        O(1) via the cached forward index + dictionary, used by the
        partial-upsert previous-version read."""
        nv = self.null_vector(col)
        if nv is not None and doc_id < len(nv) and nv[doc_id]:
            return None
        meta = self.column_metadata(col)
        fwd = self.forward(col)
        if meta.single_value:
            v = fwd[doc_id]
            if meta.encoding == Encoding.DICT:
                v = self.dictionary(col).values[int(v)]
        else:
            off = np.asarray(self.mv_offsets(col))
            ent = np.asarray(fwd[off[doc_id]: off[doc_id + 1]])
            if meta.encoding == Encoding.DICT:
                ent = self.dictionary(col).take(ent)
            return ent.tolist()
        return v.item() if isinstance(v, np.generic) else v

    def has_star_tree(self) -> bool:
        return os.path.isdir(self._path("startree"))


def write_creation_meta(segment_dir: str) -> None:
    with open(os.path.join(segment_dir, CREATION_META_FILE), "w") as f:
        json.dump(
            {"creation_time_ms": int(time.time() * 1000), "version": SEGMENT_FORMAT_VERSION}, f
        )
