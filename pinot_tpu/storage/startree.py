"""Star-tree pre-aggregation index, re-designed TPU-first.

Reference (pinot-segment-local/.../startree/v2/builder/BaseSingleTreeBuilder,
pinot-segment-spi/.../index/startree/StarTreeV2.java): sort by a dimension
split order, build an on-disk tree whose star-nodes pre-aggregate doc ranges;
queries traverse the tree level by level (StarTreeFilterOperator.java:53-87).

Pointer-chasing tree traversal is the wrong shape for a TPU. The equivalent
capability here is a **materialized aggregate segment**: docs grouped by the
full split-order dimension set, with one pre-aggregated metric column per
function-column pair (``sum__revenue``, ``count__star``, ...), stored as a
normal child segment under ``<segment>/startree/st<i>/``. A fitting query
(engine/startree_exec.py — StarTreeUtils.isFitForStarTree analog) executes
against this segment through the SAME device pipeline, re-aggregating the
pre-aggregated rows: filters/group-bys on split dimensions remain exact
because every split dimension is carried through, and the dense global-id
re-aggregation that replaces tree traversal is exactly what the hardware is
good at. Work drops from O(rows) to O(distinct dimension combinations) — the
same asymptotic win the reference's tree gives, without star-node plumbing.

max_leaf_records guards materialization bloat: if the cube has more groups
than rows/2 the index is skipped (pre-aggregation would not pay).
"""

from __future__ import annotations

import json
import os

import numpy as np

STARTREE_DIR = "startree"
META_FILE = "startree_meta.json"

# function-column pair name separator (reference: AggregationFunctionColumnPair)
SEP = "__"

SUPPORTED_FUNCTIONS = {"sum", "count", "min", "max", "distinctcounthll",
                       "percentiletdigest", "distinctcountbitmap",
                       "percentileest", "sumprecision"}


def parse_pair(pair: str):
    """'SUM__revenue' → ('sum', 'revenue'); 'COUNT__*' → ('count', '*')."""
    fn, col = pair.split(SEP, 1)
    return fn.lower(), col


def pair_column(fn: str, col: str) -> str:
    return f"{fn.lower()}{SEP}{'star' if col == '*' else col}"


def build_star_trees(segment, star_tree_configs) -> None:
    """Build all configured star-tree aggregate segments for a sealed
    segment (SegmentIndexCreationDriverImpl.java:290,316 build step)."""
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.engine.host import factorize_multi
    from pinot_tpu.storage.creator import build_segment

    for i, cfg in enumerate(star_tree_configs):
        dims = list(cfg.dimensions_split_order)
        pairs = [parse_pair(p) for p in cfg.function_column_pairs]
        for fn, col in pairs:
            if fn not in SUPPORTED_FUNCTIONS:
                raise ValueError(f"star-tree function {fn} unsupported")

        dim_values = [np.asarray(segment.values(d)) for d in dims]
        keys, ginv = factorize_multi(dim_values)
        n_groups = len(keys[0])
        if n_groups > max(1, segment.n_docs // 2):
            continue  # cube nearly as big as the data: not worth it

        out_cols: dict = {d: k for d, k in zip(dims, keys)}
        dim_specs = []
        metric_specs = []
        for d in dims:
            meta = segment.column_metadata(d)
            dim_specs.append((d, meta.data_type))
        hll_log2m = None
        tdigest_compression = None
        percentileest_compression = None
        for fn, col in pairs:
            name = pair_column(fn, col)
            if fn == "count":
                acc = np.zeros(n_groups, dtype=np.int64)
                np.add.at(acc, ginv, 1)
                metric_specs.append((name, DataType.LONG))
            elif fn == "distinctcounthll":
                # sketch pre-aggregation (DistinctCountHLLValueAggregator):
                # one int8 register plane per cube row, stored as a
                # fixed-width BYTES metric; queries re-merge planes by max
                # through the HLLMERGE rewrite (engine/startree_exec.py).
                # Same value hashing as the scan path (ops/hll.registers_np)
                # so cube and scan estimates are bit-identical.
                from pinot_tpu.ops import hll as hll_ops

                hll_log2m = hll_ops.DEFAULT_LOG2M
                regs = hll_ops.registers_np(
                    np.asarray(segment.values(col)), ginv, n_groups,
                    hll_log2m,
                )
                m = 1 << hll_log2m
                acc = np.ascontiguousarray(
                    regs.astype(np.uint8)).view(f"S{m}").reshape(n_groups)
                metric_specs.append((name, DataType.BYTES))
            elif fn == "distinctcountbitmap":
                # exact distinct-set pre-aggregation
                # (DistinctCountBitmapValueAggregator.java:1): one
                # serialized VALUE set per cube row (values, not dict ids —
                # planes in local id space could not merge across
                # segments), re-merged at query time by BITMAPMERGE
                from pinot_tpu.engine.aggspec import set_to_bytes

                v = np.asarray(segment.values(col))
                per_group = [set() for _ in range(n_groups)]
                for g, x in zip(ginv.tolist(), v.tolist()):
                    per_group[g].add(x)
                blobs = [set_to_bytes(s) for s in per_group]
                width = max((len(b) for b in blobs), default=2)
                acc = np.asarray(
                    [b.ljust(width, b"\x00") for b in blobs],
                    dtype=f"S{width}")
                metric_specs.append((name, DataType.BYTES))
            elif fn == "sumprecision":
                # exact arbitrary-precision partial sums
                # (SumPrecisionValueAggregator.java:1): one decimal string
                # per cube row, re-summed by SUMPRECISIONMERGE
                from pinot_tpu.engine.aggspec import SumPrecisionSpec

                v = np.asarray(segment.values(col))
                sums = [0] * n_groups
                for g, x in zip(ginv.tolist(), v.tolist()):
                    sums[g] = sums[g] + SumPrecisionSpec._exact(x)
                strs = [str(s).encode("ascii") for s in sums]
                width = max((len(s) for s in strs), default=1)
                acc = np.asarray(
                    [s.ljust(width, b"\x00") for s in strs], dtype=f"S{width}")
                metric_specs.append((name, DataType.BYTES))
            elif fn in ("percentiletdigest", "percentileest"):
                # digest pre-aggregation (PercentileTDigestValueAggregator):
                # one serialized t-digest per cube row, re-merged at query
                # time by TDIGESTMERGE. Pre-agg digests are approximate
                # like the reference's — cube and scan answers agree within
                # the digest's rank-error bound, not bit-exactly.
                from pinot_tpu.ops import quantile_digest as qd

                if fn == "percentiletdigest":
                    tdigest_compression = float(cfg.tdigest_compression)
                    if tdigest_compression <= 0:
                        raise ValueError(
                            f"tdigest_compression must be > 0, got "
                            f"{cfg.tdigest_compression}")
                    compression = tdigest_compression
                else:
                    # PERCENTILEEST pair: the PERCENTILE/PERCENTILEEST
                    # family's default digest resolution
                    # (PercentileEstValueAggregator's QuantileDigest role)
                    percentileest_compression = float(qd.DEFAULT_COMPRESSION)
                    compression = percentileest_compression
                v = np.asarray(segment.values(col), dtype=np.float64)
                per_group = {}
                if len(v):
                    order = np.argsort(ginv, kind="stable")
                    gs = np.asarray(ginv)[order]
                    vs = v[order]
                    bounds = np.flatnonzero(np.diff(gs)) + 1
                    starts = np.concatenate([[0], bounds])
                    ends = np.concatenate([bounds, [len(gs)]])
                    for s, e in zip(starts, ends):
                        m, w = qd.add_values([], [], vs[s:e], compression)
                        per_group[int(gs[s])] = qd.digest_to_bytes(m, w)
                empty = qd.digest_to_bytes([], [])
                blobs = [per_group.get(g, empty) for g in range(n_groups)]
                width = max((len(b) for b in blobs), default=len(empty))
                acc = np.asarray(
                    [b.ljust(width, b"\x00") for b in blobs],
                    dtype=f"S{width}")
                metric_specs.append((name, DataType.BYTES))
            else:
                v = np.asarray(segment.values(col), dtype=np.float64)
                if fn == "sum":
                    acc = np.zeros(n_groups)
                    np.add.at(acc, ginv, v)
                elif fn == "min":
                    acc = np.full(n_groups, np.inf)
                    np.minimum.at(acc, ginv, v)
                else:
                    acc = np.full(n_groups, -np.inf)
                    np.maximum.at(acc, ginv, v)
                metric_specs.append((name, DataType.DOUBLE))
            out_cols[name] = acc

        st_schema = Schema.build(
            name=f"{segment.name}_st{i}",
            dimensions=dim_specs,
            metrics=metric_specs,
        )
        out_dir = os.path.join(segment.dir, STARTREE_DIR, f"st{i}")
        build_segment(
            st_schema, out_cols, out_dir,
            TableConfig(table_name=st_schema.name), f"{segment.name}_st{i}",
        )
        with open(os.path.join(out_dir, META_FILE), "w") as f:
            json.dump(
                {
                    "dimensions_split_order": dims,
                    "function_column_pairs": list(cfg.function_column_pairs),
                    "max_leaf_records": cfg.max_leaf_records,
                    "hll_log2m": hll_log2m,
                    "tdigest_compression": tdigest_compression,
                    "percentileest_compression": percentileest_compression,
                },
                f,
            )


def load_star_trees(segment) -> list:
    """[(metadata dict, ImmutableSegment)] for a sealed segment."""
    from pinot_tpu.storage.segment import ImmutableSegment

    root = os.path.join(segment.dir, STARTREE_DIR)
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        meta_path = os.path.join(d, META_FILE)
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            out.append((meta, ImmutableSegment(d)))
    return out
