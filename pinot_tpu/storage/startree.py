"""Star-tree pre-aggregation index (placeholder until the index milestone).

Target design (reference: pinot-segment-local/.../startree/v2/builder/
BaseSingleTreeBuilder.java + StarTreeV2): sort docs by the dimension split
order, build a tree whose nodes pre-aggregate doc ranges, materialize
star-nodes for "dimension unconstrained" traversal, and store the
pre-aggregated docs as a child segment under ``<segment>/startree/`` so the
normal device pipeline can scan it.
"""

from __future__ import annotations


def build_star_trees(segment, star_tree_configs) -> None:
    raise NotImplementedError(
        "star-tree index build is not implemented yet; remove star_tree_configs "
        "from IndexingConfig or wait for the star-tree milestone"
    )
