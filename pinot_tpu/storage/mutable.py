"""Mutable (consuming) segment: row-at-a-time indexing, immediately queryable.

Equivalent of the reference's ``MutableSegmentImpl``
(pinot-segment-local/.../indexsegment/mutable/MutableSegmentImpl.java):
single-writer / multi-reader via a volatile doc counter — readers snapshot
``n_docs`` once and never see a partially-written row. Strings are
dict-encoded with an *insertion-ordered* mutable dictionary (ids are arrival
ranks, not sort ranks — same as the reference's mutable dictionaries), so
consuming segments execute on the host scan path; sealing re-encodes into
sorted dictionaries via the immutable segment creator
(realtime/converter: RealtimeSegmentConverter.java analog).

TPU stance (SURVEY.md §7 hard parts): the consuming tail is the slow path by
design — it stays on host numpy until sealed to HBM blocks.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from pinot_tpu.common.datatypes import DataType, FieldRole
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.storage.segment import ColumnMetadata, Encoding, SegmentMetadata

_INITIAL_CAPACITY = 4096


class MutableColumn:
    def __init__(self, spec):
        self.spec = spec
        self.single_value = spec.single_value
        self.dict_encoded = spec.data_type.is_string_like and spec.single_value
        if not spec.single_value:
            # MV: per-row value arrays in a grow-only list (host scan path;
            # sealing re-encodes through the creator's flatten+offsets pass)
            self._rows: list = []
            self.total_entries = 0
        elif self.dict_encoded:
            self._dict: dict = {}
            self._dict_values: list = []
            self._data = np.empty(_INITIAL_CAPACITY, dtype=np.int32)
        else:
            self._data = np.empty(_INITIAL_CAPACITY, dtype=spec.data_type.np_dtype)
        self.min_value = None
        self.max_value = None
        self.null_docs: list = []  # grow-only; readers slice to snapshot n

    def _grow(self, n: int) -> None:
        if n >= len(self._data):
            new = np.empty(max(len(self._data) * 2, n + 1), dtype=self._data.dtype)
            new[: len(self._data)] = self._data
            self._data = new

    def _track(self, v) -> None:
        if self.min_value is None or v < self.min_value:
            self.min_value = v
        if self.max_value is None or v > self.max_value:
            self.max_value = v

    def _mv_row(self, value) -> np.ndarray:
        dt = self.spec.data_type
        entries = value if isinstance(value, (list, tuple, np.ndarray)) \
            else [value]
        if dt.is_string_like:
            return np.asarray([str(v) for v in entries], dtype=np.str_)
        return np.asarray([dt.convert(v) for v in entries], dtype=dt.np_dtype)

    def _append_mv_row(self, row: np.ndarray) -> None:
        self._rows.append(row)
        self.total_entries += len(row)
        for v in row.tolist():
            self._track(v)

    def append(self, value, row_idx: int) -> None:
        if not self.single_value:
            self._append_mv_row(self._mv_row(value))
            return
        self._grow(row_idx)
        if self.dict_encoded:
            v = str(value) if self.spec.data_type is not DataType.BYTES else bytes(value)
            did = self._dict.get(v)
            if did is None:
                did = len(self._dict_values)
                self._dict[v] = did
                self._dict_values.append(v)
            self._data[row_idx] = did
        else:
            v = self.spec.data_type.convert(value)
            self._data[row_idx] = v
        self._track(v)

    # ---- columnar batch path (chunklet subsystem ingest basis) -----------
    def prepare_batch(self, vals: list):
        """Stage a batch WITHOUT mutating column state: all conversion and
        validation (the failure-prone part) happens here, so one bad row
        can never leave partial appends behind — ``commit_batch`` only
        publishes already-validated arrays."""
        try:  # C-level membership scan; nulls are the rare case
            has_null = None in vals
        except ValueError:
            # `in` compares elementwise against ndarray payloads (MV rows);
            # fall back to the identity scan the row path implies
            has_null = any(v is None for v in vals)
        if has_null:
            null_rows = [i for i, v in enumerate(vals) if v is None]
            vals = list(vals)
            fill = [] if not self.single_value else self.spec.null_value()
            for i in null_rows:
                vals[i] = fill
        else:
            null_rows = ()
        if not self.single_value:
            return ("mv", null_rows, [self._mv_row(v) for v in vals])
        dt = self.spec.data_type
        if self.dict_encoded:
            # vectorized dictionary growth: one np.unique over the batch,
            # then ONE dict probe per distinct value instead of per row.
            # Strings sort as a native U array (faster comparator); BYTES
            # stay object-typed — an 'S' array would strip trailing NULs.
            if dt is DataType.BYTES:
                arr = np.asarray([bytes(v) for v in vals], dtype=object)
            else:
                arr = np.asarray(vals)
                if arr.dtype.kind != "U":  # non-str payloads: coerce per value
                    arr = np.asarray([str(v) for v in vals])
            uniq, inv = np.unique(arr, return_inverse=True)
            return ("dict", null_rows, uniq, inv.astype(np.int32))
        try:
            arr = np.asarray(vals, dtype=dt.np_dtype)
        except (TypeError, ValueError):
            # heterogenous payloads (e.g. numeric strings): per-value coerce
            arr = np.asarray([dt.convert(v) for v in vals], dtype=dt.np_dtype)
        return ("raw", null_rows, arr)

    def commit_batch(self, staged, row0: int) -> None:
        """Publish a staged batch at doc ids [row0, row0+n)."""
        kind = staged[0]
        for i in staged[1]:
            self.null_docs.append(row0 + i)
        if kind == "mv":
            for row in staged[2]:
                self._append_mv_row(row)
            return
        if kind == "dict":
            _, _, uniq, inv = staged
            n = len(inv)
            if n == 0:
                return
            self._grow(row0 + n - 1)
            uvals = uniq.tolist()  # python values, like the row path stores
            ids = np.empty(len(uvals), dtype=np.int32)
            for j, v in enumerate(uvals):
                did = self._dict.get(v)
                if did is None:
                    did = len(self._dict_values)
                    self._dict[v] = did
                    self._dict_values.append(v)
                ids[j] = did
            self._data[row0:row0 + n] = ids[inv]
            # uniq is sorted: batch min/max are its ends
            self._track(uvals[0])
            self._track(uvals[-1])
            return
        arr = staged[2]
        n = len(arr)
        if n == 0:
            return
        self._grow(row0 + n - 1)
        self._data[row0:row0 + n] = arr
        self._track(arr.min().item())
        self._track(arr.max().item())

    def dict_table(self) -> np.ndarray:
        """Snapshot of the insertion-ordered dictionary values as an array
        (the dict list only appends, so a slice-copy is a safe snapshot).
        BYTES values stay object-typed — an 'S' array would strip trailing
        NUL bytes on the way through."""
        vals = self._dict_values[:]
        if vals and isinstance(vals[0], bytes):
            return np.asarray(vals, dtype=object)
        return np.asarray(vals)

    def values(self, n: int) -> np.ndarray:
        """Decoded raw values for the first n docs (reader snapshot); MV
        columns return an object array of per-row arrays."""
        return self.values_range(0, n)

    def values_range(self, start: int, stop: int) -> np.ndarray:
        """Decoded raw values for docs [start, stop) — the tail-view form:
        decoding a 64k-row tail must not pay a full-segment dictionary
        take (realtime/chunklet.py MutableTailView)."""
        if not self.single_value:
            out = np.empty(stop - start, dtype=object)
            rows = self._rows  # grow-only list: indexes < stop are stable
            for i in range(start, stop):
                out[i - start] = rows[i]
            return out
        if self.dict_encoded:
            return self.dict_table()[self._data[start:stop]]
        return self._data[start:stop]

    @property
    def cardinality(self) -> int:
        return len(self._dict_values) if self.dict_encoded else -1


class _MetadataView:
    """Duck-typed SegmentMetadata for the host executor / pruner."""

    def __init__(self, seg: "MutableSegment"):
        self._seg = seg

    @property
    def columns(self) -> dict:
        return {name: self._seg.column_metadata(name) for name in self._seg._cols}


class MutableSegment:
    is_mutable = True

    def __init__(self, schema: Schema, segment_name: str,
                 table_config: Optional[TableConfig] = None,
                 enable_upsert: bool = False):
        self.schema = schema
        self.segment_name = segment_name
        self.table_config = table_config or TableConfig(table_name=schema.name)
        self._cols = {n: MutableColumn(schema.field(n)) for n in schema.column_names()}
        self._count = 0  # volatile doc counter: bumped AFTER the row lands
        self._lock = threading.Lock()  # single writer enforced defensively
        self._valid = np.ones(_INITIAL_CAPACITY, dtype=bool) if enable_upsert else None
        self.start_offset = None
        self.end_offset = None
        # chunklet subsystem (realtime/chunklet.py): frozen-prefix promotion
        # into sealed device-eligible blocks. Created eagerly from config so
        # the consume loop / engine never check config themselves; MV
        # columns keep the whole segment on the host scan path (the device
        # batch layer rejects MV consuming data anyway).
        self.chunklet_index = None
        ck_cfg = getattr(self.table_config, "chunklets", None)
        if ck_cfg is not None and ck_cfg.enabled and all(
                schema.field(n).single_value for n in schema.column_names()):
            from pinot_tpu.realtime.chunklet import ChunkletIndex

            self.chunklet_index = ChunkletIndex(self, ck_cfg)

    # ---- write path ------------------------------------------------------
    def index(self, row: dict) -> int:
        """Index one row; returns its doc id. Row values missing from the
        schema default to the field's null value (recordtransformer analog)."""
        with self._lock:
            doc_id = self._count
            for name, col in self._cols.items():
                v = row.get(name)
                if v is None:
                    # record nullness BEFORE substituting the default value
                    # (IS_NULL reads this; the forward index stores the
                    # default, same as the sealed null-vector contract)
                    col.null_docs.append(doc_id)
                    v = [] if not col.single_value else col.spec.null_value()
                col.append(v, doc_id)
            if self._valid is not None and doc_id >= len(self._valid):
                new = np.ones(len(self._valid) * 2, dtype=bool)
                new[: len(self._valid)] = self._valid
                self._valid = new
            self._count = doc_id + 1  # publish: readers never see doc_id
        from pinot_tpu.common import freshness

        # broker result caches keyed on the table freshness epoch must
        # never serve counts from before this row (ISSUE 10)
        freshness.bump(self.table_config.table_name)
        return doc_id

    def index_batch(self, rows) -> int:
        """Columnar batch indexing (the chunklet subsystem's ingest basis):
        one vectorized append per column instead of n per-row dict walks.
        Conversion is staged for EVERY column before any state mutates, so
        a bad row fails the whole batch atomically — callers fall back to
        row-at-a-time ``index`` to isolate poison rows. Returns the first
        doc id of the batch. Upsert tables keep the per-row path (the
        primary-key CAS is inherently row-at-a-time)."""
        rows = rows if isinstance(rows, list) else list(rows)
        with self._lock:
            row0 = self._count
            n = len(rows)
            if n == 0:
                return row0
            staged = {
                name: col.prepare_batch([r.get(name) for r in rows])
                for name, col in self._cols.items()
            }
            for name, col in self._cols.items():
                col.commit_batch(staged[name], row0)
            if self._valid is not None:
                while row0 + n > len(self._valid):
                    new = np.ones(len(self._valid) * 2, dtype=bool)
                    new[: len(self._valid)] = self._valid
                    self._valid = new
            self._count = row0 + n  # publish the whole batch at once
        from pinot_tpu.common import freshness

        freshness.bump(self.table_config.table_name)
        return row0

    def invalidate(self, doc_id: int) -> None:
        """Upsert: flip this doc out of validDocIds
        (ThreadSafeMutableRoaringBitmap analog)."""
        if self._valid is not None:
            self._valid[doc_id] = False
            from pinot_tpu.common import freshness

            freshness.bump(self.table_config.table_name)
            if self.chunklet_index is not None:
                # a promoted chunklet covering this doc can no longer run
                # unmasked on the device path
                self.chunklet_index.note_invalidated(doc_id)

    # ---- reader protocol (host executor duck type) -----------------------
    @property
    def n_docs(self) -> int:
        return self._count

    @property
    def name(self) -> str:
        return self.segment_name

    @property
    def dir(self) -> str:
        return f"<mutable:{self.segment_name}:{self._count}>"

    @property
    def metadata(self):
        return _MetadataView(self)

    def column_names(self) -> list:
        return list(self._cols)

    def column_metadata(self, col: str) -> ColumnMetadata:
        c = self._cols[col]
        return ColumnMetadata(
            name=col,
            data_type=c.spec.data_type,
            encoding=Encoding.RAW,  # readers take the raw-value scan path
            cardinality=c.cardinality,
            min_value=c.min_value,
            max_value=c.max_value,
            is_sorted=False,
            single_value=c.single_value,
            has_dictionary=False,
            total_number_of_entries=(
                self._count if c.single_value else c.total_entries
            ),
        )

    def dictionary(self, col: str):
        return None  # insertion-ordered dict is not binary-searchable

    def bloom(self, col: str):
        return None

    def values(self, col: str) -> np.ndarray:
        return self._cols[col].values(self._count)

    def valid_docs(self, n: int):
        if self._valid is None:
            return None
        return self._valid[:n]

    def row_value(self, col: str, doc_id: int):
        """One doc's decoded value, or None when null there — O(1), used by
        the partial-upsert previous-version read (no column materialization).
        null_docs appends in doc order, so membership is a binary search."""
        import bisect

        c = self._cols[col]
        nd = c.null_docs
        if nd:
            i = bisect.bisect_left(nd, doc_id, 0, len(nd))
            if i < len(nd) and nd[i] == doc_id:
                return None
        if not c.single_value:
            return c._rows[doc_id].tolist()
        if c.dict_encoded:
            return c._dict_values[int(c._data[doc_id])]
        v = c._data[doc_id]
        return v.item() if isinstance(v, np.generic) else v

    def null_vector(self, col: str):
        """Per-doc null bitmap over all indexed docs, or None when clean
        (readers slice to their snapshot length)."""
        docs = self._cols[col].null_docs
        if not docs:
            return None
        mask = np.zeros(self._count, dtype=bool)
        ids = np.asarray(docs[:], dtype=np.int64)
        mask[ids[ids < self._count]] = True
        return mask

    # ---- seal ------------------------------------------------------------
    def seal(self, out_dir: str):
        """Consuming → immutable conversion (RealtimeSegmentConverter.java):
        re-encodes through the two-pass creator, which rebuilds *sorted*
        dictionaries and all configured indexes."""
        from pinot_tpu.storage.creator import build_segment
        from pinot_tpu.storage.segment import ImmutableSegment

        n = self._count
        ci = self.chunklet_index
        if ci is not None and ci.chunklets:
            # reuse the already-sealed chunklet column blocks for the frozen
            # prefix: only the unfrozen tail decodes through the insertion-
            # ordered dictionary here
            columns = {name: ci.column_with_tail(name, n)
                       for name in self._cols}
        else:
            columns = {name: self._cols[name].values(n) for name in self._cols}
        null_masks = {}
        for name in self._cols:
            nv = self.null_vector(name)
            if nv is not None and nv[:n].any():
                null_masks[name] = nv[:n]
        build_segment(self.schema, columns, out_dir, self.table_config,
                      self.segment_name, null_masks=null_masks or None)
        seg = ImmutableSegment(out_dir)
        if self._valid is not None:
            seg.valid_docs_mask = self._valid[:n].copy()
        if ci is not None:
            # seal retires the consuming segment's chunklet batches: drop
            # any device partials cached over them (realtime/chunklet.py)
            from pinot_tpu.realtime.chunklet import _invalidate_device_partials

            _invalidate_device_partials(f"<chunklet:{self.segment_name}:")
        from pinot_tpu.common import freshness

        # seal swaps the consuming backend for the immutable one: cached
        # broker results built over the old split must re-validate
        freshness.bump(self.table_config.table_name)
        return seg
