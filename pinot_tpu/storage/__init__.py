from pinot_tpu.storage.dictionary import Dictionary
from pinot_tpu.storage.creator import SegmentCreator, build_segment
from pinot_tpu.storage.segment import ImmutableSegment, ColumnMetadata, SegmentMetadata
from pinot_tpu.storage.device import DeviceSegment, DeviceColumn
