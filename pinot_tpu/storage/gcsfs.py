"""GCS deep-store filesystem (pinot-plugins/pinot-file-system/pinot-gcs
analog), gated on google-cloud-storage.

Segment-directory-over-prefix semantics come from the shared
``PrefixObjectFS`` base (storage/fs.py) — this module supplies only the
google-cloud-storage-backed primitive hooks. Registers lazily under the
``gs`` scheme and raises a clear error at construction when the client
library is absent.
"""

from __future__ import annotations

from pinot_tpu.storage.fs import PrefixObjectFS


def _gcs():
    try:
        from google.cloud import storage  # type: ignore

        return storage
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "gs:// deep store needs the google-cloud-storage package; "
            "install it or use a file:// deep store") from e


class GcsFS(PrefixObjectFS):
    scheme = "gs"

    def __init__(self):
        self._client = _gcs().Client()

    def _list(self, bucket: str, prefix: str, limit=None) -> list:
        kw = {"prefix": prefix}
        if limit:
            kw["max_results"] = limit
        return [b.name for b in self._client.list_blobs(bucket, **kw)]

    def _put(self, local_path: str, bucket: str, key: str) -> None:
        self._client.bucket(bucket).blob(key).upload_from_filename(local_path)

    def _get(self, bucket: str, key: str, local_path: str) -> None:
        self._client.bucket(bucket).blob(key).download_to_filename(local_path)

    @staticmethod
    def _is_not_found(exc: Exception) -> bool:
        return "NotFound" in type(exc).__name__ or "404" in str(exc)

    def _delete_objs(self, bucket: str, keys: list) -> None:
        b = self._client.bucket(bucket)
        # one round trip per batch instead of one per blob; deletes must be
        # IDEMPOTENT like S3's delete_objects — a concurrent retire racing
        # this listing raises NotFound mid-batch, which is success here
        for i in range(0, len(keys), 100):  # GCS batch cap
            try:
                with self._client.batch():
                    for k in keys[i: i + 100]:
                        b.blob(k).delete()
            except Exception as e:  # noqa: BLE001 — tolerate gone objects
                if not self._is_not_found(e):
                    raise

    def _copy_obj(self, src_bucket: str, src_key: str,
                  dst_bucket: str, dst_key: str) -> None:
        sb = self._client.bucket(src_bucket)
        sb.copy_blob(sb.blob(src_key), self._client.bucket(dst_bucket),
                     dst_key)
