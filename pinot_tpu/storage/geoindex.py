"""Geospatial filter index: the reference H3 index's role, grid-cell form.

Reference (pinot-segment-local/.../readers/geospatial/
ImmutableH3IndexReader.java + H3IndexFilterOperator): POINT columns get a
cell → doc-bitmap index so ``ST_Distance(col, point) < r`` prunes to the
cells covering the query circle instead of scanning every doc. H3 is a
JNI-backed hexagonal library; the equivalent capability here is a fixed
lat/lon **grid** index — cells are ``(floor(lat/res), floor(lon/res))``
at 0.5°, candidate cells are the bounding box of the query circle
(superset, so the exact haversine verify on candidates preserves
correctness), and postings are doc ids.
"""

from __future__ import annotations

import os

import numpy as np

RES_DEG = 0.5
_M_PER_DEG_LAT = 111_320.0

CELLS_FILE = "{col}.geo.cells.npy"
DOCS_FILE = "{col}.geo.docs.npy"
OFFS_FILE = "{col}.geo.off.npy"


def _cell_ids(lon: np.ndarray, lat: np.ndarray) -> np.ndarray:
    """int64 cell key; NaN coordinates land in a sentinel cell that no
    query bbox covers."""
    ok = np.isfinite(lon) & np.isfinite(lat)
    ci = np.floor(np.where(ok, lat, 1000.0) / RES_DEG).astype(np.int64)
    cj = np.floor(np.where(ok, lon, 1000.0) / RES_DEG).astype(np.int64)
    return ci * 100_000 + cj


class GeoGridIndex:
    def __init__(self, cells: np.ndarray, docs: np.ndarray, offs: np.ndarray):
        self.cells = cells  # sorted unique int64 cell keys
        self.docs = docs    # concatenated int32 doc postings
        self.offs = offs    # (n_cells+1,) int64

    @classmethod
    def build(cls, point_wkts) -> "GeoGridIndex":
        from pinot_tpu.ops.geo import parse_points

        lon, lat = parse_points(point_wkts)
        keys = _cell_ids(lon, lat)
        order = np.argsort(keys, kind="stable")
        sk = keys[order]
        cells, starts = np.unique(sk, return_index=True)
        offs = np.append(starts, len(sk)).astype(np.int64)
        return cls(cells, order.astype(np.int32), offs)

    def save(self, dir_path: str, col: str) -> None:
        np.save(os.path.join(dir_path, CELLS_FILE.format(col=col)),
                self.cells, allow_pickle=False)
        np.save(os.path.join(dir_path, DOCS_FILE.format(col=col)),
                self.docs, allow_pickle=False)
        np.save(os.path.join(dir_path, OFFS_FILE.format(col=col)),
                self.offs, allow_pickle=False)

    @classmethod
    def load(cls, dir_path: str, col: str):
        cp = os.path.join(dir_path, CELLS_FILE.format(col=col))
        if not os.path.exists(cp):
            return None
        return cls(
            np.load(cp, allow_pickle=False),
            np.load(os.path.join(dir_path, DOCS_FILE.format(col=col)),
                    allow_pickle=False, mmap_mode="r"),
            np.load(os.path.join(dir_path, OFFS_FILE.format(col=col)),
                    allow_pickle=False),
        )

    def candidate_docs(self, lon: float, lat: float, radius_m: float):
        """Doc ids in every cell intersecting the circle's bounding box
        (superset of true matches; caller verifies with exact haversine).
        Returns None — "no narrowing, scan" — when the bbox crosses the
        antimeridian or approaches a pole, where a raw-longitude box is
        NOT a superset of the circle."""
        dlat = radius_m / _M_PER_DEG_LAT
        if abs(lat) + dlat > 85.0:
            return None  # near-pole: lon spans wrap unpredictably
        max_abs_lat = abs(lat) + dlat
        dlon = radius_m / (_M_PER_DEG_LAT *
                           max(np.cos(np.radians(max_abs_lat)), 1e-6))
        if lon - dlon < -180.0 or lon + dlon > 180.0:
            return None  # antimeridian wrap: cells split across the seam
        lat_lo = int(np.floor((lat - dlat) / RES_DEG))
        lat_hi = int(np.floor((lat + dlat) / RES_DEG))
        lon_lo = int(np.floor((lon - dlon) / RES_DEG))
        lon_hi = int(np.floor((lon + dlon) / RES_DEG))
        chunks = []
        for ci in range(lat_lo, lat_hi + 1):
            # cells are sorted by (ci, cj): one contiguous band per ci
            lo = np.searchsorted(self.cells, ci * 100_000 + lon_lo)
            hi = np.searchsorted(self.cells, ci * 100_000 + lon_hi,
                                 side="right")
            for j in range(lo, hi):
                chunks.append(np.asarray(
                    self.docs[self.offs[j]: self.offs[j + 1]]))
        if not chunks:
            return np.empty(0, dtype=np.int32)
        return np.sort(np.concatenate(chunks))
