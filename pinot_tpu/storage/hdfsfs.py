"""HDFS deep-store filesystem
(pinot-plugins/pinot-file-system/pinot-hdfs analog) over the WebHDFS REST
gateway — stdlib urllib only, no hadoop client dependency.

Unlike the object stores (PrefixObjectFS), HDFS is a real hierarchical
filesystem, so this implements the PinotFS surface directly with WebHDFS
operations: MKDIRS, GETFILESTATUS, LISTSTATUS, DELETE (recursive),
CREATE (two-step redirect PUT), OPEN. URIs:

    hdfs://namenode:9870/path/to/segment

where the authority is the WebHDFS (HTTP) endpoint of the namenode. An
optional ``HDFS_USER`` environment variable rides as ``user.name`` on
every call (simple auth — the common dev/test posture; kerberized
clusters front WebHDFS with a gateway).

Registers under the ``hdfs`` scheme via the plugin registry, like the
s3/gs/abfss plugins.
"""

from __future__ import annotations

import json
import os
import shutil
from urllib.parse import quote, urlparse

from pinot_tpu.storage.fs import PinotFS

_TIMEOUT_S = 30.0


class HdfsFS(PinotFS):
    scheme = "hdfs"

    def __init__(self):
        self.user = os.environ.get("HDFS_USER", "")

    # ---- REST plumbing ---------------------------------------------------
    def _split(self, uri: str):
        u = urlparse(uri)
        if u.scheme != self.scheme or not u.netloc:
            raise ValueError(f"not an {self.scheme} URI: {uri!r}")
        return u.netloc, u.path or "/"

    def _url(self, authority: str, path: str, op: str, **params) -> str:
        qs = f"op={op}"
        if self.user:
            qs += f"&user.name={quote(self.user)}"
        for k, v in params.items():
            qs += f"&{k}={quote(str(v))}"
        return f"http://{authority}/webhdfs/v1{quote(path)}?{qs}"

    def _call(self, method: str, url: str, data=None,
              follow_redirect_put: bool = False, sink=None):
        """``data`` may be bytes or a FILE OBJECT (urllib streams file-like
        PUT bodies); ``sink``: stream the response into this open file
        instead of returning bytes — multi-GB segment files must not
        buffer whole on the heap."""
        import shutil as _shutil
        import urllib.error
        import urllib.request

        req = urllib.request.Request(url, method=method)
        try:
            with urllib.request.urlopen(req, timeout=_TIMEOUT_S) as resp:
                if sink is not None:
                    _shutil.copyfileobj(resp, sink)
                    return b""
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 307 and follow_redirect_put:
                # CREATE/APPEND two-step: the namenode redirects to a
                # datanode; PUT the payload there (streamed when file-like)
                loc = e.headers.get("Location")
                req2 = urllib.request.Request(
                    loc, data=(data if data is not None else b""),
                    method="PUT")
                with urllib.request.urlopen(req2, timeout=_TIMEOUT_S) as r2:
                    return r2.read()
            if e.code == 404:
                raise FileNotFoundError(url) from e
            raise

    def _status(self, authority: str, path: str):
        try:
            raw = self._call("GET", self._url(authority, path,
                                              "GETFILESTATUS"))
        except FileNotFoundError:
            return None
        return json.loads(raw.decode("utf-8"))["FileStatus"]

    # ---- PinotFS surface -------------------------------------------------
    def mkdir(self, path: str) -> None:
        auth, p = self._split(path)
        self._call("PUT", self._url(auth, p, "MKDIRS"))

    def delete(self, path: str) -> None:
        auth, p = self._split(path)
        try:
            self._call("DELETE", self._url(auth, p, "DELETE",
                                           recursive="true"))
        except FileNotFoundError:
            pass  # idempotent like the object stores

    def exists(self, path: str) -> bool:
        auth, p = self._split(path)
        return self._status(auth, p) is not None

    def _list_status(self, authority: str, path: str) -> list:
        """[(name, 'FILE'|'DIRECTORY')] — one LISTSTATUS, types included,
        so directory walks don't need a GETFILESTATUS per child."""
        raw = self._call("GET", self._url(authority, path, "LISTSTATUS"))
        statuses = json.loads(raw.decode("utf-8"))
        return sorted(
            (s["pathSuffix"], s["type"])
            for s in statuses["FileStatuses"]["FileStatus"]
            if s["pathSuffix"])

    def list_files(self, path: str) -> list:
        auth, p = self._split(path)
        return [n for n, _t in self._list_status(auth, p)]

    def _upload_file(self, local: str, auth: str, remote: str) -> None:
        with open(local, "rb") as f:
            self._call("PUT", self._url(auth, remote, "CREATE",
                                        overwrite="true"),
                       data=f, follow_redirect_put=True)

    def _download_file(self, auth: str, remote: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        with open(local, "wb") as f:
            self._call("GET", self._url(auth, remote, "OPEN"), sink=f)

    def copy(self, src: str, dst: str) -> None:
        pfx = f"{self.scheme}://"
        src_h, dst_h = src.startswith(pfx), dst.startswith(pfx)
        if not src_h and dst_h:  # upload (segment push)
            self.delete(dst)
            auth, p = self._split(dst)
            if os.path.isdir(src):
                self._call("PUT", self._url(auth, p, "MKDIRS"))
                for root, _, files in os.walk(src):
                    for f in sorted(files):
                        full = os.path.join(root, f)
                        rel = os.path.relpath(full, src).replace(os.sep, "/")
                        self._upload_file(full, auth, f"{p.rstrip('/')}/{rel}")
            else:
                self._upload_file(src, auth, p)
        elif src_h and not dst_h:  # download (server sync)
            auth, p = self._split(src)
            st = self._status(auth, p)
            if st is None:
                raise FileNotFoundError(src)
            if st["type"] == "FILE":
                self._download_file(auth, p, dst)
                return
            os.makedirs(dst, exist_ok=True)
            for name, ftype in self._list_status(auth, p):
                child = f"{src.rstrip('/')}/{name}"
                local = os.path.join(dst, name)
                if ftype == "DIRECTORY":
                    self.copy(child, local)
                else:
                    self._download_file(auth, f"{p.rstrip('/')}/{name}",
                                        local)
        elif src_h and dst_h:
            # no server-side copy op in WebHDFS: bounce through a temp dir
            import tempfile

            tmp = tempfile.mkdtemp(prefix="hdfs_cp_")
            try:
                self.copy(src, os.path.join(tmp, "x"))
                self.copy(os.path.join(tmp, "x"), dst)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise ValueError(
                f"HdfsFS.copy needs at least one {self.scheme}:// side")
