"""Bloom filters for host-side segment pruning.

Equivalent of the reference's guava-format bloom readers
(pinot-segment-local/.../readers/bloom/) used by
``ColumnValueSegmentPruner``: answers "might this segment contain value v?"
for EQ/IN predicates before any device work is scheduled.

Layout: uint64 bitset array; k derived from a fixed 1% target FPP. Hashing is
double-hashing over FNV-1a/FNV-1 of the value's utf-8/bytes form (we need
determinism across processes, not guava compatibility).
"""

from __future__ import annotations

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _value_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, float) and float(v).is_integer():
        v = int(v)
    return str(v).encode("utf-8")


def _positions(v, m_bits: int, k: int) -> list[int]:
    b = _value_bytes(v)
    h1 = _fnv1a(b)
    h2 = _fnv1a(b + b"\x01") | 1
    return [((h1 + i * h2) & _MASK64) % m_bits for i in range(k)]


class BloomFilter:
    K = 7  # ~1% fpp at 10 bits/element

    def __init__(self, bits: np.ndarray):
        self._bits = bits  # uint64 words; word 0 is reserved for m_bits
        self.m_bits = int(bits[0])

    @classmethod
    def build(cls, values, bits_per_element: int = 10) -> "BloomFilter":
        n = max(1, len(values))
        m_bits = max(64, n * bits_per_element)
        words = np.zeros(1 + (m_bits + 63) // 64, dtype=np.uint64)
        words[0] = m_bits
        for v in values:
            for pos in _positions(v, m_bits, cls.K):
                words[1 + pos // 64] |= np.uint64(1 << (pos % 64))
        return cls(words)

    def might_contain(self, v) -> bool:
        for pos in _positions(v, self.m_bits, self.K):
            if not (int(self._bits[1 + pos // 64]) >> (pos % 64)) & 1:
                return False
        return True

    def save(self, path: str) -> None:
        np.save(path, self._bits, allow_pickle=False)

    @classmethod
    def load(cls, path: str) -> "BloomFilter":
        return cls(np.load(path, allow_pickle=False))


def build_bloom(raw_values, dict_values, out_path: str) -> None:
    """Build from raw values or (deduped) dictionary values."""
    values = dict_values if dict_values is not None else np.unique(np.asarray(raw_values))
    BloomFilter.build(list(values)).save(out_path)
