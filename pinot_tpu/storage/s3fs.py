"""S3 deep-store filesystem (pinot-plugins/pinot-file-system/pinot-s3
analog), gated on boto3.

Maps the PinotFS surface onto S3 object operations the way S3PinotFS
does: a "directory" is a key prefix, ``copy`` walks local files into
objects (and back for downloads), ``delete`` removes the prefix. The
segment lifecycle only ever copies whole segment directories, so the
prefix model is sufficient.

The build image ships no AWS SDK, so the module registers lazily under
the ``s3`` scheme and raises a clear error at construction when boto3 is
absent — the registry itself never breaks (plugin-isolation contract).

Config via environment (the reference reads pinot.controller.storage
properties; here the standard AWS env/credentials chain applies, plus
``PINOT_TPU_S3_ENDPOINT`` for S3-compatible stores).
"""

from __future__ import annotations

import os
from urllib.parse import urlparse

from pinot_tpu.storage.fs import PinotFS


def _boto3():
    try:
        import boto3  # type: ignore

        return boto3
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "s3:// deep store needs the boto3 package; install it or use a "
            "file:// deep store") from e


def _split(uri: str):
    u = urlparse(uri)
    if u.scheme != "s3" or not u.netloc:
        raise ValueError(f"not an s3 URI: {uri!r}")
    return u.netloc, u.path.lstrip("/")


class S3FS(PinotFS):
    def __init__(self):
        b3 = _boto3()
        kwargs = {}
        endpoint = os.environ.get("PINOT_TPU_S3_ENDPOINT")
        if endpoint:
            kwargs["endpoint_url"] = endpoint
        self._s3 = b3.client("s3", **kwargs)

    def mkdir(self, path: str) -> None:
        pass  # prefixes need no creation

    def _dir_keys(self, bucket: str, prefix: str, max_keys=None) -> list:
        """Keys of the 'directory' at prefix: everything under prefix + '/'
        plus an exact-key object — a bare prefix match would also hit
        same-prefix siblings (seg_1 vs seg_10)."""
        p = prefix.rstrip("/")
        keys = self._list_keys(bucket, p + "/", max_keys=max_keys)
        if max_keys is None or len(keys) < max_keys:
            # the exact key sorts FIRST among keys sharing the prefix
            exact = self._list_keys(bucket, p, max_keys=1)
            if exact and exact[0] == p and p not in keys:
                keys.append(p)
        return keys

    def delete(self, path: str) -> None:
        bucket, prefix = _split(path)
        keys = self._dir_keys(bucket, prefix)
        for i in range(0, len(keys), 1000):
            self._s3.delete_objects(
                Bucket=bucket,
                Delete={"Objects": [{"Key": k} for k in keys[i: i + 1000]]})

    def exists(self, path: str) -> bool:
        bucket, prefix = _split(path)
        return bool(self._dir_keys(bucket, prefix, max_keys=1))

    def _list_keys(self, bucket: str, prefix: str, max_keys=None) -> list:
        keys = []
        token = None
        while True:
            kw = {"Bucket": bucket, "Prefix": prefix}
            if token:
                kw["ContinuationToken"] = token
            if max_keys:
                kw["MaxKeys"] = max_keys
            resp = self._s3.list_objects_v2(**kw)
            keys.extend(o["Key"] for o in resp.get("Contents", ()))
            if max_keys or not resp.get("IsTruncated"):
                return keys
            token = resp.get("NextContinuationToken")

    def copy(self, src: str, dst: str) -> None:
        src_s3 = src.startswith("s3://")
        dst_s3 = dst.startswith("s3://")
        if not src_s3 and dst_s3:  # upload (segment push)
            self.delete(dst)  # PinotFS contract: dst is REPLACED
            bucket, prefix = _split(dst)
            if os.path.isdir(src):
                for root, _, files in os.walk(src):
                    for f in sorted(files):
                        full = os.path.join(root, f)
                        rel = os.path.relpath(full, src)
                        self._s3.upload_file(
                            full, bucket, f"{prefix}/{rel}".replace(os.sep, "/"))
            else:
                self._s3.upload_file(src, bucket, prefix)
        elif src_s3 and not dst_s3:  # download (server sync)
            bucket, prefix = _split(src)
            prefix = prefix.rstrip("/")
            keys = self._dir_keys(bucket, prefix)
            if not keys:
                raise FileNotFoundError(src)
            for key in keys:
                rel = key[len(prefix):].lstrip("/")
                local = os.path.join(dst, rel) if rel else dst
                os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
                self._s3.download_file(bucket, key, local)
        elif src_s3 and dst_s3:
            self.delete(dst)  # PinotFS contract: dst is REPLACED
            sb, sp = _split(src)
            sp = sp.rstrip("/")
            db, dp = _split(dst)
            for key in self._dir_keys(sb, sp):
                rel = key[len(sp):].lstrip("/")
                self._s3.copy_object(
                    Bucket=db, Key=f"{dp}/{rel}".rstrip("/"),
                    CopySource={"Bucket": sb, "Key": key})
        else:
            raise ValueError("S3FS.copy needs at least one s3:// side")

    def list_files(self, path: str) -> list:
        bucket, prefix = _split(path)
        pfx = prefix.rstrip("/") + "/" if prefix else ""
        names = set()
        for key in self._list_keys(bucket, pfx):
            rest = key[len(pfx):]
            names.add(rest.split("/", 1)[0])
        return sorted(n for n in names if n)
