"""S3 deep-store filesystem (pinot-plugins/pinot-file-system/pinot-s3
analog), gated on boto3.

Segment-directory-over-prefix semantics come from the shared
``PrefixObjectFS`` base (storage/fs.py) — this module supplies only the
five boto3-backed primitive hooks. Registers lazily under the ``s3``
scheme and raises a clear error at construction when boto3 is absent.

Config via environment: the standard AWS env/credentials chain applies,
plus ``PINOT_TPU_S3_ENDPOINT`` for S3-compatible stores.
"""

from __future__ import annotations

import os

from pinot_tpu.storage.fs import PrefixObjectFS


def _boto3():
    try:
        import boto3  # type: ignore

        return boto3
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "s3:// deep store needs the boto3 package; install it or use a "
            "file:// deep store") from e


class S3FS(PrefixObjectFS):
    scheme = "s3"

    def __init__(self):
        b3 = _boto3()
        kwargs = {}
        endpoint = os.environ.get("PINOT_TPU_S3_ENDPOINT")
        if endpoint:
            kwargs["endpoint_url"] = endpoint
        self._s3 = b3.client("s3", **kwargs)

    def _list(self, bucket: str, prefix: str, limit=None) -> list:
        keys = []
        token = None
        while True:
            kw = {"Bucket": bucket, "Prefix": prefix}
            if token:
                kw["ContinuationToken"] = token
            if limit:
                kw["MaxKeys"] = limit
            resp = self._s3.list_objects_v2(**kw)
            keys.extend(o["Key"] for o in resp.get("Contents", ()))
            if limit or not resp.get("IsTruncated"):
                return keys
            token = resp.get("NextContinuationToken")

    def _put(self, local_path: str, bucket: str, key: str) -> None:
        self._s3.upload_file(local_path, bucket, key)

    def _get(self, bucket: str, key: str, local_path: str) -> None:
        self._s3.download_file(bucket, key, local_path)

    def _delete_objs(self, bucket: str, keys: list) -> None:
        for i in range(0, len(keys), 1000):  # API batch cap
            self._s3.delete_objects(
                Bucket=bucket,
                Delete={"Objects": [{"Key": k} for k in keys[i: i + 1000]]})

    def _copy_obj(self, src_bucket: str, src_key: str,
                  dst_bucket: str, dst_key: str) -> None:
        self._s3.copy_object(Bucket=dst_bucket, Key=dst_key,
                             CopySource={"Bucket": src_bucket,
                                         "Key": src_key})
