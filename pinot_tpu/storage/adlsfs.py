"""ADLS (Azure Blob / abfss) deep-store filesystem
(pinot-plugins/pinot-file-system/pinot-adls analog), gated on
azure-storage-blob.

Segment-directory-over-prefix semantics come from the shared
``PrefixObjectFS`` base (storage/fs.py) — this module supplies only the
azure-storage-blob-backed primitive hooks (container == bucket). Registers
lazily under the ``abfss`` scheme and raises a clear error at construction
when the client library is absent. The account connection string rides the
standard ``AZURE_STORAGE_CONNECTION_STRING`` environment variable.
"""

from __future__ import annotations

import os

from pinot_tpu.storage.fs import PrefixObjectFS


def _azure_blob():
    try:
        from azure.storage import blob  # type: ignore

        return blob
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "abfss:// deep store needs the azure-storage-blob package; "
            "install it or use a file:// deep store") from e


class AdlsFS(PrefixObjectFS):
    scheme = "abfss"

    def __init__(self):
        blob = _azure_blob()
        conn = os.environ.get("AZURE_STORAGE_CONNECTION_STRING", "")
        self._client = blob.BlobServiceClient.from_connection_string(conn)

    def _container(self, bucket: str):
        # abfss URIs carry container@account.dfs.core.windows.net as the
        # netloc; the SDK wants the bare container name (the account is
        # fixed by the connection string)
        return self._client.get_container_client(bucket.split("@", 1)[0])

    def _list(self, bucket: str, prefix: str, limit=None) -> list:
        names = []
        for b in self._container(bucket).list_blobs(name_starts_with=prefix):
            names.append(b.name if hasattr(b, "name") else b["name"])
            if limit and len(names) >= limit:
                break
        return names

    def _put(self, local_path: str, bucket: str, key: str) -> None:
        with open(local_path, "rb") as f:
            self._container(bucket).upload_blob(key, f, overwrite=True)

    def _get(self, bucket: str, key: str, local_path: str) -> None:
        with open(local_path, "wb") as f:
            f.write(self._container(bucket).download_blob(key).readall())

    def _delete_objs(self, bucket: str, keys: list) -> None:
        c = self._container(bucket)
        for k in keys:
            try:
                c.delete_blob(k)
            except Exception as e:  # noqa: BLE001 — idempotent like S3/GCS
                if "NotFound" not in type(e).__name__ and "404" not in str(e):
                    raise

    def _copy_obj(self, src_bucket: str, src_key: str,
                  dst_bucket: str, dst_key: str) -> None:
        import time

        src_url = self._container(src_bucket).get_blob_client(src_key).url
        dst = self._container(dst_bucket).get_blob_client(dst_key)
        dst.start_copy_from_url(src_url)
        # start_copy_from_url only INITIATES the copy (pending for large /
        # cross-account blobs); the PrefixObjectFS contract is synchronous
        # (callers delete the source right after a move) — poll to success
        deadline = time.time() + 300
        while time.time() < deadline:
            props = dst.get_blob_properties() if hasattr(
                dst, "get_blob_properties") else None
            status = getattr(getattr(props, "copy", None), "status", None) \
                if props is not None else None
            if status in (None, "success"):
                return
            if status in ("failed", "aborted"):
                raise RuntimeError(
                    f"abfss copy {src_key} -> {dst_key} {status}")
            time.sleep(0.5)
        raise TimeoutError(f"abfss copy {src_key} -> {dst_key} still pending")
