"""Segment creation: columnar data -> sealed segment directory.

Equivalent of the reference's two-pass ``SegmentIndexCreationDriverImpl``
(pinot-segment-local/.../creator/impl/SegmentIndexCreationDriverImpl.java:101
init / :196 build): pass 1 collects per-column stats (cardinality, min/max,
sortedness — creator/impl/stats/), pass 2 writes dictionaries, forward
indexes and auxiliary indexes (SegmentColumnarIndexCreator.java). Here both
passes are fused into vectorized numpy (``np.unique`` yields stats + dict +
encoded ids at once), and indexes are written as dense mmap-able npy arrays
instead of bit-packed buffers.

Encoding policy (TPU-first, diverging from the reference's
dictionary-everything default): STRING/JSON/BYTES and all dimension /
datetime columns are dict-encoded (device work stays in int32 id space);
metric columns are stored raw so SUM/AVG avoid a device-side gather.
``no_dictionary_columns`` forces RAW for numeric columns.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional, Sequence

import numpy as np

from pinot_tpu.common.datatypes import DataType, FieldRole
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.storage import partition as partition_mod
from pinot_tpu.storage.segment import (
    METADATA_FILE,
    ColumnMetadata,
    Encoding,
    ImmutableSegment,
    SegmentMetadata,
    build_zone_map,
    write_creation_meta,
)

import json


def _np_column(values, dtype: DataType) -> np.ndarray:
    """Coerce an ingested column to its canonical numpy representation.
    Columns already in canonical dtype pass through without a per-element
    copy (the conversion loop dominated segment build time at 10M+ rows)."""
    if dtype.is_string_like:
        arr = np.asarray(values) if not isinstance(values, np.ndarray) else values
        if dtype is DataType.BYTES:
            if arr.dtype.kind == "S":
                return arr
            return np.asarray(
                [v if isinstance(v, bytes) else bytes(v) for v in values],
                dtype=np.bytes_,
            )
        if arr.dtype.kind == "U":
            return arr
        return np.asarray([str(v) for v in values], dtype=np.str_)
    arr = np.asarray(values)
    if arr.dtype == dtype.np_dtype:
        return arr
    if arr.dtype == object:
        arr = np.asarray([dtype.convert(v) for v in values])
    return arr.astype(dtype.np_dtype)


class SegmentCreator:
    def __init__(
        self,
        schema: Schema,
        table_config: Optional[TableConfig] = None,
        segment_name: str = "segment_0",
    ):
        self.schema = schema
        self.table_config = table_config or TableConfig(table_name=schema.name)
        self.segment_name = segment_name

    def build(self, columns: Mapping[str, Sequence], out_dir: str,
              null_masks: Optional[Mapping[str, Sequence]] = None) -> str:
        """Build a sealed segment from column arrays; returns the segment dir.

        Null semantics (NullValueVectorReader analog): ``None`` entries are
        detected, replaced by the field's default null value in the forward
        index, and recorded in a per-column null vector
        (``<col>.nullvec.npy``). ``null_masks`` lets callers that already
        substituted defaults (the mutable-segment seal) pass explicit masks.
        """
        os.makedirs(out_dir, exist_ok=True)
        idx_cfg = self.table_config.indexing
        n_docs = None
        col_meta: dict[str, ColumnMetadata] = {}

        for name in self.schema.column_names():
            spec = self.schema.field(name)
            if name not in columns:
                raise KeyError(f"input data missing column {name!r}")
            raw_in = columns[name]

            detected = _detect_none(raw_in)
            null_mask = None if null_masks is None else null_masks.get(name)
            if detected is not None:
                raw_in = _substitute_nulls(raw_in, detected, spec)
                null_mask = detected if null_mask is None \
                    else (np.asarray(null_mask, dtype=bool) | detected)

            if not spec.single_value:
                # multi-value: flatten + offsets
                lens = np.fromiter((len(r) for r in raw_in), dtype=np.int64, count=len(raw_in))
                flat = [v for row in raw_in for v in row]
                raw = _np_column(flat, spec.data_type)
                mv_off = np.zeros(len(raw_in) + 1, dtype=np.int64)
                np.cumsum(lens, out=mv_off[1:])
            else:
                raw = _np_column(raw_in, spec.data_type)
                mv_off = None

            nd = len(raw_in)
            if n_docs is None:
                n_docs = nd
            elif nd != n_docs:
                raise ValueError(f"column {name} has {nd} rows, expected {n_docs}")

            use_dict = self._use_dictionary(spec, idx_cfg.no_dictionary_columns)
            meta = self._write_column(
                name, spec, raw, mv_off, out_dir, use_dict, idx_cfg, nd
            )
            if null_mask is not None and np.asarray(null_mask).any():
                np.save(os.path.join(out_dir, f"{name}.nullvec.npy"),
                        np.asarray(null_mask, dtype=bool), allow_pickle=False)
                meta.has_null_vector = True
            col_meta[name] = meta

        time_col = self.table_config.time_column
        start = end = None
        if time_col and time_col in col_meta:
            start = col_meta[time_col].min_value
            end = col_meta[time_col].max_value

        meta = SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_config.table_name,
            n_docs=int(n_docs or 0),
            columns=col_meta,
            time_column=time_col,
            start_time=start,
            end_time=end,
            crc=_segment_crc(out_dir),
        )
        with open(os.path.join(out_dir, METADATA_FILE), "w") as f:
            json.dump(meta.to_json(), f, indent=1, default=_json_default)
        write_creation_meta(out_dir)

        # star-tree build happens after the base segment is sealed, like the
        # reference (SegmentIndexCreationDriverImpl.java:290,316)
        if idx_cfg.star_tree_configs:
            from pinot_tpu.storage.startree import build_star_trees

            build_star_trees(ImmutableSegment(out_dir), idx_cfg.star_tree_configs)
        return out_dir

    @staticmethod
    def _use_dictionary(spec, no_dict_cols) -> bool:
        if spec.data_type.is_string_like:
            return True
        if spec.name in no_dict_cols:
            return False
        return spec.role is not FieldRole.METRIC

    def _write_column(self, name, spec, raw, mv_off, out_dir, use_dict, idx_cfg, n_docs):
        def p(fname):
            return os.path.join(out_dir, fname)

        total_entries = len(raw)
        is_sorted = bool(np.all(raw[1:] >= raw[:-1])) if total_entries > 1 else True
        if not spec.single_value:
            is_sorted = False

        if use_dict:
            from pinot_tpu.storage.dictionary import Dictionary

            dictionary, ids = Dictionary.build(raw)
            packed_bits = None
            if idx_cfg.enable_bit_packing and spec.single_value:
                from pinot_tpu import native

                bits = native.bits_needed(dictionary.cardinality)
                if bits <= 16:  # >=2x smaller than int32, else not worth it
                    native.pack(ids, bits).tofile(p(f"{name}.fwdpacked.bin"))
                    packed_bits = bits
            if packed_bits is None:
                np.save(p(f"{name}.fwd.npy"), ids, allow_pickle=False)
            # a rebuild into the same dir with packing toggled must not
            # leave another format behind (stale files skew the CRC and
            # ride every download)
            stale = [p(f"{name}.fwdz.bin"), p(f"{name}.fwdz.off.npy")]
            stale.append(p(f"{name}.fwd.npy") if packed_bits is not None
                         else p(f"{name}.fwdpacked.bin"))
            for path in stale:
                if os.path.exists(path):
                    os.unlink(path)
            dictionary.save(p(f"{name}.dict.npy"))
            cardinality = dictionary.cardinality
            if cardinality:
                minv, maxv = dictionary.get(0), dictionary.get(cardinality - 1)
            else:
                minv = maxv = None
            encoding = Encoding.DICT
            compression = None
            fwd_for_inv = ids
            dict_values = dictionary.values
        else:
            dict_values = None
            packed_bits = None
            codec_map = getattr(idx_cfg, "compression_codec", {}) or {}
            if (name in idx_cfg.compressed_columns or name in codec_map) \
                    and spec.single_value:
                from pinot_tpu import native

                codec = codec_map.get(name, "zlib")
                blob, offs = native.compress_chunks(raw, codec=codec)
                blob.tofile(p(f"{name}.fwdz.bin"))
                np.save(p(f"{name}.fwdz.off.npy"), offs, allow_pickle=False)
                compression = codec
            else:
                np.save(p(f"{name}.fwd.npy"), raw, allow_pickle=False)
                compression = None
            # rebuilds with a different encoding config must not leave the
            # other format behind (stale files skew the CRC)
            stale = [p(f"{name}.fwdpacked.bin")]
            stale += [p(f"{name}.fwd.npy")] if compression else \
                [p(f"{name}.fwdz.bin"), p(f"{name}.fwdz.off.npy")]
            for path in stale:
                if os.path.exists(path):
                    os.unlink(path)
            cardinality = int(len(np.unique(raw)))
            minv, maxv = (raw.min(), raw.max()) if len(raw) else (None, None)
            encoding = Encoding.RAW
            fwd_for_inv = None

        if mv_off is not None:
            np.save(p(f"{name}.mvoff.npy"), mv_off, allow_pickle=False)

        if spec.single_value:
            # per-block zone map over the forward index (local dict ids for
            # DICT, raw values for RAW): the device block-skip path's prune
            # basis (ops/blockskip.py). Local ids remap to the batch's
            # global id space monotonically (both dictionaries are sorted),
            # so min/max survive the remap — engine/params.py reads this
            # file instead of re-scanning the column at batch build.
            zm_src = fwd_for_inv if use_dict else raw
            np.save(p(f"{name}.zmap.npy"), build_zone_map(zm_src),
                    allow_pickle=False)
        elif os.path.exists(p(f"{name}.zmap.npy")):
            os.unlink(p(f"{name}.zmap.npy"))  # SV→MV rebuild: stale zone map

        has_inverted = False
        if name in idx_cfg.inverted_index_columns and fwd_for_inv is not None:
            self._write_inverted(name, fwd_for_inv, cardinality, mv_off, out_dir)
            has_inverted = True

        has_bloom = False
        if name in idx_cfg.bloom_filter_columns:
            from pinot_tpu.storage.bloom import build_bloom

            build_bloom(raw if dict_values is None else None, dict_values, p(f"{name}.bloom.npy"))
            has_bloom = True

        has_json_index = False
        if name in idx_cfg.json_index_columns:
            if not (spec.single_value and spec.data_type.is_string_like):
                raise ValueError(
                    f"json index requires a single-value STRING/JSON column, "
                    f"got {name}")
            from pinot_tpu.storage.jsonindex import build_json_index

            build_json_index(raw, p(f"{name}.jsonidx"))
            has_json_index = True

        has_text_index = False
        if name in idx_cfg.text_index_columns:
            if not (spec.single_value and spec.data_type.is_string_like):
                raise ValueError(
                    f"text index requires a single-value STRING column, "
                    f"got {name}")
            from pinot_tpu.storage.textindex import build_text_index

            build_text_index(raw, p(f"{name}.textidx"))
            has_text_index = True

        has_fst_index = False
        if name in getattr(idx_cfg, "fst_index_columns", ()):
            if encoding != Encoding.DICT or dict_values is None:
                raise ValueError(
                    f"fst index requires a dictionary column, got {name}")
            from pinot_tpu.storage.fstindex import TrigramIndex

            TrigramIndex.build(dict_values).save(out_dir, name)
            has_fst_index = True

        has_h3_index = False
        if name in getattr(idx_cfg, "h3_index_columns", ()):
            if not (spec.single_value and spec.data_type.is_string_like):
                raise ValueError(
                    f"geo (h3-role) index requires a single-value STRING "
                    f"POINT column, got {name}")
            from pinot_tpu.storage.geoindex import GeoGridIndex

            GeoGridIndex.build(raw).save(out_dir, name)
            has_h3_index = True

        # Range acceleration: DICT columns get it for free — the sorted
        # dictionary maps a value range to a dict-id interval. RAW SV
        # columns get a sorted-projection range index (RangeIndexCreator /
        # BitSlicedRangeIndexReader analog): doc ids in value order plus the
        # sorted values, so a range is two binary searches + a doc-id slice.
        has_range = name in idx_cfg.range_index_columns and encoding == Encoding.DICT
        if name in idx_cfg.range_index_columns and encoding == Encoding.RAW \
                and spec.single_value:
            order = np.argsort(raw, kind="stable").astype(np.int32)
            np.save(p(f"{name}.range.docs.npy"), order, allow_pickle=False)
            np.save(p(f"{name}.range.vals.npy"), raw[order], allow_pickle=False)
            has_range = True

        part_fn = part_n = parts = None
        pmap = self.table_config.partition.column_partition_map
        if name in pmap:
            fn, n_part = pmap[name]
            vals = raw if dict_values is None else dict_values
            pids = partition_mod.partition_ids(np.asarray(vals), fn, n_part)
            part_fn, part_n, parts = fn, n_part, sorted(set(int(x) for x in np.unique(pids)))

        return ColumnMetadata(
            name=name,
            data_type=spec.data_type,
            encoding=encoding,
            cardinality=int(cardinality),
            min_value=_scalar(minv),
            max_value=_scalar(maxv),
            is_sorted=is_sorted,
            single_value=spec.single_value,
            max_mv_entries=int(np.max(np.diff(mv_off))) if mv_off is not None and len(mv_off) > 1 else 1,
            has_dictionary=use_dict,
            has_inverted=has_inverted,
            has_range=has_range,
            has_bloom=has_bloom,
            has_json_index=has_json_index,
            has_text_index=has_text_index,
            has_fst_index=has_fst_index,
            has_h3_index=has_h3_index,
            packed_bits=packed_bits,
            compression=compression,
            total_number_of_entries=int(total_entries),
            partition_function=part_fn,
            num_partitions=part_n,
            partitions=parts,
        )

    @staticmethod
    def _write_inverted(name, ids, cardinality, mv_off, out_dir):
        """Inverted index: per-dict-id sorted doc lists, concatenated.

        Dense equivalent of one RoaringBitmap per dict id
        (OffHeapBitmapInvertedIndexCreator.java). ``argsort(kind='stable')``
        groups doc ids by dict id while preserving doc order within a group.
        """
        if mv_off is not None:
            # map each flattened entry back to its doc id
            doc_of_entry = np.repeat(
                np.arange(len(mv_off) - 1, dtype=np.int64), np.diff(mv_off)
            )
            order = np.argsort(ids, kind="stable")
            docs = doc_of_entry[order].astype(np.int32)
            counts = np.bincount(ids, minlength=cardinality)
        else:
            order = np.argsort(ids, kind="stable").astype(np.int32)
            docs = order
            counts = np.bincount(ids, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        np.save(os.path.join(out_dir, f"{name}.inv.docs.npy"), docs, allow_pickle=False)
        np.save(os.path.join(out_dir, f"{name}.inv.off.npy"), offsets, allow_pickle=False)


def _segment_crc(out_dir: str) -> str:
    """Content fingerprint over the segment's index files (the reference's
    segment CRC role: refresh-push detection, download validation). Hashes
    every file's name + size + first/last 1MB — full-content hashing of
    multi-GB forward indexes would tax large builds for a fingerprint whose
    job is change detection, not bit-rot integrity."""
    import zlib

    h = 0
    for fname in sorted(os.listdir(out_dir)):
        path = os.path.join(out_dir, fname)
        if fname == METADATA_FILE or not os.path.isfile(path):
            continue
        size = os.path.getsize(path)
        h = zlib.crc32(f"{fname}:{size};".encode(), h)
        with open(path, "rb") as f:
            h = zlib.crc32(f.read(1 << 20), h)
            if size > (2 << 20):
                f.seek(-(1 << 20), os.SEEK_END)
                h = zlib.crc32(f.read(), h)
    return format(h, "08x")


def _detect_none(raw_in) -> Optional[np.ndarray]:
    """Per-doc ``is None`` mask, or None when no entry can be null (typed
    numpy input) or none is. MV rows count as null when the ROW is None."""
    if isinstance(raw_in, np.ndarray) and raw_in.dtype != object:
        return None
    mask = np.fromiter((v is None for v in raw_in), dtype=bool,
                       count=len(raw_in))
    return mask if mask.any() else None


def _substitute_nulls(raw_in, mask: np.ndarray, spec) -> list:
    """Replace null entries with the field's default null value
    (FieldSpec.getDefaultNullValue), empty list for MV rows."""
    filler = [] if not spec.single_value else spec.null_value()
    return [filler if is_null else v for v, is_null in zip(raw_in, mask)]


def _scalar(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _json_default(o):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, bytes):
        return o.hex()
    raise TypeError(f"not JSON serializable: {type(o)}")


def build_segment(
    schema: Schema,
    columns: Mapping[str, Sequence],
    out_dir: str,
    table_config: Optional[TableConfig] = None,
    segment_name: str = "segment_0",
    null_masks: Optional[Mapping[str, Sequence]] = None,
) -> ImmutableSegment:
    SegmentCreator(schema, table_config, segment_name).build(
        columns, out_dir, null_masks=null_masks
    )
    return ImmutableSegment(out_dir)
