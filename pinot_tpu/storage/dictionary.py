"""Sorted value dictionaries.

Equivalent to the reference's immutable sorted dictionaries
(pinot-segment-local/.../readers/{Int,Long,Float,Double,String,Bytes}Dictionary.java):
values are stored sorted; ids are ranks; lookup is binary search. Vectorized
with numpy instead of per-call binary search — predicate evaluation resolves
whole literal sets at once, and range predicates become two ``searchsorted``
calls returning a dict-id interval (the trick behind the reference's
dictionary-based predicate evaluators,
pinot-core/.../operator/filter/predicate/).
"""

from __future__ import annotations

import numpy as np


class Dictionary:
    """Immutable sorted dictionary: id <-> value, id order == sort order."""

    def __init__(self, values: np.ndarray):
        # `values` must be sorted ascending and unique.
        self._values = values

    # ---- construction ---------------------------------------------------
    @classmethod
    def build(cls, raw: np.ndarray) -> tuple["Dictionary", np.ndarray]:
        """Build from a raw value column; returns (dictionary, dict_ids[int32]).

        One-pass equivalent of the reference's stats-collector + dictionary
        creator (SegmentDictionaryCreator).
        """
        values, inverse = np.unique(raw, return_inverse=True)
        return cls(values), inverse.astype(np.int32)

    # ---- accessors ------------------------------------------------------
    @property
    def values(self) -> np.ndarray:
        return self._values

    def __len__(self) -> int:
        return len(self._values)

    @property
    def cardinality(self) -> int:
        return len(self._values)

    def get(self, dict_id: int):
        return self._values[dict_id]

    def take(self, dict_ids: np.ndarray) -> np.ndarray:
        """Vectorized id -> value (result materialization path)."""
        return self._values[dict_ids]

    # ---- predicate resolution (value -> id space) -----------------------
    def index_of(self, value) -> int:
        """Exact id of value, or -1 (reference: Dictionary.indexOf)."""
        i = int(np.searchsorted(self._values, value))
        if i < len(self._values) and self._values[i] == value:
            return i
        return -1

    def ids_of(self, values) -> np.ndarray:
        """Ids of the values present in the dictionary (for IN/EQ predicates).

        Values not representable in the dictionary's dtype (longer strings,
        non-integral floats against an int dictionary) are dropped, never
        truncated into false matches.
        """
        if len(self._values) == 0 or len(values) == 0:
            return np.empty(0, dtype=np.int32)
        vals = np.asarray(values)
        kind = self._values.dtype.kind
        if kind in ("U", "S"):
            vals = vals.astype(kind)  # natural width for the queried values
            if vals.dtype.itemsize > self._values.dtype.itemsize:
                unit = 4 if kind == "U" else 1
                max_len = self._values.dtype.itemsize // unit
                vals = vals[np.char.str_len(vals) <= max_len]
                if len(vals) == 0:
                    return np.empty(0, dtype=np.int32)
            cast = vals.astype(self._values.dtype)
        else:
            cast = vals.astype(self._values.dtype)
            exact = cast.astype(np.float64) == vals.astype(np.float64)
            cast = cast[exact]
            if len(cast) == 0:
                return np.empty(0, dtype=np.int32)
        idx = np.searchsorted(self._values, cast)
        idx_clipped = np.minimum(idx, len(self._values) - 1)
        hit = self._values[idx_clipped] == cast
        return idx_clipped[hit].astype(np.int32)

    def range_ids(self, lower, upper, lower_inclusive=True, upper_inclusive=True) -> tuple[int, int]:
        """Dict-id half-open interval [lo, hi) matching a value range.

        Mirrors RangePredicateEvaluatorFactory's dictionary-based evaluator:
        a value range on a sorted dictionary is a contiguous id range.
        """
        if lower is None:
            lo = 0
        else:
            side = "left" if lower_inclusive else "right"
            lo = int(np.searchsorted(self._values, lower, side=side))
        if upper is None:
            hi = len(self._values)
        else:
            side = "right" if upper_inclusive else "left"
            hi = int(np.searchsorted(self._values, upper, side=side))
        return lo, max(lo, hi)

    # ---- persistence ----------------------------------------------------
    def save(self, path: str) -> None:
        np.save(path, self._values, allow_pickle=False)

    @classmethod
    def load(cls, path: str, mmap: bool = True) -> "Dictionary":
        arr = np.load(path, mmap_mode="r" if mmap else None, allow_pickle=False)
        return cls(arr)
