"""SQLAlchemy dialect over the DB-API client — the second client surface
(pinot-clients/pinot-jdbc-client role: the JDBC driver is a standards
surface wrapped around the java client; a SQLAlchemy dialect is the
pythonic equivalent wrapped around the DB-API module).

Gated on ``sqlalchemy`` (not in the build image): importing this module is
safe; constructing the dialect without sqlalchemy raises a clear error.

Usage:

    from pinot_tpu.client.sqlalchemy_dialect import register_dialect
    register_dialect()
    engine = sqlalchemy.create_engine("pinot://localhost:8099")
    pd.read_sql("SELECT ... FROM tbl", engine)

URL: ``pinot://host:port`` → the broker's HTTP endpoint.
"""

from __future__ import annotations

TYPE_MAP = {
    # Pinot column data types → sqlalchemy type FACTORY NAMES; resolved
    # lazily so this module imports without sqlalchemy present
    "INT": "INTEGER",
    "LONG": "BIGINT",
    "FLOAT": "FLOAT",
    "DOUBLE": "FLOAT",
    "STRING": "VARCHAR",
    "BOOLEAN": "BOOLEAN",
    "TIMESTAMP": "TIMESTAMP",
    "BYTES": "LargeBinary",
    "JSON": "JSON",
    "BIG_DECIMAL": "Numeric",
}


def _sqlalchemy():
    try:
        import sqlalchemy

        return sqlalchemy
    except ImportError as e:  # pragma: no cover — exercised via fake module
        raise RuntimeError(
            "the pinot:// SQLAlchemy dialect needs the sqlalchemy package; "
            "use the DB-API client (pinot_tpu.client.connect) directly "
            "otherwise") from e


def _resolve_type(sa, name: str):
    return getattr(sa.types, TYPE_MAP.get(name, "VARCHAR"), sa.types.VARCHAR)


def make_dialect_class():
    """Build the dialect class against the installed sqlalchemy (deferred
    base-class resolution keeps the module importable without it)."""
    sa = _sqlalchemy()
    from sqlalchemy.engine import default

    class PinotDialect(default.DefaultDialect):
        name = "pinot"
        driver = "pinot_tpu"
        paramstyle = "qmark"
        supports_statement_cache = True
        supports_native_boolean = True
        supports_sane_rowcount = False
        supports_multivalues_insert = False
        postfetch_lastrowid = False

        @classmethod
        def import_dbapi(cls):
            import pinot_tpu.client as dbapi

            return dbapi

        # SQLAlchemy <2 spelling
        @classmethod
        def dbapi(cls):
            return cls.import_dbapi()

        def create_connect_args(self, url):
            host = url.host or "localhost"
            port = url.port or 8099
            return [f"http://{host}:{port}"], {}

        def do_ping(self, dbapi_connection) -> bool:
            # SHOW TABLES is the cheapest broker round trip: a live broker
            # answers it; a dead connection raises → False so the pool
            # invalidates and reconnects (the one job pre-ping has)
            try:
                cur = dbapi_connection.cursor()
                cur.execute("SHOW TABLES")
                return True
            except Exception:  # noqa: BLE001 — transport failure
                return False

        def has_table(self, connection, table_name, schema=None, **kw):
            return table_name in self.get_table_names(connection, schema)

        def get_table_names(self, connection, schema=None, **kw):
            from pinot_tpu.client import DatabaseError

            cur = connection.connection.cursor()
            try:
                cur.execute("SHOW TABLES")
                return [r[0] for r in cur.fetchall()]
            except DatabaseError:
                # in-band broker error (a broker without the catalog op):
                # empty catalog. Transport failures PROPAGATE — a down
                # broker must not reflect as an empty database.
                return []

        def get_columns(self, connection, table_name, schema=None, **kw):
            """Column metadata from a LIMIT 0 probe: the DataTable schema
            carries names + Pinot types, which is what the JDBC driver's
            ResultSetMetaData exposes too."""
            cur = connection.connection.cursor()
            cur.execute(f"SELECT * FROM {table_name} LIMIT 0")
            out = []
            for (name, type_code, *_rest) in cur.description or []:
                out.append({
                    "name": name,
                    "type": _resolve_type(sa, str(type_code))(),
                    "nullable": True,
                    "default": None,
                })
            return out

        def get_pk_constraint(self, connection, table_name, schema=None, **kw):
            return {"constrained_columns": [], "name": None}

        def get_foreign_keys(self, connection, table_name, schema=None, **kw):
            return []

        def get_indexes(self, connection, table_name, schema=None, **kw):
            return []

        def get_schema_names(self, connection, **kw):
            return ["default"]

        def get_view_names(self, connection, schema=None, **kw):
            return []

    return PinotDialect


def register_dialect() -> None:
    """Register ``pinot://`` with sqlalchemy's dialect registry."""
    sa = _sqlalchemy()
    cls = make_dialect_class()
    sa.dialects.registry.register(
        "pinot", "pinot_tpu.client.sqlalchemy_dialect", "dialect")
    # module attribute the registry entrypoint resolves
    globals()["dialect"] = cls
    return cls
