"""Python client: DB-API-flavored access to a broker.

Equivalent of the reference's client libraries (pinot-clients/
pinot-java-client's Connection/ResultSetGroup and the external pinotdb
driver): ``connect()`` to a broker HTTP endpoint (or wrap an in-process
Broker / registry for embedded use), cursors with ``execute`` /
``fetch*`` / ``description`` / ``rowcount``, and broker response stats
on the cursor. Read-only by design — DML raises, like the reference.

    from pinot_tpu.client import connect
    conn = connect("http://localhost:8099")
    cur = conn.cursor()
    cur.execute("SELECT city, COUNT(*) FROM t GROUP BY city")
    for row in cur:
        ...
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

apilevel = "2.0"
threadsafety = 1
paramstyle = "qmark"


class Error(Exception):
    """DB-API base error."""


class DatabaseError(Error):
    """Query-level failure reported by the cluster."""


class ProgrammingError(Error):
    """Client misuse (closed cursor, fetch before execute...)."""


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: Optional[list] = None
        self._pos = 0
        self.description = None
        self.rowcount = -1
        self.stats: dict = {}
        self._closed = False

    # ---- DB-API surface -------------------------------------------------
    def execute(self, sql: str, params=None) -> "Cursor":
        if self._closed:
            raise ProgrammingError("cursor is closed")
        if params is not None:
            # qmark substitution with conservative literal quoting;
            # ? inside single-quoted literals is not a placeholder
            parts = _split_placeholders(sql)
            if len(parts) != len(params) + 1:
                raise ProgrammingError(
                    f"query has {len(parts) - 1} placeholders, "
                    f"{len(params)} params given")
            out = []
            for i, p in enumerate(parts):
                out.append(p)
                if i < len(params):
                    out.append(_quote(params[i]))
            sql = "".join(out)
        resp = self._conn._execute(sql)
        if resp.get("exceptions"):
            raise DatabaseError(resp["exceptions"])
        rt = resp.get("resultTable") or {"dataSchema": {"columnNames": [],
                                                        "columnDataTypes": []},
                                         "rows": []}
        names = rt["dataSchema"]["columnNames"]
        types = rt["dataSchema"]["columnDataTypes"]
        self.description = [(n, t, None, None, None, None, None)
                            for n, t in zip(names, types)]
        self._rows = [tuple(r) for r in rt["rows"]]
        self._pos = 0
        self.rowcount = len(self._rows)
        self.stats = {k: v for k, v in resp.items()
                      if k not in ("resultTable", "exceptions")}
        return self

    def _require_rows(self) -> list:
        if self._closed:
            raise ProgrammingError("cursor is closed")
        if self._rows is None:
            raise ProgrammingError("fetch before execute")
        return self._rows

    def fetchone(self):
        rows = self._require_rows()
        if self._pos >= len(rows):
            return None
        row = rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> list:
        rows = self._require_rows()
        if size is None:
            size = self.arraysize
        out = rows[self._pos: self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> list:
        rows = self._require_rows()
        out = rows[self._pos:]
        self._pos = len(rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self) -> None:
        self._closed = True
        self._rows = None


def _split_placeholders(sql: str) -> list:
    """Split on ? placeholders, ignoring ?s inside single-quoted strings
    AND double-quoted identifiers."""
    parts, cur = [], []
    in_sq = in_dq = False
    for ch in sql:
        if ch == "'" and not in_dq:
            in_sq = not in_sq
            cur.append(ch)
        elif ch == '"' and not in_sq:
            in_dq = not in_dq
            cur.append(ch)
        elif ch == "?" and not in_sq and not in_dq:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _quote(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v).replace("'", "''")
    return f"'{s}'"


class Connection:
    def __init__(self, broker_url: Optional[str] = None, broker=None,
                 registry=None, timeout_s: float = 30.0, auth=None,
                 ssl_context=None):
        """``auth``: optional (username, password) for brokers running
        with HTTP Basic auth. ``ssl_context``: optional ssl.SSLContext for
        https:// broker URLs (e.g. TlsConfig.client_ssl_context() to trust
        a private CA)."""
        self._ssl_context = ssl_context
        if broker_url is None and broker is None and registry is None:
            raise ProgrammingError(
                "connect() needs a broker_url, a Broker, or a registry")
        self._url = broker_url.rstrip("/") if broker_url else None
        self._auth_header = None
        if auth is not None:
            import base64

            cred = base64.b64encode(
                f"{auth[0]}:{auth[1]}".encode("utf-8")).decode("ascii")
            self._auth_header = f"Basic {cred}"
        self._broker = broker
        self._owns_broker = False
        if self._broker is None and registry is not None:
            from pinot_tpu.broker.broker import Broker

            self._broker = Broker(registry, timeout_s=timeout_s)
            self._owns_broker = True
        self._timeout_s = timeout_s
        self._closed = False

    # over-quota (429) handling: one bounded retry after Retry-After —
    # a per-table QPS quota is a *pacing* signal, not a hard failure;
    # the sleep is capped so a hostile/buggy header can't hang a client
    MAX_RETRY_AFTER_S = 5.0

    @staticmethod
    def _retry_after_s(value) -> float:
        try:
            return max(0.05, min(float(value), Connection.MAX_RETRY_AFTER_S))
        except (TypeError, ValueError):
            return 0.5

    @staticmethod
    def _is_quota_rejection(resp: dict) -> bool:
        excs = resp.get("exceptions") or []
        return bool(excs) and all(x.get("errorCode") == 429 for x in excs)

    def _execute(self, sql: str) -> dict:
        if self._closed:
            raise ProgrammingError("connection is closed")
        if self._broker is not None:
            resp = self._broker.execute(sql)
            if self._is_quota_rejection(resp):
                # in-process brokers ship the 429 in-band; honor the
                # response's own hint when present, then retry ONCE
                import time

                time.sleep(self._retry_after_s(
                    resp.get("retryAfterSeconds", 0.5)))
                resp = self._broker.execute(sql)
            return resp
        return self._execute_http(sql, retry_quota=True)

    def _execute_http(self, sql: str, retry_quota: bool) -> dict:
        headers = {"Content-Type": "application/json"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        req = urllib.request.Request(
            self._url + "/query/sql",
            data=json.dumps({"sql": sql}).encode("utf-8"),
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout_s,
                                        context=self._ssl_context) as resp:
                return json.loads(resp.read())
        except Error:
            raise
        except urllib.error.HTTPError as e:
            if e.code == 401:
                raise DatabaseError(
                    "authentication failed (HTTP 401): check the "
                    "connection's auth=(user, password)") from e
            if e.code == 429 and retry_quota:
                # over-quota: back off for the broker's Retry-After
                # (bounded) and retry once before surfacing the error
                import time

                time.sleep(self._retry_after_s(
                    e.headers.get("Retry-After") if e.headers else None))
                return self._execute_http(sql, retry_quota=False)
            raise DatabaseError(f"broker returned HTTP {e.code}") from e
        except Exception as e:  # noqa: BLE001 — transport failure
            raise DatabaseError(f"broker unreachable: {e}") from e

    def cursor(self) -> Cursor:
        if self._closed:
            raise ProgrammingError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True
        if self._owns_broker and self._broker is not None:
            self._broker.close()

    def commit(self) -> None:
        pass  # read-only: DB-API requires the method to exist

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def connect(broker_url: Optional[str] = None, **kwargs) -> Connection:
    return Connection(broker_url, **kwargs)
